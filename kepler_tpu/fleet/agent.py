"""Fleet agent: streams per-window feature rows to the cluster aggregator.

The node-side half of the DCN plane (SURVEY §5 "distributed communication
backend"): subscribes to the monitor's raw window samples, serializes them
(``fleet.wire``), and POSTs to the aggregator's ``/v1/report``. The node's
own Prometheus exporter is untouched — the aggregator is an *additional*
consumer, exactly as Prometheus scrape is in the reference.

Failure model (reference degrade-gracefully stance, hardened): an
unreachable aggregator never blocks or kills the node monitor. Samples
queue in a small ring (newest wins); the send path reuses one persistent
connection, retries with exponential backoff + jitter, and a circuit
breaker sheds sends entirely while open so a dead aggregator costs the
node one failed probe per cooldown instead of a connect timeout per
window. Breaker state is surfaced through :meth:`health` for the API
server's ``/healthz``. Fault-injection points (``kepler_tpu.fault``) cover
the whole path: connect refusal, slow sends, body corruption, clock skew.

Durability (ISSUE 3): with a ``fleet.spool.Spool`` attached, every window
is appended to the crash-safe on-disk spool before any send attempt and
acknowledged only on a 2xx (or permanent 4xx), so agent crashes and
outages longer than the ring replay the backlog instead of losing it —
replayed records keep their original ``run``+``seq`` identity; only the
transmit-time header fields (``sent_at``, the delivery-latency
``delivery_path``/``appended_at``) are restamped at send. The
breaker/backoff machinery stays the sole send gate in both modes.

Self-telemetry (ISSUE 4): the emit→spool-append→drain→send legs carry
``telemetry.span`` instrumentation, and every window opens a delivery
trace (``trace`` id + ``emitted_at`` in the wire header) that the
aggregator closes at merge into
``kepler_fleet_delivery_latency_seconds{path="fresh"|"replay"}``.

HA ingest tier (ISSUE 11): with ``peers`` set (the replicas'
``aggregator.peers`` list), the agent learns the consistent-hash ring
LAZILY — it dials any peer, follows the structured ``421 + owner +
epoch`` redirect to the replica that owns its ``node_name``, and
re-resolves when a response advertises a higher membership epoch. A
replica outage falls back to the machinery above unchanged (backoff,
breaker, spool), with one addition: each consecutive failure rotates to
the next peer, so the first live replica answers with ownership truth
(a 2xx or a redirect). On an owner CHANGE the hand-off is hot: the
agent rewinds its spool tail (``handoff_replay`` records) so the new
owner rebuilds the node's recent state from real records — replicas
that already ingested them absorb the overlap through the ``(run,
seq)`` dedup window, and the ``acked_through`` watermark stamped at
transmit keeps the new owner's gap detection from fabricating loss for
windows the OLD owner acknowledged.
"""

from __future__ import annotations

# keplint: monotonic-only — backoff/breaker/rate-limit math must survive
# NTP steps; wall time only via the injected clock seam (sent_at).

import base64
import collections
import http.client
import json
import logging
import math
import random
import socket
import ssl
import threading
import time as _time
import urllib.parse
import uuid
from typing import Any, Callable, Sequence

from kepler_tpu import fault, telemetry
from kepler_tpu.fleet import journal
from kepler_tpu.fleet.delivery import keyframe_wanted
from kepler_tpu.fleet.ring import coerce_epoch, sanitize_peer
from kepler_tpu.fleet.spool import Spool, SpoolRecord
from kepler_tpu.fleet.wire import (WireError, WireLayoutV2,
                                   encode_delta_v2, encode_report,
                                   encode_report_batch,
                                   encode_report_v2, peek_identity,
                                   restamp_transmit, transcode_to_v1)
from kepler_tpu.monitor.monitor import PowerMonitor, WindowSample
from kepler_tpu.parallel.fleet import MODE_RATIO, NodeReport
from kepler_tpu.service.lifecycle import CancelContext, backoff_with_jitter

log = logging.getLogger("kepler.fleet.agent")

# circuit-breaker states (health()["breaker"])
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half-open"


class AggregatorRejectedError(http.client.HTTPException):
    """4xx from the aggregator: the delivery path is HEALTHY, this payload
    is permanently rejected (skew, auth, size, malformed). Retrying would
    fail forever and tripping the breaker would shed GOOD reports from an
    aggregator that is demonstrably up — so the drain loop drops the
    sample instead."""

    def __init__(self, status: int) -> None:
        super().__init__(f"aggregator rejected report: {status}")
        self.status = status


class UnsendableRecordError(Exception):
    """A (spooled) record that cannot even be serialized for transmit
    (restamp failed: format drift across an upgrade, CRC-missed
    corruption). Dropped WITHOUT touching the circuit breaker — no
    network contact happened, so it is evidence of nothing."""


class OwnerRedirectError(Exception):
    """421 from a replica that does not own this node: a structured
    redirect naming the owning peer + the ring membership epoch. NOT a
    rejection (the payload is fine) and NOT an outage (the tier
    answered) — the drain loop follows it to the owner and retries the
    SAME window there."""

    def __init__(self, owner: str | None, epoch: int | None) -> None:
        super().__init__(
            f"report redirected to owner {owner!r} (epoch {epoch})")
        self.owner = owner
        self.epoch = epoch


class ThrottledError(Exception):
    """429 from the aggregator: a THROTTLE, not a failure. The tier is
    alive and over its admission budget; the record is safe (spooled or
    still in hand) and will be accepted later — so a 429 must never
    feed the circuit breaker, trip peer rotation, count as a send
    failure, or move the ``_disrupted_at`` replay watermark. The drain
    loop just waits out the (coerced, jittered) Retry-After."""

    def __init__(self, retry_after: float) -> None:
        super().__init__(f"aggregator shedding load "
                         f"(retry after {retry_after:g}s)")
        self.retry_after = retry_after


class _BatchUnsupportedError(Exception):
    """The batch endpoint is not usable against this target (an older
    replica's 404/405, or a 400 for an envelope it cannot parse):
    remember that and fall back to single-record sends — never an
    outage signal, never a reason to drop records."""


class NeedsKeyframeError(Exception):
    """Structured 409 from the aggregator to a wire-v2 DELTA frame: it
    holds no matching base for this node (fresh owner after a hand-off,
    evicted base row, run change). Treated like a 421 — the tier is
    alive and the payload is fine, so the drain loop resends the SAME
    window as a full keyframe: never a failure, never breaker food."""


class _WireDowngradeError(Exception):
    """A 415/400 answered to a v2-encoded frame: an old replica that
    cannot speak wire v2. The target is remembered as v1-only for
    ``wire_degraded_ttl`` (the PR 12 batch 404/405 downgrade, wire-
    shaped) and the SAME record retries as v1 — nothing dropped,
    nothing counted as an outage."""


# backoff used when a 429 carries no usable Retry-After (absent,
# non-numeric, negative, bool) — an adversarial owner must not be able
# to park an agent, so hostile values coerce HERE, not at honor time
DEFAULT_RETRY_AFTER = 1.0

# byte budget for one batched-drain request: well under the server's
# 64 MiB report-body cap, with headroom for the restamp's header growth
# and the envelope framing. Without this bound a backlog of large
# reports could build a body the server 413s FOREVER — the same batch
# re-peeked every round, the drain wedged.
MAX_BATCH_BYTES = 32 << 20


def coerce_retry_after(raw: object, default: float = DEFAULT_RETRY_AFTER,
                       cap: float = 300.0) -> float:
    """Harden a wire Retry-After (header string or batch-response JSON
    number): non-numeric/negative/bool/non-finite values fall back to
    ``default``; everything is clamped to ``cap`` so a hostile replica
    cannot park an agent forever. Mirrors the run/seq and ring-header
    coercion discipline (PR 3 / PR 11)."""
    cap = max(0.0, cap)
    if isinstance(raw, bool):
        return min(default, cap)
    if isinstance(raw, (int, float)):
        val = float(raw)
    elif isinstance(raw, str):
        try:
            val = float(raw.strip())
        except ValueError:
            return min(default, cap)
    else:
        return min(default, cap)
    if not math.isfinite(val) or val < 0.0:
        return min(default, cap)
    return min(val, cap)


class _TokenBucket:
    """Replay pacer: at most ``rate`` records/s with a burst of
    ``burst`` — a rejoining agent slews its spool backlog in instead of
    dumping it on a replica that just absorbed a herd. Monotonic-clock
    only (injected seam); single-threaded (the drain loop owns it)."""

    __slots__ = ("_rate", "_burst", "_tokens", "_last", "_monotonic")

    def __init__(self, rate: float, burst: int,
                 monotonic: Callable[[], float]) -> None:
        self._rate = max(1e-6, float(rate))
        self._burst = max(1, int(burst))
        self._tokens = float(self._burst)
        self._monotonic = monotonic
        self._last = monotonic()

    def take(self, want: int) -> tuple[int, float]:
        """→ ``(granted, wait_s)``: up to ``want`` tokens now, or
        ``(0, seconds until one accrues)``."""
        now = self._monotonic()
        self._tokens = min(float(self._burst),
                           self._tokens + (now - self._last) * self._rate)
        self._last = now
        if self._tokens < 1.0:
            return 0, (1.0 - self._tokens) / self._rate
        granted = min(max(1, want), int(self._tokens))
        self._tokens -= granted
        return granted, 0.0


def _parse_redirect(data: bytes, headers) -> tuple[str | None, int | None]:
    """(owner, epoch) from a 421 response — body JSON first, the
    ``X-Kepler-Owner``/``X-Kepler-Epoch`` headers as fallback. Both
    values arrive from the network and are laundered through the ring's
    sanitizers; an unusable redirect returns ``(None, None)`` and is
    handled as a failed send, never followed blindly."""
    owner: object = None
    epoch: object = None
    try:
        payload = json.loads(data)
        if isinstance(payload, dict):
            owner = payload.get("owner")
            epoch = payload.get("epoch")
    except (ValueError, UnicodeDecodeError):
        pass
    if owner is None:
        owner = headers.get("X-Kepler-Owner")
    if epoch is None:
        epoch = _epoch_from_header(headers.get("X-Kepler-Epoch"))
    return sanitize_peer(owner), coerce_epoch(epoch)


def _epoch_from_header(raw: str | None) -> int | None:
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


class _PeerTarget:
    """One dialable ingest replica (parsed once, switched cheaply).

    ``display`` is the credential-stripped identity (no URL userinfo):
    it is what leaves the process — health payloads, log lines, and the
    ``owner`` wire header — so an endpoint of the documented
    ``https://user:pw@agg:28283`` form never leaks its password."""

    __slots__ = ("url", "display", "host", "port", "path", "batch_path",
                 "tls", "auth_header", "tls_ctx")

    def __init__(self, url: str, display: str, host: str, port: int,
                 path: str, tls: bool, auth_header: str, tls_ctx) -> None:
        self.url = url
        self.display = display
        self.host = host
        self.port = port
        self.path = path
        self.batch_path = path + "s"  # /v1/report → /v1/reports
        self.tls = tls
        self.auth_header = auth_header
        self.tls_ctx = tls_ctx


def _parse_target(endpoint: str, tls_skip_verify: bool) -> _PeerTarget:
    u = urllib.parse.urlsplit(endpoint if "//" in endpoint
                              else f"http://{endpoint}")
    if not u.hostname or not u.port:
        raise ValueError(
            f"aggregator endpoint needs host:port, got {endpoint!r}")
    tls = u.scheme == "https"
    auth_header = ""
    if u.username is not None:
        creds = f"{urllib.parse.unquote(u.username)}:" \
                f"{urllib.parse.unquote(u.password or '')}"
        auth_header = "Basic " + base64.b64encode(creds.encode()).decode()
        if not tls:
            log.warning(
                "aggregator endpoint has basic-auth credentials but no "
                "https:// scheme — the Authorization header will go over "
                "the wire in cleartext")
    tls_ctx = None
    if tls:
        tls_ctx = ssl.create_default_context()
        if tls_skip_verify:
            tls_ctx.check_hostname = False
            tls_ctx.verify_mode = ssl.CERT_NONE
    display = (f"{u.scheme}://{u.hostname}:{u.port}" if "//" in endpoint
               else f"{u.hostname}:{u.port}")
    return _PeerTarget(endpoint, display, u.hostname, u.port,
                       (u.path.rstrip("/") or "") + "/v1/report",
                       tls, auth_header, tls_ctx)


class FleetAgent:
    # keplint: protocol-transition — delivery-state birth
    def __init__(
        self,
        monitor: PowerMonitor,
        endpoint: str,
        node_name: str = "",
        mode: int = MODE_RATIO,
        timeout_s: float = 2.0,
        queue_max: int = 8,
        tls_skip_verify: bool = False,
        backoff_initial: float = 0.1,
        backoff_max: float = 5.0,
        breaker_threshold: int = 5,
        breaker_cooldown: float = 10.0,
        flush_timeout_s: float = 2.0,
        clock: Callable[[], float] | None = None,
        monotonic: Callable[[], float] | None = None,
        jitter_seed: int | None = None,
        spool: Spool | None = None,
        peers: Sequence[str] | None = None,
        handoff_replay: int = 8,
        drain_batch_max: int = 1,
        drain_replay_rps: float = 0.0,
        drain_retry_after_max: float = 300.0,
        wire_version: int = 2,
        keyframe_every: int = 16,
        wire_degraded_ttl: float = 60.0,
    ) -> None:
        self._monitor = monitor
        self._endpoint = endpoint
        self._node_name = node_name or socket.gethostname()
        self._mode = mode
        self._timeout = timeout_s
        # in-memory ring of (seq, sample, emitted_at, trace_id): the
        # delivery queue without a spool, the degraded fallback with one
        # (disk write failures). emitted_at/trace ride along because mem
        # items serialize lazily at SEND time, but the delivery trace
        # opens at WINDOW time.
        self._queue: collections.deque[
            tuple[int, WindowSample, float, str]] = \
            collections.deque(maxlen=queue_max)
        # durable delivery: when set, every window is appended to the
        # crash-safe spool before any send attempt and only acked on 2xx
        self._spool = spool
        self._wake = threading.Event()
        # seq is assigned at WINDOW time (enqueue), not send time, so a
        # dropped/evicted window leaves a visible seq gap the aggregator
        # counts as kepler_fleet_windows_lost_total — loss accounting
        # depends on dropped windows consuming sequence numbers
        self._seq = 0
        self._run_nonce = uuid.uuid4().hex[:16]  # identifies this agent run
        self._clock = clock or _time.time
        self._monotonic = monotonic or _time.monotonic
        self._drop_logged: float | None = None  # monotonic of last warning
        # wall clock of the last observed delivery disruption (failed
        # send, or shedding while the breaker is open): a window that was
        # emitted at or before it waited out an outage, so its eventual
        # delivery is labeled path="replay" in the aggregator's
        # delivery-latency histogram. None = never disrupted.
        self._disrupted_at: float | None = None
        # retry/backoff + circuit breaker (jitter is seeded so resilience
        # tests replay the exact same schedule)
        self._backoff_initial = max(backoff_initial, 1e-3)
        self._backoff_max = max(backoff_max, self._backoff_initial)
        self._breaker_threshold = max(1, breaker_threshold)
        self._breaker_cooldown = max(breaker_cooldown, 1e-3)
        self._flush_timeout = max(0.0, flush_timeout_s)
        self._rng = random.Random(jitter_seed)
        self._breaker_state = BREAKER_CLOSED
        self._breaker_open_until = 0.0
        self._breaker_backoff = self._breaker_cooldown  # escalates per reopen
        self._consecutive_failures = 0
        # ("spool", SpoolRecord) | ("mem", seq, sample) | None
        self._inflight: tuple | None = None
        self._conn: http.client.HTTPConnection | None = None
        self._stats = {"sent_total": 0, "send_failures": 0,
                       "dropped_total": 0, "server_rejections": 0,
                       "connects_total": 0,
                       "breaker_opens": 0, "flushed_on_shutdown": 0,
                       "redirects_followed": 0, "failovers": 0,
                       "handoffs": 0, "throttled_total": 0,
                       "drain_batches": 0, "drain_batch_records": 0,
                       "keyframes_sent": 0, "deltas_sent": 0,
                       "keyframe_resends": 0, "wire_downgrades": 0}
        # wire v2 fast path (ISSUE 14): windows encode as binary v2
        # KEYFRAMES (what the spool stores — replay/hand-off needs no
        # base state); at TRANSMIT time a fresh window whose identity
        # planes match the last ACKED keyframe ships as a delta frame
        # instead (changed rows only; FLAG_SAME when nothing moved).
        # Every `keyframe_every`-th window resends full, a structured
        # 409 needs-keyframe forces the next send full, and a replica
        # answering 415/400 to v2 bytes is remembered as v1-only for
        # `wire_degraded_ttl` then re-probed.
        self._wire_version = 2 if int(wire_version) >= 2 else 1
        self._keyframe_every = max(1, int(keyframe_every))
        self._wire_degraded_ttl = max(1e-3, float(wire_degraded_ttl))
        self._kf_base: "tuple[int, bytes] | None" = None  # (seq, bytes)
        self._since_keyframe = 0
        self._needs_keyframe = False
        self._v1_until: dict[str, float] = {}  # target url → monotonic
        # overload control (ISSUE 12): batched spool drain + throttle
        # handling. drain_batch_max > 1 ships K spooled records per
        # /v1/reports request during recovery replay; drain_replay_rps
        # token-bucket-paces that replay (0 = unpaced) so a rejoining
        # agent slews its backlog in rather than dumping it; 429
        # Retry-After values are coerced + clamped (a hostile owner
        # must not park the agent) and honored with decorrelated jitter.
        self._drain_batch_max = max(1, int(drain_batch_max))
        # floored: a zero clamp would turn every 429 into an immediate
        # resend — a tight hammer loop that defeats admission control
        self._retry_after_max = max(1e-3, float(drain_retry_after_max))
        self._pacer: _TokenBucket | None = None
        if drain_replay_rps > 0.0:
            self._pacer = _TokenBucket(drain_replay_rps,
                                       self._drain_batch_max,
                                       self._monotonic)
        # decorrelated-jitter state for consecutive throttles (reset on
        # any successful send)
        self._throttle_prev: float | None = None
        self._throttle_logged: float | None = None  # monotonic
        # targets whose batch endpoint answered 404/405/400 (an older
        # replica): fall back to single-record sends there
        self._no_batch_targets: set[str] = set()
        # HA ingest tier: the replica set. With one endpoint this is a
        # 1-peer tier and every ring mechanism below is inert; with
        # ``peers`` (the replicas' aggregator.peers list, basic-auth/TLS
        # carried per URL exactly like the single endpoint) the agent
        # follows 421 owner redirects and fails over between replicas.
        # TLS contexts are built once per peer, not per send.
        self._tls_skip_verify = tls_skip_verify
        urls = [u for u in (list(peers) if peers else []) if u]
        if endpoint and endpoint not in urls:
            urls.insert(0, endpoint)
        if not urls:
            raise ValueError("fleet agent needs an aggregator endpoint "
                             "or a non-empty peers list")
        self._peers = [_parse_target(u, tls_skip_verify) for u in urls]
        # loop/growth bounds FROZEN at the configured membership: a
        # replica naming ever-fresh owners must neither grow the peer
        # list without bound nor raise its own redirect-hop budget
        self._configured_peers = len(self._peers)
        self._max_learned_peers = self._configured_peers + 8
        # ring state, learned lazily off responses: the current owner
        # target, the highest membership epoch seen, redirect-loop
        # accounting, and the delivered watermark (highest seq with a
        # 2xx from ANY replica) stamped into every transmit header
        self._handoff_replay = max(0, int(handoff_replay))
        self._ring_epoch = 0
        self._redirect_hops = 0
        self._acked_through = 0
        # the replica that took the last 2xx: a success landing on a
        # DIFFERENT one means this node's owner moved (whether we got
        # there via a 421 redirect or by failover luck) — that is the
        # hand-off moment, and the spool tail re-delivers
        self._last_ok_target: _PeerTarget | None = None
        self._set_target(self._peers[0])

    def name(self) -> str:
        return "fleet-agent"

    def init(self) -> None:
        self._monitor.add_window_listener(self._on_window)
        if self._spool is not None and self._spool.pending_records():
            self._wake.set()  # replay the crash backlog without waiting
        log.info("fleet agent: node=%s → %s://%s:%d%s%s%s",
                 self._node_name, "https" if self._tls else "http",
                 self._host, self._port, self._path,
                 " (basic auth)" if self._auth_header else "",
                 " (durable spool)" if self._spool is not None else "")

    def _on_window(self, sample: WindowSample) -> None:
        # runs inside the monitor's refresh lock: must stay cheap. The
        # window takes its seq HERE so a window lost anywhere downstream
        # (ring overflow, spool eviction, disk failure) leaves a seq gap
        # the aggregator counts as loss. It also opens its delivery
        # trace here: a trace id + emit wall time ride the wire header
        # so the aggregator can close the trace at merge into a true
        # end-to-end latency. With a spool, the window is made durable
        # before any send attempt (one buffered write; fsync is batched,
        # never per-window by default); a disk failure degrades to the
        # in-memory ring instead of blocking the monitor.
        with telemetry.span("agent.emit"):
            self._seq += 1
            seq = self._seq
            emitted_at = self._clock()
            trace_id = uuid.uuid4().hex[:16]
            if self._spool is not None:
                try:
                    body = self._encode(sample, seq, trace_id=trace_id,
                                        emitted_at=emitted_at)
                    with telemetry.span("agent.spool_append"):
                        appended = self._spool.append(body)
                    if appended:
                        self._wake.set()
                        return
                except Exception:
                    log.exception("spool append failed; falling back to "
                                  "the in-memory ring for this window")
            if len(self._queue) == self._queue.maxlen:
                self._stats["dropped_total"] += 1
            self._queue.append((seq, sample, emitted_at, trace_id))
            self._wake.set()

    # keplint: thread-role=agent
    def run(self, ctx: CancelContext) -> None:
        while not ctx.cancelled():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            self._drain(ctx)
            if self._spool is not None:
                # batched-durability tick on THIS thread — kept off the
                # append path (monitor refresh lock) and independent of
                # breaker state, so an outage backlog still hits disk
                self._spool.sync()
            if ctx.wait(0.0):
                return

    # keplint: thread-role=shutdown
    def shutdown(self) -> None:
        self._wake.set()
        # best-effort final flush: a clean node drain delivers its queued
        # window(s) instead of abandoning them. Bounded by flush_timeout_s
        # and skipped while the breaker is open (aggregator presumed down).
        # With a spool, anything not flushed stays durable and replays on
        # the next run — the flush is a latency nicety, not the safety net.
        if self._breaker_state != BREAKER_OPEN:
            deadline = self._monotonic() + self._flush_timeout
            while self._monotonic() < deadline:
                item = self._inflight or self._next_item()
                if item is None:
                    break
                self._inflight = item
                try:
                    self._send_item(item)
                except UnsendableRecordError as err:
                    self._finish_item(item)
                    if item[0] != "batch":
                        self._stats["dropped_total"] += 1
                    log.info("shutdown flush: unsendable record (%s)", err)
                    continue
                except ThrottledError as err:
                    # the flush is a latency nicety; a shedding tier has
                    # asked us to go away — the spool keeps everything
                    if item[0] == "batch":
                        self._inflight = None
                    log.info("shutdown flush stopped (throttled): %s", err)
                    break
                except NeedsKeyframeError:
                    self._on_needs_keyframe()
                    continue
                except _WireDowngradeError:
                    self._v1_until[self._target.url] = \
                        self._monotonic() + self._wire_degraded_ttl
                    self._stats["wire_downgrades"] += 1
                    continue
                except _BatchUnsupportedError:
                    self._no_batch_targets.add(self._target.url)
                    self._inflight = None
                    continue
                except OwnerRedirectError as err:
                    if item[0] == "batch":
                        self._inflight = None  # re-peek past acked prefix
                    if self._follow_redirect(err):
                        continue  # retry against the named owner
                    log.info("shutdown flush stopped (unusable "
                             "redirect): %s", err)
                    break
                except AggregatorRejectedError as err:
                    # this one sample is unacceptable; the rest may flush
                    self._finish_item(item)
                    self._stats["dropped_total"] += 1
                    self._stats["server_rejections"] += 1
                    log.info("shutdown flush: report rejected (%s)", err)
                    continue
                except (OSError, http.client.HTTPException) as err:
                    log.info("shutdown flush stopped (%d left): %s",
                             self.backlog(), err)
                    break
                self._finish_item(item)
                self._stats["sent_total"] += 1
                self._stats["flushed_on_shutdown"] += 1
        self._close_conn()
        if self._spool is not None:
            self._spool.close()

    def health(self) -> dict:
        """Probe for the API server's /healthz (server.health registry)."""
        out = {
            "ok": self._breaker_state != BREAKER_OPEN,
            "breaker": self._breaker_state,
            "consecutive_failures": self._consecutive_failures,
            "queued": self.backlog(),
            "target": self._target.display,
            "ring_epoch": self._ring_epoch,
            "acked_through": self._acked_through,
            "wire_version": (1 if self._wire_version < 2
                             or self._target_downgraded()
                             else 2),
            **self._stats,
        }
        if self._spool is not None:
            out["spool_pending"] = self._spool.pending_records()
        return out

    def spool_health(self) -> dict:
        """Spool probe for the HealthRegistry (utilization, oldest-record
        age, eviction counters). Reports ok with no spool configured."""
        if self._spool is None:
            return {"ok": True, "enabled": False}
        return {"enabled": True, **self._spool.health()}

    def backlog(self) -> int:
        """Windows awaiting delivery (spool backlog + in-memory ring).
        An in-flight SPOOL record is still unacked and therefore already
        inside pending_records() — only a mem item (popped off the ring)
        needs counting separately."""
        inflight = self._inflight
        pending = len(self._queue) + (
            1 if inflight is not None and inflight[0] == "mem" else 0)
        if self._spool is not None:
            pending += self._spool.pending_records()
        return pending

    def collect(self):
        """prometheus_client custom-collector hook: the breaker-state
        gauge (always) plus spool durability metrics (only when a spool
        is configured)."""
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )
        breaker = GaugeMetricFamily(
            "kepler_fleet_agent_breaker_state",
            "Send circuit-breaker state as an enum gauge: exactly one "
            "of the three state labels is 1 at any scrape (alert on "
            'kepler_fleet_agent_breaker_state{state="open"} == 1)',
            labels=["state"])
        for state in (BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN):
            breaker.add_metric([state],
                               1.0 if self._breaker_state == state
                               else 0.0)
        yield breaker
        if self._spool is None:
            return
        stats = self._spool.stats()
        evicted = CounterMetricFamily(
            "kepler_fleet_spool_evicted_total",
            "Unacked windows discarded by spool cap eviction")
        evicted.add_metric([], stats["evicted_total"])
        yield evicted
        pending = GaugeMetricFamily(
            "kepler_fleet_spool_pending_records",
            "Windows appended to the spool and not yet acknowledged")
        pending.add_metric([], self._spool.pending_records())
        yield pending
        util = GaugeMetricFamily(
            "kepler_fleet_spool_utilization_ratio",
            "Spool bytes in use as a fraction of the configured cap")
        util.add_metric([], self._spool.utilization())
        yield util
        age = GaugeMetricFamily(
            "kepler_fleet_spool_oldest_record_age_seconds",
            "Age of the oldest unacknowledged spooled window")
        age.add_metric([], self._spool.oldest_age() or 0.0)
        yield age
        errors = CounterMetricFamily(
            "kepler_fleet_spool_write_errors_total",
            "Spool appends that failed at the disk layer")
        errors.add_metric([], stats["write_errors_total"])
        yield errors

    # -- internals ---------------------------------------------------------

    def _drain(self, ctx: CancelContext | None) -> None:
        """Send queued samples, honoring breaker state and backoff.

        Closed: send with exponential-backoff retries; `breaker_threshold`
        consecutive failures open the breaker. Open: shed (no connection
        attempts) until the cooldown elapses, then half-open. Half-open:
        one probe send — success closes the breaker, failure re-opens it
        with a doubled (capped) cooldown.
        """
        if (self._inflight is None and not self._queue
                and (self._spool is None
                     or self._spool.pending_records() == 0)):
            return  # idle wake: no work, no telemetry cycle recorded
        with telemetry.span("agent.drain"):
            self._drain_pending(ctx)

    def _drain_pending(self, ctx: CancelContext | None) -> None:
        while not (ctx is not None and ctx.cancelled()):
            now = self._monotonic()
            if (self._breaker_state == BREAKER_OPEN
                    and now < self._breaker_open_until):
                # shedding: backlog stays in the spool/ring. The outage
                # is still ongoing — keep the disruption watermark
                # current so windows emitted DURING the open window are
                # labeled replays when they finally deliver.
                self._disrupted_at = self._clock()
                return
            item = self._inflight
            if item is None:
                # an elapsed-cooldown breaker stays OPEN until a sample
                # exists to probe with: health must not report recovery
                # that nothing demonstrated
                item = self._next_item()
                if item is None:
                    return
                self._inflight = item
            if item[0] == "batch" and self._pacer is not None:
                # replay pacing: the token bucket caps how fast the
                # backlog slews in — a depleted bucket waits for a
                # token instead of dumping the spool on the aggregator
                granted, wait = self._pacer.take(len(item[1]))
                if granted == 0:
                    self._inflight = None
                    if ctx is None or ctx.wait(wait):
                        return
                    continue
                if granted < len(item[1]):
                    item = ("batch", item[1][:granted])
                    self._inflight = item
            if self._breaker_state == BREAKER_OPEN:
                self._breaker_state = BREAKER_HALF_OPEN
                log.info("circuit breaker half-open: probing aggregator")
            try:
                sent_seq = self._send_item(item)
            except ThrottledError as err:
                # a 429 is a throttle, not a failure: no breaker/
                # failover/disruption bookkeeping — wait out the
                # (coerced) Retry-After with decorrelated jitter and
                # retry. Spooled records stay durable meanwhile.
                self._stats["throttled_total"] += 1
                if item[0] == "batch":
                    # the concluded prefix was acked inside the send;
                    # re-peek the rest from the cursor next round
                    self._inflight = None
                self._log_throttle(err)
                delay = self._throttle_delay(err.retry_after)
                if ctx is None or ctx.wait(delay):
                    return
                continue
            except NeedsKeyframeError:
                # the SAME window retries as a full keyframe: the tier
                # answered (breaker-closing evidence), nothing dropped,
                # nothing counted as a failure — a 421 in wire clothing
                self._on_needs_keyframe()
                self._note_send_success()
                continue
            except _WireDowngradeError:
                # old replica: remember it as v1-only for the TTL and
                # retry the SAME record transcoded down
                self._v1_until[self._target.url] = \
                    self._monotonic() + self._wire_degraded_ttl
                self._stats["wire_downgrades"] += 1
                self._note_send_success()
                log.info("target %s cannot parse wire v2; downgrading "
                         "to v1 for %.0fs", self._target.display,
                         self._wire_degraded_ttl)
                continue
            except _BatchUnsupportedError:
                # older replica without /v1/reports: remember and fall
                # back to single-record sends against this target
                self._no_batch_targets.add(self._target.url)
                self._inflight = None
                continue
            except UnsendableRecordError as err:
                # poisoned record: ack + drop so the backlog moves on,
                # but leave the breaker exactly as it was — this proves
                # nothing about the aggregator (a half-open probe simply
                # passes to the next record). Batch items already acked
                # and counted their poisoned records internally.
                self._finish_item(item)
                if item[0] != "batch":
                    self._stats["dropped_total"] += 1
                    log.warning("dropping unsendable spooled record: %s",
                                err)
                continue
            except OwnerRedirectError as err:
                # this replica answered "not mine": follow the redirect
                # and retry the SAME window against the named owner. An
                # unusable redirect (loop, hostile owner) degrades to
                # the ordinary failure path — backoff + failover decide
                # the next attempt, the spool keeps the record safe.
                if item[0] == "batch":
                    # any concluded prefix was acked in the send;
                    # re-peek the remainder against the new owner
                    self._inflight = None
                if self._follow_redirect(err):
                    continue
                self._on_send_failure(err)
                self._rotate_target()
                if self._breaker_state == BREAKER_OPEN:
                    return
                delay = self._backoff_delay()
                if ctx is None or ctx.wait(delay):
                    return
                continue
            except AggregatorRejectedError as err:
                # the aggregator ANSWERED: delivery is healthy, this
                # payload will never be accepted — drop it and count the
                # response as breaker-closing evidence (retrying a 4xx
                # forever would shed good reports from a live aggregator).
                # A spooled record is acked too: replaying a permanent
                # reject forever would wedge the whole backlog behind it.
                self._finish_item(item)
                self._stats["dropped_total"] += 1
                self._stats["server_rejections"] += 1
                self._log_drop(err)
                self._note_send_success()
                continue
            except (OSError, http.client.HTTPException) as err:
                if item[0] == "batch":
                    # records are durable in the spool; re-peek from
                    # the cursor after backoff (dedup absorbs any
                    # record the replica processed before dying)
                    self._inflight = None
                self._on_send_failure(err)
                # probe a different replica next: during a replica
                # outage successive attempts cycle the peer list, and
                # the first live one answers with ownership truth
                self._rotate_target()
                if self._breaker_state == BREAKER_OPEN:
                    return
                # closed, below threshold: retry after backoff with jitter
                delay = self._backoff_delay()
                if ctx is None or ctx.wait(delay):
                    return
                continue
            self._finish_item(item)
            if sent_seq:
                # delivered watermark (any replica's 2xx): stamped into
                # every transmit header so a NEW owner's gap detection
                # never counts windows a previous owner acknowledged
                self._advance_acked(sent_seq)
            if self._target is not self._last_ok_target:
                if self._last_ok_target is not None:
                    self._handoff_rewind()
                self._last_ok_target = self._target
            self._redirect_hops = 0
            self._stats["sent_total"] += 1
            self._note_send_success()

    def _next_item(self) -> tuple | None:
        """Next undelivered window: the durable spool backlog first (it
        holds the OLDEST windows, including a previous run's replay),
        then the in-memory ring. A backlog deeper than one record
        drains BATCHED (``("batch", [records])``) when batching is
        enabled and the current target supports it — recovery replay
        then ships K records per request instead of one."""
        if self._spool is not None:
            if (self._drain_batch_max > 1
                    and self._target.url not in self._no_batch_targets
                    and self._spool.pending_records() > 1):
                recs = self._spool.peek_batch(self._drain_batch_max)
                # byte-bound the request body: truncate (never drop) at
                # the budget — an over-budget HEAD record falls through
                # to the single path, which always handled big reports
                total = 0
                for k, rec in enumerate(recs):
                    total += len(rec.payload) + 256
                    if total > MAX_BATCH_BYTES and k > 0:
                        recs = recs[:k]
                        break
                if len(recs) > 1:
                    return ("batch", recs)
            rec = self._spool.peek()
            if rec is not None:
                return ("spool", rec)
        if self._queue:
            seq, sample, emitted_at, trace_id = self._queue.popleft()
            return ("mem", seq, sample, emitted_at, trace_id)
        return None

    def _finish_item(self, item: tuple) -> None:
        """The item's delivery concluded (2xx or permanent 4xx): advance
        the spool ack cursor so it is never re-sent. Batch items acked
        per record inside the send — only the in-flight slot clears."""
        self._inflight = None
        if item[0] == "spool":
            assert self._spool is not None
            self._spool.ack(item[1])  # validated: never acks a record
            # other than the one whose delivery just concluded

    def _note_send_success(self) -> None:
        """The aggregator responded — close the breaker, reset schedules."""
        if self._breaker_state != BREAKER_CLOSED:
            log.info("circuit breaker closed: aggregator recovered")
            journal.emit("breaker.close", target=self._target.display,
                         failures=self._consecutive_failures)
        self._breaker_state = BREAKER_CLOSED
        self._consecutive_failures = 0
        self._breaker_backoff = self._breaker_cooldown
        self._throttle_prev = None  # throttle jitter restarts fresh

    def _throttle_delay(self, retry_after: float) -> float:
        """Decorrelated jitter over the server's Retry-After hint:
        consecutive throttles spread a herd of waiting agents apart
        (``sleep = uniform(hint, prev * 3)``, clamped) instead of
        re-synchronizing them on the hint's exact value."""
        base = max(1e-3, retry_after)
        prev = self._throttle_prev if self._throttle_prev else base
        delay = min(max(self._retry_after_max, base),
                    self._rng.uniform(base, max(base, prev * 3.0)))
        self._throttle_prev = delay
        return delay

    def _log_throttle(self, err: ThrottledError) -> None:
        # same monotonic rate-limit SHAPE as send failures, but its OWN
        # timestamp and INFO level — sustained throttling must not
        # suppress the data-loss WARNING (_log_drop), which is the
        # operator's only loss signal exactly during overload
        now = self._monotonic()
        if self._throttle_logged is None \
                or now - self._throttle_logged >= 30.0:
            self._throttle_logged = now
            log.info("aggregator throttled this agent (429): %s", err)

    def _on_send_failure(self, err: Exception) -> None:
        self._stats["send_failures"] += 1
        self._consecutive_failures += 1
        # windows emitted at or before this instant waited through a
        # delivery disruption — their eventual sends are replays
        self._disrupted_at = self._clock()
        self._log_drop(err)
        half_open = self._breaker_state == BREAKER_HALF_OPEN
        if (half_open
                or self._consecutive_failures >= self._breaker_threshold):
            if half_open:
                # failed probe: double the cooldown, capped — but never
                # below the operator-configured base cooldown
                self._breaker_backoff = min(
                    self._breaker_backoff * 2,
                    max(60.0, self._breaker_cooldown))
            self._breaker_state = BREAKER_OPEN
            self._breaker_open_until = (self._monotonic()
                                        + self._breaker_backoff)
            self._stats["breaker_opens"] += 1
            journal.emit("breaker.open", target=self._target.display,
                         failures=self._consecutive_failures,
                         cooldown_s=round(self._breaker_backoff, 3),
                         probe_failed=half_open)
            # shed the in-flight IN-MEMORY sample — by reopen time it is
            # stale. A spooled record is NOT shed: it stays durably
            # unacked and replays after the cooldown (losing it would
            # defeat the spool's whole reason to exist).
            if self._inflight is not None:
                if self._inflight[0] == "mem":
                    self._stats["dropped_total"] += 1
                self._inflight = None
            log.warning("circuit breaker open for %.1fs after %d "
                        "consecutive send failures: %s",
                        self._breaker_backoff,
                        self._consecutive_failures, err)

    def _backoff_delay(self) -> float:
        return backoff_with_jitter(self._backoff_initial, self._backoff_max,
                                   self._consecutive_failures, self._rng)

    def _set_target(self, target: _PeerTarget) -> None:
        self._target = target
        self._host, self._port = target.host, target.port
        self._path, self._tls = target.path, target.tls
        self._auth_header = target.auth_header
        self._tls_ctx = target.tls_ctx

    def _resolve_peer(self, owner: str) -> "_PeerTarget | None":
        """The dialable target for a redirect's (sanitized) owner id: an
        exact URL, display, or host:port match in the known peer list,
        else — lazy ring learning for agents with a stale peers config —
        the owner parsed as a fresh endpoint and remembered. Learning is
        BOUNDED: past the cap an unknown owner is an unusable redirect
        (failure path), never unbounded peer-list growth."""
        for t in self._peers:
            if owner in (t.url, t.display, f"{t.host}:{t.port}"):
                return t
        if len(self._peers) >= self._max_learned_peers:
            return None
        try:
            target = _parse_target(owner, self._tls_skip_verify)
        except ValueError:
            return None
        self._peers.append(target)
        return target

    def _follow_redirect(self, err: OwnerRedirectError) -> bool:
        """Adopt a 421's owner + epoch. Returns False (caller treats it
        as a failed send) when the redirect is unusable: hostile/empty
        owner, a target we are already on, or an owner-disagreement
        loop — the hop budget is frozen at the CONFIGURED peer count
        (not the learned list, which a hostile replica could grow) and
        resets only on a successful send."""
        self._adopt_epoch(err.epoch)
        if err.owner is None:
            return False
        self._redirect_hops += 1
        if self._redirect_hops > self._configured_peers + 2:
            return False
        target = self._resolve_peer(err.owner)
        if target is None or target is self._target:
            return False
        self._close_conn()
        self._set_target(target)
        self._stats["redirects_followed"] += 1
        # the redirect IS an aggregator answer — the ingest tier is
        # alive, so it closes the breaker like any other response
        self._note_send_success()
        log.info("ingest owner moved: following redirect to %s "
                 "(ring epoch %d)", target.display, self._ring_epoch)
        return True

    def _handoff_rewind(self) -> None:
        """Hot hand-off: the last 2xx came from a DIFFERENT replica
        than the one before — this node's owner moved. Re-deliver the
        spool tail so the new owner rebuilds the node's recent state
        from real records; any replica that already ingested them
        absorbs the overlap through the (run, seq) dedup window."""
        if self._spool is None or not self._handoff_replay:
            return
        rewound = self._spool.rewind(self._handoff_replay)
        if rewound:
            self._stats["handoffs"] += 1
            journal.emit("spool.rewind", records=rewound,
                         target=self._target.display)
            # an in-flight peek predates the rewound cursor (its ack
            # would no-op anyway) — drop it so the drain restarts from
            # the rewound tail in order
            self._inflight = None
            log.info("hand-off: re-delivering %d spooled record(s) to "
                     "the new owner %s", rewound, self._target.display)

    def _rotate_target(self) -> None:
        """Outage failover: point the next attempt at the next
        configured peer — the first live replica answers with ownership
        truth (a 2xx if it owns this node, a 421 redirect if not)."""
        if len(self._peers) <= 1:
            return
        i = self._peers.index(self._target)
        self._close_conn()
        self._set_target(self._peers[(i + 1) % len(self._peers)])
        self._stats["failovers"] += 1

    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is not None:
            return self._conn
        if self._tls:
            conn: http.client.HTTPConnection = http.client.HTTPSConnection(
                self._host, self._port, timeout=self._timeout,
                context=self._tls_ctx)
        else:
            conn = http.client.HTTPConnection(self._host, self._port,
                                              timeout=self._timeout)
        self._conn = conn
        self._stats["connects_total"] += 1
        return conn

    def _close_conn(self) -> None:
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _encode(self, sample: WindowSample, seq: int,
                trace_id: str = "", emitted_at: float | None = None
                ) -> bytes:
        """Wire bytes for one window — WITHOUT ``sent_at``, which is a
        transmit-time property stamped by :meth:`_post` (a spooled record
        may be sent long after it was encoded). ``trace_id``/
        ``emitted_at`` are WINDOW-time properties: the delivery trace
        opens when the window is emitted, not when it is serialized."""
        batch = sample.batch
        report = NodeReport(
            node_name=self._node_name,
            zone_deltas_uj=sample.zone_deltas_uj,
            zone_valid=sample.zone_valid,
            usage_ratio=sample.usage_ratio,
            cpu_deltas=batch.cpu_deltas,
            workload_ids=list(batch.ids),
            node_cpu_delta=batch.node_cpu_delta,
            dt_s=sample.dt_s,
            mode=self._mode,
            workload_kinds=batch.kinds,
        )
        if self._wire_version >= 2:
            # binary v2 keyframe — the durable form (spooled records
            # are ALWAYS keyframes; the delta rewrite happens at
            # transmit against the last acked keyframe)
            return encode_report_v2(report, list(sample.zone_names),
                                    seq=seq, run=self._run_nonce,
                                    trace_id=trace_id,
                                    emitted_at=emitted_at)
        return encode_report(report, list(sample.zone_names), seq=seq,
                             run=self._run_nonce, trace_id=trace_id,
                             emitted_at=emitted_at)

    def _target_downgraded(self) -> bool:
        """True while the current target is remembered as v1-only; an
        elapsed ``wire_degraded_ttl`` clears the mark so the next send
        re-probes v2."""
        until = self._v1_until.get(self._target.url)
        if until is None:
            return False
        if self._monotonic() >= until:
            del self._v1_until[self._target.url]
            return False
        return True

    def _prepare_wire(self, body: bytes,
                      path: str) -> "tuple[bytes, tuple | None]":
        """Pick this send's wire form → ``(frame, info)``.

        v1 bodies pass through. A v2 keyframe against a v1-downgraded
        target transcodes down (raising WireError → the caller's
        unsendable path). Otherwise a FRESH window with a usable acked
        base ships as a delta (``info = ("delta",)``); everything else
        stays a keyframe (``info = ("kf", seq, body)`` when it can
        become the next base). Replays always ship full — a hand-off's
        new owner has no base state, and the spool holds keyframes."""
        if body[: len(WireLayoutV2.MAGIC)] != WireLayoutV2.MAGIC:
            return body, None
        if self._wire_version < 2 or self._target_downgraded():
            return transcode_to_v1(body), None
        run, seq = peek_identity(body)
        # the keyframe/delta choice is the PURE predicate
        # (fleet/delivery.py, model-checked by kepmc) — the 409
        # convergence property lives there
        want_kf = keyframe_wanted(
            needs_keyframe=self._needs_keyframe, delivery_path=path,
            has_base=self._kf_base is not None,
            run_matches=(run == self._run_nonce),
            since_keyframe=self._since_keyframe,
            keyframe_every=self._keyframe_every)
        if not want_kf and self._kf_base is not None:
            delta = encode_delta_v2(body, self._kf_base[1])
            if delta is not None:
                return delta, ("delta",)
        if run == self._run_nonce and seq > 0:
            return body, ("kf", seq, body)
        return body, None

    # keplint: protocol-transition — adopt an ACCEPTED keyframe as the
    # delta base (runs for spooled keyframes the owner concluded too)
    def _adopt_kf_base(self, seq: int, body: bytes) -> None:
        self._kf_base = (seq, body)
        self._since_keyframe = 0
        self._needs_keyframe = False

    # keplint: protocol-transition — a 409 latches the forced keyframe:
    # the NEXT send of this window always ships full (convergence)
    def _on_needs_keyframe(self) -> None:
        self._needs_keyframe = True
        self._stats["keyframe_resends"] += 1

    # keplint: protocol-transition — delivered watermark: a seq SOME
    # replica 2xx'd; monotonic, stamped into every transmit header
    def _advance_acked(self, seq: int) -> None:
        self._acked_through = max(self._acked_through, seq)

    # keplint: protocol-transition — the ring epoch only ratchets
    # forward (stale redirects/accepts can never regress it)
    def _adopt_epoch(self, epoch: int | None) -> None:
        if epoch is not None and epoch > self._ring_epoch:
            self._ring_epoch = epoch

    # keplint: protocol-transition — delta-cadence tick
    def _after_wire_success(self, info: "tuple | None") -> None:
        """A 2xx landed: adopt the keyframe as the delta base, or tick
        the delta cadence toward the next scheduled keyframe."""
        if info is None:
            return
        if info[0] == "kf":
            self._adopt_kf_base(info[1], info[2])
            self._stats["keyframes_sent"] += 1
        else:
            self._since_keyframe += 1
            self._stats["deltas_sent"] += 1

    def _delivery_path(self, origin_wall: float, recovered: bool) -> str:
        """Label for the delivery-latency histogram: a crash-backlog
        record (``recovered``) or a window that waited through a send
        disruption is a replay; everything else is a fresh send."""
        if recovered:
            return "replay"
        if self._disrupted_at is not None \
                and origin_wall <= self._disrupted_at:
            return "replay"
        return "fresh"

    def _send_item(self, item: tuple) -> int:
        """Send one queued window; returns its seq (0 when the payload
        carries none, or belongs to a PREVIOUS run — an old run's
        replayed seqs must not inflate this run's delivered watermark,
        or they could mask the new run's own leading-window loss) so
        the caller can advance ``acked_through`` after the ack."""
        if item[0] == "batch":
            return self._send_batch(item[1])
        if item[0] == "spool":
            rec = item[1]
            path = self._delivery_path(rec.appended_at, rec.recovered)
            run, seq = peek_identity(rec.payload)
            with telemetry.span("agent.send"):
                self._post(rec.payload, path=path,
                           appended_at=rec.appended_at)
            return seq if run == self._run_nonce else 0
        _tag, seq, sample, emitted_at, trace_id = item
        path = self._delivery_path(emitted_at, False)
        with telemetry.span("agent.send"):
            self._post(self._encode(sample, seq, trace_id=trace_id,
                                    emitted_at=emitted_at),
                       path=path)
        return seq

    def _send(self, sample: WindowSample, seq: int | None = None) -> None:
        """Encode + POST one sample (direct-send path used by tests and
        the pre-spool call sites). ``seq=None`` takes the next number."""
        if seq is None:
            self._seq += 1
            seq = self._seq
        self._post(self._encode(sample, seq,
                                trace_id=uuid.uuid4().hex[:16],
                                emitted_at=self._clock()))

    def _fire_presend_faults(self) -> None:
        """Connection-level fault sites, consulted once per send attempt
        BEFORE any payload work — exactly where a real refused connect,
        slow network, or shedding replica would interpose."""
        spec = fault.fire("net.refuse")
        if spec is not None:
            self._close_conn()
            raise ConnectionRefusedError("fault-injected connect refusal")
        spec = fault.fire("net.slow")
        if spec is not None:
            _time.sleep(min(spec.arg or 0.05, self._timeout))
        spec = fault.fire("net.throttle")
        if spec is not None:
            # chaos stand-in for an admission-shedding replica: the send
            # is answered 429 before any bytes move (arg = Retry-After)
            raise ThrottledError(coerce_retry_after(
                spec.arg if spec.arg is not None else DEFAULT_RETRY_AFTER,
                cap=self._retry_after_max))

    def _transport_post(self, url_path: str,
                        body: bytes) -> tuple[Any, bytes]:
        """One POST over the persistent connection (fault sites fired
        by the caller via :meth:`_fire_presend_faults`; the one-way
        ``net.partition`` fires after the response). → (response,
        body bytes)."""
        headers = {"Content-Type": "application/octet-stream"}
        if self._auth_header:
            headers["Authorization"] = self._auth_header
        conn = self._connection()
        try:
            conn.request("POST", url_path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        except Exception:
            # a dead persistent connection is not reusable — reconnect on
            # the next attempt
            self._close_conn()
            raise
        jnl = journal.active()
        if jnl.enabled:
            # merge the replica's HLC piggyback (EVERY response carries
            # it when its journal is on — accepts, 421 redirects, 409
            # needs-keyframe, 429 sheds) so this agent's breaker/spool
            # events order causally after the replica's state changes.
            # A hostile stamp is laundered away; a vaulted one is
            # clamped (observe_text → parse_hlc + drift clamp).
            jnl.observe_text(resp.headers.get("X-Kepler-HLC"))
        if fault.fire("net.partition") is not None:
            # one-way partition: the replica processed the report but
            # its response never made it back — the agent must treat
            # the send as failed and re-deliver later (the dedup window
            # absorbs the duplicate)
            self._close_conn()
            raise OSError("fault-injected one-way partition "
                          "(response lost)")
        if resp.status >= 300 or resp.will_close:
            self._close_conn()
        return resp, data

    def _learn_epoch(self, headers: Any) -> None:
        """Lazy epoch learning: accepts advertise the ring epoch too,
        so a settled agent still notices a membership bump."""
        self._adopt_epoch(coerce_epoch(
            _epoch_from_header(headers.get("X-Kepler-Epoch"))))

    def _post(self, body: bytes, path: str = "fresh",
              appended_at: float | None = None) -> None:
        self._fire_presend_faults()
        sent_at = self._clock()
        spec = fault.fire("report.clock_skew")
        if spec is not None:
            sent_at += spec.arg if spec.arg is not None else 300.0
        try:
            frame, wire_info = self._prepare_wire(body, path)
            frame = restamp_transmit(frame, sent_at, delivery_path=path,
                                     appended_at=appended_at,
                                     owner=self._target.display,
                                     epoch=self._ring_epoch,
                                     acked_through=self._acked_through)
        except WireError as err:
            # a spooled record that no longer parses (disk corruption the
            # CRC missed, or a format change across restart) can never be
            # sent — drop it so the backlog doesn't wedge behind it, but
            # through a path that does NOT masquerade as an aggregator
            # response (no network contact happened)
            raise UnsendableRecordError(str(err)) from err
        sent_v2 = frame[: len(WireLayoutV2.MAGIC)] == WireLayoutV2.MAGIC
        sent_delta = wire_info is not None and wire_info[0] == "delta"
        spec = fault.fire("net.corrupt_body")
        if spec is not None:
            # drop the tail: header (and node name) stay parseable, the
            # array manifest overruns → deterministic WireError server-side
            frame = frame[:-4]
        resp, data = self._transport_post(self._path, frame)
        if resp.status == 421:
            owner, epoch = _parse_redirect(data, resp.headers)
            raise OwnerRedirectError(owner, epoch)
        if resp.status == 409 and sent_delta \
                and resp.headers.get("X-Kepler-Needs-Keyframe"):
            # only a DELTA can legitimately need a keyframe; the marker
            # on anything else is a hostile/buggy server and falls
            # through to the permanent-reject path (no resend loop)
            raise NeedsKeyframeError()
        if sent_v2 and (resp.status == 415 or (
                resp.status == 400
                and (b"bad magic" in data or b"unsupported" in data))):
            # an old replica that can't parse v2 bytes at all (its v1
            # decoder answers "bad magic"/"unsupported version"):
            # downgrade this target and retry the SAME record as v1. A
            # 400 naming any OTHER defect is a real quarantine of a
            # corrupt record and keeps its permanent-reject semantics.
            raise _WireDowngradeError()
        if resp.status == 429:
            # a throttle, never a failure: the Retry-After is hostile
            # wire input until coerced (clamped so an adversarial owner
            # can't park this agent forever)
            raise ThrottledError(coerce_retry_after(
                resp.headers.get("Retry-After"),
                cap=self._retry_after_max))
        if 400 <= resp.status < 500:
            raise AggregatorRejectedError(resp.status)
        if resp.status >= 300:
            raise http.client.HTTPException(
                f"aggregator returned {resp.status}")
        self._learn_epoch(resp.headers)
        self._after_wire_success(wire_info)

    def _send_batch(self, recs: "list[SpoolRecord]") -> int:
        """Ship consecutive spooled records as ONE ``/v1/reports``
        request (batched recovery drain) and conclude each according to
        its per-record status. Records are acked IN ORDER as their
        statuses conclude; the first throttle/redirect stops the walk —
        the concluded prefix stays acked, the rest re-peeks from the
        cursor. Returns the highest acked seq of the CURRENT run (the
        ``acked_through`` watermark input). Every per-record status is
        hostile wire input: malformed rows conclude nothing."""
        assert self._spool is not None
        self._fire_presend_faults()
        sent_at = self._clock()
        spec = fault.fire("report.clock_skew")
        if spec is not None:
            sent_at += spec.arg if spec.arg is not None else 300.0
        bodies: list[bytes] = []
        batch: list[SpoolRecord] = []
        downgraded = self._wire_version < 2 or self._target_downgraded()
        for rec in recs:
            path = self._delivery_path(rec.appended_at, rec.recovered)
            try:
                payload = rec.payload
                if downgraded:
                    # v1-only target: spooled v2 keyframes transcode
                    # down per record (v1 payloads pass through)
                    payload = transcode_to_v1(payload)
                bodies.append(restamp_transmit(
                    payload, sent_at, delivery_path=path,
                    appended_at=rec.appended_at,
                    owner=self._target.display,
                    epoch=self._ring_epoch,
                    acked_through=self._acked_through))
            except WireError as err:
                if bodies:
                    # truncate: the poisoned record surfaces as the
                    # batch head next round and is dropped there
                    break
                # poisoned head: ack + drop exactly like the single
                # path (no network contact — evidence of nothing)
                self._spool.ack(rec)
                self._stats["dropped_total"] += 1
                log.warning("dropping unsendable spooled record: %s", err)
                continue
            batch.append(rec)
        if not bodies:
            raise UnsendableRecordError(
                "entire batch head was unsendable (already dropped)")
        with telemetry.span("agent.send"):
            resp, data = self._transport_post(
                self._target.batch_path, encode_report_batch(bodies))
        status = resp.status
        if status in (400, 404, 405, 413):
            # an older replica without /v1/reports, one that cannot
            # parse the envelope, or a smaller body cap than ours
            # (413): fall back to single-record sends against this
            # target — nothing concluded, nothing dropped
            raise _BatchUnsupportedError(
                f"batch endpoint answered {status}")
        if status == 421:
            owner, epoch = _parse_redirect(data, resp.headers)
            raise OwnerRedirectError(owner, epoch)
        if status == 429:
            raise ThrottledError(coerce_retry_after(
                resp.headers.get("Retry-After"),
                cap=self._retry_after_max))
        if status != 200:
            raise http.client.HTTPException(
                f"aggregator returned {status}")
        self._learn_epoch(resp.headers)
        try:
            payload = json.loads(data)
        except (ValueError, UnicodeDecodeError):
            payload = None
        results = (payload.get("results")
                   if isinstance(payload, dict) else None)
        if not isinstance(results, list):
            # hostile/garbled response: nothing provably concluded —
            # the records stay spooled and the failure path sets pace
            raise http.client.HTTPException(
                "unparseable batch response body")
        self._stats["drain_batches"] += 1
        top_seq = 0
        concluded = 0
        throttle: float | None = None
        redirect: "tuple | None" = None
        kf_base: "tuple[int, bytes] | None" = None
        wire_downgrade = False
        for rec, row in zip(batch, results):
            st = row.get("status") if isinstance(row, dict) else None
            if isinstance(st, bool) or not isinstance(st, int):
                break  # hostile row: stop concluding records here
            if (st in (400, 415) and not downgraded
                    and rec.payload[: len(WireLayoutV2.MAGIC)]
                    == WireLayoutV2.MAGIC):
                err_txt = row.get("error")
                if isinstance(err_txt, str) and (
                        "bad magic" in err_txt
                        or "unsupported" in err_txt):
                    # a pre-v2 replica whose batch endpoint exists but
                    # whose v1 decoder rejects every v2 record: this is
                    # the wire-downgrade signature, NOT a permanent
                    # reject — stop concluding WITHOUT acking so the
                    # durable backlog retries transcoded to v1
                    wire_downgrade = True
                    break
            if 200 <= st < 300:
                self._spool.ack(rec)
                concluded += 1
                run, seq = peek_identity(rec.payload)
                if run == self._run_nonce:
                    top_seq = max(top_seq, seq)
                    if (seq > 0 and not downgraded
                            and rec.payload[: len(WireLayoutV2.MAGIC)]
                            == WireLayoutV2.MAGIC):
                        # a spooled keyframe the owner just accepted is
                        # a fresh delta base — after a herd replay the
                        # agent resumes deltas immediately
                        kf_base = (seq, rec.payload)
                continue
            if st == 409 and isinstance(row.get("needs_keyframe"),
                                        bool) and row["needs_keyframe"]:
                # spooled records are already keyframes, so this can
                # only be a hostile/buggy server: stop concluding
                # WITHOUT acking (never drop a durable record on it)
                break
            if st == 429:
                throttle = coerce_retry_after(
                    row.get("retry_after"), cap=self._retry_after_max)
                break
            if st == 421:
                redirect = (sanitize_peer(row.get("owner")),
                            coerce_epoch(row.get("epoch")))
                break
            if 400 <= st < 500:
                # per-record permanent reject: ack + drop so the rest
                # of the backlog never wedges behind it (single-path
                # semantics, record by record)
                self._spool.ack(rec)
                concluded += 1
                self._stats["dropped_total"] += 1
                self._stats["server_rejections"] += 1
                continue
            break  # per-record 5xx: not concluded; retries later
        self._stats["drain_batch_records"] += concluded
        if top_seq:
            self._advance_acked(top_seq)
        if kf_base is not None:
            self._adopt_kf_base(kf_base[0], kf_base[1])
        if wire_downgrade and concluded == 0:
            # nothing concluded: surface the downgrade so the drain
            # marks the target v1-only and retries the SAME batch
            # transcoded — never the failure path (the replica is up)
            raise _WireDowngradeError()
        if wire_downgrade:
            # a prefix concluded before the v2 wall: mark the target
            # here so the next peek already transcodes
            self._v1_until[self._target.url] = \
                self._monotonic() + self._wire_degraded_ttl
            self._stats["wire_downgrades"] += 1
        if throttle is not None:
            raise ThrottledError(throttle)
        if redirect is not None:
            raise OwnerRedirectError(*redirect)
        if concluded == 0:
            # a 200 that concluded NOTHING (hostile rows, short/empty
            # results, per-record 5xx) must not read as success — the
            # drain would re-peek the identical batch and spin. The
            # failure path's backoff sets the retry pace instead.
            raise http.client.HTTPException(
                "batch response concluded no records")
        return top_seq

    def _log_drop(self, err: Exception) -> None:
        # rate-limit to one warning per 30 s of MONOTONIC time (not sample
        # time: a stalled or skewed monitor clock must not suppress the
        # operator's only signal that reports are failing)
        now = self._monotonic()
        if self._drop_logged is None or now - self._drop_logged >= 30.0:
            self._drop_logged = now
            log.warning("fleet report send failed (aggregator unreachable "
                        "or rejecting): %s", err)
