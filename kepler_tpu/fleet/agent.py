"""Fleet agent: streams per-window feature rows to the cluster aggregator.

The node-side half of the DCN plane (SURVEY §5 "distributed communication
backend"): subscribes to the monitor's raw window samples, serializes them
(``fleet.wire``), and POSTs to the aggregator's ``/v1/report``. The node's
own Prometheus exporter is untouched — the aggregator is an *additional*
consumer, exactly as Prometheus scrape is in the reference.

Failure model mirrors the reference's degrade-gracefully stance: an
unreachable aggregator never blocks or kills the node monitor. Samples
queue in a small ring (newest wins) and drop with a rate-limited warning —
the aggregator pads/masks missing nodes out of the batch anyway.
"""

from __future__ import annotations

import base64
import collections
import http.client
import logging
import socket
import ssl
import threading
import urllib.parse
import uuid

from kepler_tpu.fleet.wire import encode_report
from kepler_tpu.monitor.monitor import PowerMonitor, WindowSample
from kepler_tpu.parallel.fleet import MODE_RATIO, NodeReport
from kepler_tpu.service.lifecycle import CancelContext

log = logging.getLogger("kepler.fleet.agent")


class FleetAgent:
    def __init__(
        self,
        monitor: PowerMonitor,
        endpoint: str,
        node_name: str = "",
        mode: int = MODE_RATIO,
        timeout_s: float = 2.0,
        queue_max: int = 8,
        tls_skip_verify: bool = False,
    ) -> None:
        self._monitor = monitor
        self._endpoint = endpoint
        self._node_name = node_name or socket.gethostname()
        self._mode = mode
        self._timeout = timeout_s
        self._queue: collections.deque[WindowSample] = collections.deque(
            maxlen=queue_max)
        self._wake = threading.Event()
        self._seq = 0
        self._run_nonce = uuid.uuid4().hex[:16]  # identifies this agent run
        self._drop_logged = 0.0
        u = urllib.parse.urlsplit(endpoint if "//" in endpoint
                                  else f"http://{endpoint}")
        if not u.hostname or not u.port:
            raise ValueError(
                f"aggregator endpoint needs host:port, got {endpoint!r}")
        self._host, self._port = u.hostname, u.port
        self._path = (u.path.rstrip("/") or "") + "/v1/report"
        self._tls = u.scheme == "https"
        # aggregator behind basic auth (webconfig.py): credentials ride in
        # the endpoint URL userinfo — https://user:pw@agg:28283
        self._auth_header = ""
        if u.username is not None:
            creds = f"{urllib.parse.unquote(u.username)}:" \
                    f"{urllib.parse.unquote(u.password or '')}"
            self._auth_header = "Basic " + base64.b64encode(
                creds.encode()).decode()
            if not self._tls:
                log.warning(
                    "aggregator endpoint has basic-auth credentials but no "
                    "https:// scheme — the Authorization header will go over "
                    "the wire in cleartext")
        # fixed for the agent's lifetime → build the TLS context once, not
        # per report send
        self._tls_ctx = None
        if self._tls:
            self._tls_ctx = ssl.create_default_context()
            if tls_skip_verify:
                self._tls_ctx.check_hostname = False
                self._tls_ctx.verify_mode = ssl.CERT_NONE

    def name(self) -> str:
        return "fleet-agent"

    def init(self) -> None:
        self._monitor.add_window_listener(self._on_window)
        log.info("fleet agent: node=%s → %s://%s:%d%s%s",
                 self._node_name, "https" if self._tls else "http",
                 self._host, self._port, self._path,
                 " (basic auth)" if self._auth_header else "")

    def _on_window(self, sample: WindowSample) -> None:
        # runs inside the monitor's refresh lock: enqueue only
        self._queue.append(sample)
        self._wake.set()

    def run(self, ctx: CancelContext) -> None:
        while not ctx.cancelled():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            while self._queue:
                sample = self._queue.popleft()
                try:
                    self._send(sample)
                except (OSError, http.client.HTTPException) as err:
                    self._log_drop(sample, err)
            if ctx.wait(0.0):
                return

    def shutdown(self) -> None:
        self._wake.set()

    # -- internals ---------------------------------------------------------

    def _send(self, sample: WindowSample) -> None:
        batch = sample.batch
        report = NodeReport(
            node_name=self._node_name,
            zone_deltas_uj=sample.zone_deltas_uj,
            zone_valid=sample.zone_valid,
            usage_ratio=sample.usage_ratio,
            cpu_deltas=batch.cpu_deltas,
            workload_ids=list(batch.ids),
            node_cpu_delta=batch.node_cpu_delta,
            dt_s=sample.dt_s,
            mode=self._mode,
            workload_kinds=batch.kinds,
        )
        self._seq += 1
        body = encode_report(report, list(sample.zone_names), seq=self._seq,
                             run=self._run_nonce)
        if self._tls:
            conn = http.client.HTTPSConnection(
                self._host, self._port, timeout=self._timeout,
                context=self._tls_ctx)
        else:
            conn = http.client.HTTPConnection(self._host, self._port,
                                              timeout=self._timeout)
        headers = {"Content-Type": "application/octet-stream"}
        if self._auth_header:
            headers["Authorization"] = self._auth_header
        try:
            conn.request("POST", self._path, body=body, headers=headers)
            resp = conn.getresponse()
            resp.read()
            if resp.status >= 300:
                raise http.client.HTTPException(
                    f"aggregator returned {resp.status}")
        finally:
            conn.close()

    def _log_drop(self, sample: WindowSample, err: Exception) -> None:
        # rate-limit to one warning per 30 s of sample time so a down
        # aggregator doesn't flood the node's logs every interval
        if sample.timestamp - self._drop_logged >= 30.0:
            self._drop_logged = sample.timestamp
            log.warning("dropping fleet report (aggregator unreachable): %s",
                        err)
