"""Fleet agent: streams per-window feature rows to the cluster aggregator.

The node-side half of the DCN plane (SURVEY §5 "distributed communication
backend"): subscribes to the monitor's raw window samples, serializes them
(``fleet.wire``), and POSTs to the aggregator's ``/v1/report``. The node's
own Prometheus exporter is untouched — the aggregator is an *additional*
consumer, exactly as Prometheus scrape is in the reference.

Failure model (reference degrade-gracefully stance, hardened): an
unreachable aggregator never blocks or kills the node monitor. Samples
queue in a small ring (newest wins); the send path reuses one persistent
connection, retries with exponential backoff + jitter, and a circuit
breaker sheds sends entirely while open so a dead aggregator costs the
node one failed probe per cooldown instead of a connect timeout per
window. Breaker state is surfaced through :meth:`health` for the API
server's ``/healthz``. Fault-injection points (``kepler_tpu.fault``) cover
the whole path: connect refusal, slow sends, body corruption, clock skew.
"""

from __future__ import annotations

# keplint: monotonic-only — backoff/breaker/rate-limit math must survive
# NTP steps; wall time only via the injected clock seam (sent_at).

import base64
import collections
import http.client
import logging
import random
import socket
import ssl
import threading
import time as _time
import urllib.parse
import uuid
from typing import Callable

from kepler_tpu import fault
from kepler_tpu.fleet.wire import encode_report
from kepler_tpu.monitor.monitor import PowerMonitor, WindowSample
from kepler_tpu.parallel.fleet import MODE_RATIO, NodeReport
from kepler_tpu.service.lifecycle import CancelContext, backoff_with_jitter

log = logging.getLogger("kepler.fleet.agent")

# circuit-breaker states (health()["breaker"])
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half-open"


class AggregatorRejectedError(http.client.HTTPException):
    """4xx from the aggregator: the delivery path is HEALTHY, this payload
    is permanently rejected (skew, auth, size, malformed). Retrying would
    fail forever and tripping the breaker would shed GOOD reports from an
    aggregator that is demonstrably up — so the drain loop drops the
    sample instead."""

    def __init__(self, status: int) -> None:
        super().__init__(f"aggregator rejected report: {status}")
        self.status = status


class FleetAgent:
    def __init__(
        self,
        monitor: PowerMonitor,
        endpoint: str,
        node_name: str = "",
        mode: int = MODE_RATIO,
        timeout_s: float = 2.0,
        queue_max: int = 8,
        tls_skip_verify: bool = False,
        backoff_initial: float = 0.1,
        backoff_max: float = 5.0,
        breaker_threshold: int = 5,
        breaker_cooldown: float = 10.0,
        flush_timeout_s: float = 2.0,
        clock: Callable[[], float] | None = None,
        monotonic: Callable[[], float] | None = None,
        jitter_seed: int | None = None,
    ) -> None:
        self._monitor = monitor
        self._endpoint = endpoint
        self._node_name = node_name or socket.gethostname()
        self._mode = mode
        self._timeout = timeout_s
        self._queue: collections.deque[WindowSample] = collections.deque(
            maxlen=queue_max)
        self._wake = threading.Event()
        self._seq = 0
        self._run_nonce = uuid.uuid4().hex[:16]  # identifies this agent run
        self._clock = clock or _time.time
        self._monotonic = monotonic or _time.monotonic
        self._drop_logged: float | None = None  # monotonic of last warning
        # retry/backoff + circuit breaker (jitter is seeded so resilience
        # tests replay the exact same schedule)
        self._backoff_initial = max(backoff_initial, 1e-3)
        self._backoff_max = max(backoff_max, self._backoff_initial)
        self._breaker_threshold = max(1, breaker_threshold)
        self._breaker_cooldown = max(breaker_cooldown, 1e-3)
        self._flush_timeout = max(0.0, flush_timeout_s)
        self._rng = random.Random(jitter_seed)
        self._breaker_state = BREAKER_CLOSED
        self._breaker_open_until = 0.0
        self._breaker_backoff = self._breaker_cooldown  # escalates per reopen
        self._consecutive_failures = 0
        self._inflight: WindowSample | None = None
        self._conn: http.client.HTTPConnection | None = None
        self._stats = {"sent_total": 0, "send_failures": 0,
                       "dropped_total": 0, "server_rejections": 0,
                       "connects_total": 0,
                       "breaker_opens": 0, "flushed_on_shutdown": 0}
        u = urllib.parse.urlsplit(endpoint if "//" in endpoint
                                  else f"http://{endpoint}")
        if not u.hostname or not u.port:
            raise ValueError(
                f"aggregator endpoint needs host:port, got {endpoint!r}")
        self._host, self._port = u.hostname, u.port
        self._path = (u.path.rstrip("/") or "") + "/v1/report"
        self._tls = u.scheme == "https"
        # aggregator behind basic auth (webconfig.py): credentials ride in
        # the endpoint URL userinfo — https://user:pw@agg:28283
        self._auth_header = ""
        if u.username is not None:
            creds = f"{urllib.parse.unquote(u.username)}:" \
                    f"{urllib.parse.unquote(u.password or '')}"
            self._auth_header = "Basic " + base64.b64encode(
                creds.encode()).decode()
            if not self._tls:
                log.warning(
                    "aggregator endpoint has basic-auth credentials but no "
                    "https:// scheme — the Authorization header will go over "
                    "the wire in cleartext")
        # fixed for the agent's lifetime → build the TLS context once, not
        # per report send
        self._tls_ctx = None
        if self._tls:
            self._tls_ctx = ssl.create_default_context()
            if tls_skip_verify:
                self._tls_ctx.check_hostname = False
                self._tls_ctx.verify_mode = ssl.CERT_NONE

    def name(self) -> str:
        return "fleet-agent"

    def init(self) -> None:
        self._monitor.add_window_listener(self._on_window)
        log.info("fleet agent: node=%s → %s://%s:%d%s%s",
                 self._node_name, "https" if self._tls else "http",
                 self._host, self._port, self._path,
                 " (basic auth)" if self._auth_header else "")

    def _on_window(self, sample: WindowSample) -> None:
        # runs inside the monitor's refresh lock: enqueue only. A full
        # ring drops its oldest sample (newest wins) — account for it so
        # prolonged outages are visible in health()/metrics.
        if len(self._queue) == self._queue.maxlen:
            self._stats["dropped_total"] += 1
        self._queue.append(sample)
        self._wake.set()

    def run(self, ctx: CancelContext) -> None:
        while not ctx.cancelled():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            self._drain(ctx)
            if ctx.wait(0.0):
                return

    def shutdown(self) -> None:
        self._wake.set()
        # best-effort final flush: a clean node drain delivers its queued
        # window(s) instead of abandoning them. Bounded by flush_timeout_s
        # and skipped while the breaker is open (aggregator presumed down).
        if self._breaker_state != BREAKER_OPEN:
            deadline = self._monotonic() + self._flush_timeout
            while ((self._inflight is not None or self._queue)
                   and self._monotonic() < deadline):
                sample = self._inflight
                if sample is None:
                    sample = self._queue.popleft()
                self._inflight = sample
                try:
                    self._send(sample)
                except AggregatorRejectedError as err:
                    # this one sample is unacceptable; the rest may flush
                    self._inflight = None
                    self._stats["dropped_total"] += 1
                    self._stats["server_rejections"] += 1
                    log.info("shutdown flush: report rejected (%s)", err)
                    continue
                except (OSError, http.client.HTTPException) as err:
                    log.info("shutdown flush stopped (%d left): %s",
                             len(self._queue) + 1, err)
                    break
                self._inflight = None
                self._stats["sent_total"] += 1
                self._stats["flushed_on_shutdown"] += 1
        self._close_conn()

    def health(self) -> dict:
        """Probe for the API server's /healthz (server.health registry)."""
        return {
            "ok": self._breaker_state != BREAKER_OPEN,
            "breaker": self._breaker_state,
            "consecutive_failures": self._consecutive_failures,
            "queued": len(self._queue),
            **self._stats,
        }

    # -- internals ---------------------------------------------------------

    def _drain(self, ctx: CancelContext | None) -> None:
        """Send queued samples, honoring breaker state and backoff.

        Closed: send with exponential-backoff retries; `breaker_threshold`
        consecutive failures open the breaker. Open: shed (no connection
        attempts) until the cooldown elapses, then half-open. Half-open:
        one probe send — success closes the breaker, failure re-opens it
        with a doubled (capped) cooldown.
        """
        while not (ctx is not None and ctx.cancelled()):
            now = self._monotonic()
            if (self._breaker_state == BREAKER_OPEN
                    and now < self._breaker_open_until):
                return  # shedding: samples stay in the newest-wins ring
            sample = self._inflight
            if sample is None:
                # an elapsed-cooldown breaker stays OPEN until a sample
                # exists to probe with: health must not report recovery
                # that nothing demonstrated
                if not self._queue:
                    return
                sample = self._queue.popleft()
                self._inflight = sample
            if self._breaker_state == BREAKER_OPEN:
                self._breaker_state = BREAKER_HALF_OPEN
                log.info("circuit breaker half-open: probing aggregator")
            try:
                self._send(sample)
            except AggregatorRejectedError as err:
                # the aggregator ANSWERED: delivery is healthy, this
                # payload will never be accepted — drop it and count the
                # response as breaker-closing evidence (retrying a 4xx
                # forever would shed good reports from a live aggregator)
                self._inflight = None
                self._stats["dropped_total"] += 1
                self._stats["server_rejections"] += 1
                self._log_drop(err)
                self._note_send_success()
                continue
            except (OSError, http.client.HTTPException) as err:
                self._on_send_failure(err)
                if self._breaker_state == BREAKER_OPEN:
                    return
                # closed, below threshold: retry after backoff with jitter
                delay = self._backoff_delay()
                if ctx is None or ctx.wait(delay):
                    return
                continue
            self._inflight = None
            self._stats["sent_total"] += 1
            self._note_send_success()

    def _note_send_success(self) -> None:
        """The aggregator responded — close the breaker, reset schedules."""
        if self._breaker_state != BREAKER_CLOSED:
            log.info("circuit breaker closed: aggregator recovered")
        self._breaker_state = BREAKER_CLOSED
        self._consecutive_failures = 0
        self._breaker_backoff = self._breaker_cooldown

    def _on_send_failure(self, err: Exception) -> None:
        self._stats["send_failures"] += 1
        self._consecutive_failures += 1
        self._log_drop(err)
        half_open = self._breaker_state == BREAKER_HALF_OPEN
        if (half_open
                or self._consecutive_failures >= self._breaker_threshold):
            if half_open:
                # failed probe: double the cooldown, capped — but never
                # below the operator-configured base cooldown
                self._breaker_backoff = min(
                    self._breaker_backoff * 2,
                    max(60.0, self._breaker_cooldown))
            self._breaker_state = BREAKER_OPEN
            self._breaker_open_until = (self._monotonic()
                                        + self._breaker_backoff)
            self._stats["breaker_opens"] += 1
            # shed the in-flight sample too — by reopen time it is stale
            if self._inflight is not None:
                self._inflight = None
                self._stats["dropped_total"] += 1
            log.warning("circuit breaker open for %.1fs after %d "
                        "consecutive send failures: %s",
                        self._breaker_backoff,
                        self._consecutive_failures, err)

    def _backoff_delay(self) -> float:
        return backoff_with_jitter(self._backoff_initial, self._backoff_max,
                                   self._consecutive_failures, self._rng)

    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is not None:
            return self._conn
        if self._tls:
            conn: http.client.HTTPConnection = http.client.HTTPSConnection(
                self._host, self._port, timeout=self._timeout,
                context=self._tls_ctx)
        else:
            conn = http.client.HTTPConnection(self._host, self._port,
                                              timeout=self._timeout)
        self._conn = conn
        self._stats["connects_total"] += 1
        return conn

    def _close_conn(self) -> None:
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _send(self, sample: WindowSample) -> None:
        spec = fault.fire("net.refuse")
        if spec is not None:
            self._close_conn()
            raise ConnectionRefusedError("fault-injected connect refusal")
        spec = fault.fire("net.slow")
        if spec is not None:
            _time.sleep(min(spec.arg or 0.05, self._timeout))
        batch = sample.batch
        report = NodeReport(
            node_name=self._node_name,
            zone_deltas_uj=sample.zone_deltas_uj,
            zone_valid=sample.zone_valid,
            usage_ratio=sample.usage_ratio,
            cpu_deltas=batch.cpu_deltas,
            workload_ids=list(batch.ids),
            node_cpu_delta=batch.node_cpu_delta,
            dt_s=sample.dt_s,
            mode=self._mode,
            workload_kinds=batch.kinds,
        )
        self._seq += 1
        sent_at = self._clock()
        spec = fault.fire("report.clock_skew")
        if spec is not None:
            sent_at += spec.arg if spec.arg is not None else 300.0
        body = encode_report(report, list(sample.zone_names), seq=self._seq,
                             run=self._run_nonce, sent_at=sent_at)
        spec = fault.fire("net.corrupt_body")
        if spec is not None:
            # drop the tail: header (and node name) stay parseable, the
            # array manifest overruns → deterministic WireError server-side
            body = body[:-4]
        headers = {"Content-Type": "application/octet-stream"}
        if self._auth_header:
            headers["Authorization"] = self._auth_header
        conn = self._connection()
        try:
            conn.request("POST", self._path, body=body, headers=headers)
            resp = conn.getresponse()
            resp.read()
        except Exception:
            # a dead persistent connection is not reusable — reconnect on
            # the next attempt
            self._close_conn()
            raise
        if resp.status >= 300 or resp.will_close:
            self._close_conn()
        if 400 <= resp.status < 500:
            raise AggregatorRejectedError(resp.status)
        if resp.status >= 300:
            raise http.client.HTTPException(
                f"aggregator returned {resp.status}")

    def _log_drop(self, err: Exception) -> None:
        # rate-limit to one warning per 30 s of MONOTONIC time (not sample
        # time: a stalled or skewed monitor clock must not suppress the
        # operator's only signal that reports are failing)
        now = self._monotonic()
        if self._drop_logged is None or now - self._drop_logged >= 30.0:
            self._drop_logged = now
            log.warning("fleet report send failed (aggregator unreachable "
                        "or rejecting): %s", err)
