"""Fleet plane: node agents → cluster aggregator over DCN.

The reference's only aggregation plane is Prometheus scrape (SURVEY §2
checklist); this package adds the TPU-native one from BASELINE.json: agents
stream per-window feature rows (``wire`` format) to an ``Aggregator`` that
attributes the whole fleet as one sharded device program and scatters watts
back per node.
"""

from kepler_tpu.fleet.agent import FleetAgent
from kepler_tpu.fleet.aggregator import Aggregator
from kepler_tpu.fleet.ring import HashRing
from kepler_tpu.fleet.scoreboard import FleetScoreboard
from kepler_tpu.fleet.spool import Spool
from kepler_tpu.fleet.wire import (
    WireError,
    decode_report,
    encode_report,
)

__all__ = [
    "Aggregator",
    "FleetAgent",
    "FleetScoreboard",
    "HashRing",
    "Spool",
    "WireError",
    "decode_report",
    "encode_report",
]
