"""Fleet plane: node agents → cluster aggregator over DCN.

The reference's only aggregation plane is Prometheus scrape (SURVEY §2
checklist); this package adds the TPU-native one from BASELINE.json: agents
stream per-window feature rows (``wire`` format) to an ``Aggregator`` that
attributes the whole fleet as one sharded device program and scatters watts
back per node.
"""

from kepler_tpu.fleet.agent import FleetAgent
from kepler_tpu.fleet.aggregator import Aggregator
from kepler_tpu.fleet.membership import (
    AutoscaleDecision,
    AutoscalePolicy,
    AutoscaleSignals,
    CoordinatorLease,
    MembershipError,
    elect_successor,
    plan_succession,
)
from kepler_tpu.fleet.ring import HashRing
from kepler_tpu.fleet.scoreboard import FleetScoreboard
from kepler_tpu.fleet.spool import Spool
from kepler_tpu.fleet.wire import (
    WireError,
    decode_report,
    encode_report,
)

__all__ = [
    "Aggregator",
    "AutoscaleDecision",
    "AutoscalePolicy",
    "AutoscaleSignals",
    "CoordinatorLease",
    "FleetAgent",
    "FleetScoreboard",
    "HashRing",
    "MembershipError",
    "Spool",
    "WireError",
    "decode_report",
    "elect_successor",
    "encode_report",
    "plan_succession",
]
