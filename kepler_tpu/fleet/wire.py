"""Fleet wire format: NodeReport ⇄ bytes.

The reference has no inter-node plane (SURVEY §2 checklist — Prometheus
scrape is its only aggregation path); this framework adds a DCN leg: node
agents stream per-window feature rows to the cluster aggregator, which
batches them into the `[nodes × pods × features]` tensor (BASELINE.json
north star).

Format (version 1): a fixed magic, a length-prefixed JSON header (names,
scalars, array manifest), then the raw little-endian array bytes in
manifest order. No pickle anywhere — payloads arrive over the network and
are treated as untrusted: dtypes come from a whitelist, every length is
bounds-checked before allocation.
"""

from __future__ import annotations

import json
import struct
from typing import Any

import numpy as np

from kepler_tpu.parallel.fleet import NodeReport

MAGIC = b"KTPUFL1\n"
_HEADER_LEN = struct.Struct("<I")
MAX_HEADER_BYTES = 16 << 20
MAX_ARRAY_BYTES = 256 << 20
# batch envelope (ISSUE 12 batched spool drain): a length-prefixed
# multi-report request so recovery replay ships K spooled records per
# POST instead of one. Each inner record is a full encode_report payload
# — no per-record format fork, and the aggregator runs each through the
# SAME single-report ingest (per-record dedup, quarantine, admission).
BATCH_MAGIC = b"KTPUFB1\n"
_BATCH_COUNT = struct.Struct("<I")
_RECORD_LEN = struct.Struct("<I")
MAX_BATCH_RECORDS = 1024
# node names become Prometheus label values, scoreboard/tracker keys, and
# log fields; the cap matches the scoreboard's name_cap so one contract
# bounds every store keyed on the name
MAX_NODE_NAME = 128


# keplint: sanitizes — the chokepoint that launders a wire-derived node
# name: printable ASCII only (newlines would forge log lines; control
# bytes corrupt label values), length-capped so hostile names can't mint
# unbounded store keys / metric series
def sanitize_node_name(name: str) -> str:
    cleaned = "".join(c for c in name[:MAX_NODE_NAME]
                      if " " <= c <= "\x7e")
    return cleaned.strip()

_DTYPES = {"float32": np.float32, "float64": np.float64,
           "int8": np.int8, "int32": np.int32, "bool": np.bool_}


def encode_report(report: NodeReport, zone_names: list[str],
                  seq: int = 0, run: str = "",
                  sent_at: float | None = None,
                  trace_id: str = "",
                  emitted_at: float | None = None) -> bytes:
    """Serialize one node's window for the POST /v1/report body.

    ``sent_at`` (agent wall clock, seconds) lets the aggregator detect
    clock-skewed senders; omitted for pre-skew-check agents.
    ``trace_id``/``emitted_at`` open the per-window delivery trace: the
    agent stamps both at WINDOW time (emit), the aggregator closes the
    trace at merge and observes ``received - emitted_at`` into
    ``kepler_fleet_delivery_latency_seconds``. Omitted by pre-telemetry
    agents — the aggregator then simply records no observation."""
    arrays: list[tuple[str, np.ndarray]] = [
        ("zone_deltas_uj", np.ascontiguousarray(
            report.zone_deltas_uj, np.float32)),
        ("zone_valid", np.ascontiguousarray(report.zone_valid, np.bool_)),
        ("cpu_deltas", np.ascontiguousarray(report.cpu_deltas, np.float32)),
    ]
    if report.workload_kinds is not None:
        arrays.append(("workload_kinds", np.ascontiguousarray(
            report.workload_kinds, np.int8)))
    header: dict[str, Any] = {
        "v": 1,
        "seq": seq,
        # per-agent-run nonce: lets the aggregator tell a restarted agent
        # re-sending the same seq value apart from a retransmission
        "run": run,
        "node_name": report.node_name,
        "zone_names": list(zone_names),
        "usage_ratio": float(report.usage_ratio),
        "node_cpu_delta": float(report.node_cpu_delta),
        "dt_s": float(report.dt_s),
        "mode": int(report.mode),
        "workload_ids": list(report.workload_ids),
        "meta": dict(report.meta),
        "arrays": [
            {"name": n, "dtype": str(a.dtype), "shape": list(a.shape)}
            for n, a in arrays
        ],
    }
    if sent_at is not None:
        header["sent_at"] = float(sent_at)
    if trace_id:
        header["trace"] = str(trace_id)
    if emitted_at is not None:
        header["emitted_at"] = float(emitted_at)
    header_bytes = json.dumps(header, separators=(",", ":")).encode()
    parts = [MAGIC, _HEADER_LEN.pack(len(header_bytes)), header_bytes]
    parts += [a.tobytes() for _, a in arrays]
    return b"".join(parts)


class WireError(ValueError):
    pass


def encode_report_batch(payloads: "list[bytes]") -> bytes:
    """Wrap encoded report payloads in the batch envelope for
    ``POST /v1/reports`` (batched spool drain). Bounded: callers must
    keep batches within :data:`MAX_BATCH_RECORDS`."""
    if not payloads:
        raise WireError("empty report batch")
    if len(payloads) > MAX_BATCH_RECORDS:
        raise WireError(
            f"batch of {len(payloads)} exceeds {MAX_BATCH_RECORDS}")
    parts = [BATCH_MAGIC, _BATCH_COUNT.pack(len(payloads))]
    for p in payloads:
        parts.append(_RECORD_LEN.pack(len(p)))
        parts.append(p)
    return b"".join(parts)


def decode_report_batch(data: bytes) -> "list[bytes]":
    """Split a batch envelope into its per-record payloads (each still
    an opaque ``encode_report`` blob the caller decodes individually).
    The payload arrives over the network: every length is bounds-checked
    before a slice, the record count is capped, and trailing garbage is
    rejected — a malformed envelope is a :class:`WireError`, never an
    allocation or an index error."""
    if len(data) < len(BATCH_MAGIC) + _BATCH_COUNT.size:
        raise WireError("short batch payload")
    if data[: len(BATCH_MAGIC)] != BATCH_MAGIC:
        raise WireError("bad batch magic")
    off = len(BATCH_MAGIC)
    (count,) = _BATCH_COUNT.unpack_from(data, off)
    off += _BATCH_COUNT.size
    if count < 1 or count > MAX_BATCH_RECORDS:
        raise WireError(f"batch count {count} out of range "
                        f"[1, {MAX_BATCH_RECORDS}]")
    out: list[bytes] = []
    for i in range(count):
        if off + _RECORD_LEN.size > len(data):
            raise WireError(f"batch record {i} truncated")
        (rlen,) = _RECORD_LEN.unpack_from(data, off)
        off += _RECORD_LEN.size
        if rlen > MAX_HEADER_BYTES + MAX_ARRAY_BYTES \
                or off + rlen > len(data):
            raise WireError(f"batch record {i} overruns payload")
        out.append(data[off: off + rlen])
        off += rlen
    if off != len(data):
        raise WireError("trailing bytes after batch records")
    return out


# keplint: sanitizes — the node name is laundered through
# sanitize_node_name before it leaves; path/mode collapse to a bounded
# enum, so nothing here can mint hostile store keys or labels
def peek_routing(data: bytes) -> tuple[str, str, int]:
    """Best-effort ``(node_name, delivery_path, mode)`` from a payload —
    the admission controller's pre-decode priority inputs. The name is
    sanitized, the path clamped to ``fresh``/``replay``, the mode to a
    plain int. Never raises; garbage reads as the HIGHEST priority
    class (``("", "fresh", 0)``) so a mangled header is judged by the
    real decode, not shed on a guess."""
    try:
        if data[: len(MAGIC)] != MAGIC:
            return "", "fresh", 0
        off = len(MAGIC)
        (hlen,) = _HEADER_LEN.unpack_from(data, off)
        off += _HEADER_LEN.size
        if hlen > MAX_HEADER_BYTES or off + hlen > len(data):
            return "", "fresh", 0
        header = json.loads(data[off: off + hlen])
        if not isinstance(header, dict):
            return "", "fresh", 0
        name = header.get("node_name")
        name = sanitize_node_name(name) if isinstance(name, str) else ""
        path = ("replay" if header.get("delivery_path") == "replay"
                else "fresh")
        mode = header.get("mode")
        if isinstance(mode, bool) or not isinstance(mode, int):
            mode = 0
        return name, path, mode
    except Exception:
        return "", "fresh", 0


def restamp_transmit(data: bytes, sent_at: float,
                     delivery_path: str | None = None,
                     appended_at: float | None = None,
                     owner: str | None = None,
                     epoch: int | None = None,
                     acked_through: int | None = None) -> bytes:
    """Rewrite a report payload's transmit-time header fields in place.

    Spooled records (``fleet.spool``) keep their original ``run``/``seq``
    identity but must carry a TRANSMIT-time ``sent_at``: the aggregator's
    clock-skew quarantine compares ``sent_at`` against its receive time,
    so a backlog replayed hours after the window was measured would look
    like a skewed sender if the append-time stamp rode along.

    ``delivery_path`` ("fresh"/"replay") and ``appended_at`` (the spool's
    original append stamp) are transmit-time properties too — the agent
    only knows at send time whether a window waited out an outage, and
    the aggregator's delivery-latency histogram measures replays from the
    ORIGINAL append time under the ``path="replay"`` label.

    The HA-ingest ring fields are transmit-time as well: ``owner`` (the
    replica the agent believes owns it), ``epoch`` (the agent's known
    ring epoch), and ``acked_through`` (the highest seq the agent has a
    2xx for — any replica's). A spooled record replayed to a NEW owner
    after a hand-off must carry the agent's CURRENT view, not the one
    baked in at append time: ``acked_through`` is how a fresh owner's
    seq tracker seeds without fabricating a leading-gap loss spike for
    windows that were delivered to the previous owner.

    Only the JSON header is re-serialized — array bytes pass through
    untouched. Raises :class:`WireError` on a payload it cannot parse."""
    if len(data) < len(MAGIC) + _HEADER_LEN.size or \
            data[: len(MAGIC)] != MAGIC:
        raise WireError("bad magic")
    off = len(MAGIC)
    (hlen,) = _HEADER_LEN.unpack_from(data, off)
    off += _HEADER_LEN.size
    if hlen > MAX_HEADER_BYTES or off + hlen > len(data):
        raise WireError("bad header length")
    try:
        header = json.loads(data[off: off + hlen])
    except (json.JSONDecodeError, UnicodeDecodeError) as err:
        raise WireError(f"bad header json: {err}") from err
    if not isinstance(header, dict):
        raise WireError("header is not a mapping")
    header["sent_at"] = float(sent_at)
    if delivery_path is not None:
        header["delivery_path"] = str(delivery_path)
    if appended_at is not None:
        header["appended_at"] = float(appended_at)
    if owner is not None:
        header["owner"] = str(owner)
    if epoch is not None:
        header["epoch"] = int(epoch)
    if acked_through is not None:
        header["acked_through"] = int(acked_through)
    header_bytes = json.dumps(header, separators=(",", ":")).encode()
    return b"".join([MAGIC, _HEADER_LEN.pack(len(header_bytes)),
                     header_bytes, data[off + hlen:]])


def restamp_sent_at(data: bytes, sent_at: float) -> bytes:
    """Back-compat alias: rewrite only ``sent_at`` (see
    :func:`restamp_transmit`)."""
    return restamp_transmit(data, sent_at)


# keplint: taint-source — the ONLY wire accessor that skips validation
# (the body already failed decoding); callers must sanitize_node_name()
# before the peeked name touches a label, store key, or log line
def peek_node_name(data: bytes) -> str | None:
    """Best-effort node name from a (possibly malformed) payload.

    Used by the aggregator's per-node degradation accounting: when
    ``decode_report`` rejects a body, a salvageable header still tells us
    WHICH node is sending garbage. Never raises; returns None when even
    the header is unreadable."""
    try:
        if data[: len(MAGIC)] != MAGIC:
            return None
        off = len(MAGIC)
        (hlen,) = _HEADER_LEN.unpack_from(data, off)
        off += _HEADER_LEN.size
        if hlen > MAX_HEADER_BYTES or off + hlen > len(data):
            return None
        header = json.loads(data[off: off + hlen])
        name = header.get("node_name") if isinstance(header, dict) else None
        return name if isinstance(name, str) and name else None
    except Exception:
        return None


def peek_identity(data: bytes) -> tuple[str, int]:
    """Best-effort ``(run, seq)`` from a payload (``("", 0)`` when
    unreadable or absent).

    Used by the agent's delivered-watermark accounting: a spooled
    record's identity lives only in its wire header, and the agent
    needs it at ACK time to advance ``acked_through`` — scoped to the
    run, because an old run's replayed seqs say nothing about the
    current run's stream. Never raises."""
    try:
        if data[: len(MAGIC)] != MAGIC:
            return "", 0
        off = len(MAGIC)
        (hlen,) = _HEADER_LEN.unpack_from(data, off)
        off += _HEADER_LEN.size
        if hlen > MAX_HEADER_BYTES or off + hlen > len(data):
            return "", 0
        header = json.loads(data[off: off + hlen])
        if not isinstance(header, dict):
            return "", 0
        seq = header.get("seq")
        run = header.get("run")
        if isinstance(seq, bool) or not isinstance(seq, int) or seq < 0:
            seq = 0
        if not isinstance(run, str):
            run = ""
        return run, seq
    except Exception:
        return "", 0


# keplint: sanitizes — every field is validated (dtype whitelist, bounds
# checks, node-name charset/length) or the whole report is rejected, so
# decoded output is trusted downstream
def decode_report(data: bytes) -> tuple[NodeReport, dict[str, Any]]:
    """Parse a report payload → (NodeReport, header). Raises WireError on
    any malformed/oversized input."""
    if len(data) < len(MAGIC) + _HEADER_LEN.size:
        raise WireError("short payload")
    if data[: len(MAGIC)] != MAGIC:
        raise WireError("bad magic")
    off = len(MAGIC)
    (hlen,) = _HEADER_LEN.unpack_from(data, off)
    off += _HEADER_LEN.size
    if hlen > MAX_HEADER_BYTES or off + hlen > len(data):
        raise WireError("bad header length")
    try:
        header = json.loads(data[off: off + hlen])
    except (json.JSONDecodeError, UnicodeDecodeError) as err:
        raise WireError(f"bad header json: {err}") from err
    off += hlen
    if not isinstance(header, dict) or header.get("v") != 1:
        raise WireError(f"unsupported version {header.get('v')!r}")

    arrays: dict[str, np.ndarray] = {}
    for spec in header.get("arrays", []):
        name, dtype_s = spec.get("name"), spec.get("dtype")
        shape = spec.get("shape")
        if dtype_s not in _DTYPES:
            raise WireError(f"dtype {dtype_s!r} not allowed")
        if (not isinstance(shape, list) or len(shape) != 1
                or not isinstance(shape[0], int) or shape[0] < 0):
            raise WireError(f"bad shape {shape!r} for {name!r}")
        dtype = np.dtype(_DTYPES[dtype_s])
        nbytes = shape[0] * dtype.itemsize
        if nbytes > MAX_ARRAY_BYTES or off + nbytes > len(data):
            raise WireError(f"array {name!r} overruns payload")
        arrays[name] = np.frombuffer(
            data, dtype=dtype, count=shape[0], offset=off).copy()
        off += nbytes

    zone_names = header.get("zone_names")
    if (not isinstance(zone_names, list)
            or not all(isinstance(z, str) for z in zone_names)):
        raise WireError("zone_names must be a list of strings")
    raw_name = header.get("node_name")
    if not isinstance(raw_name, str):
        raise WireError("node_name must be a string")
    node_name = sanitize_node_name(raw_name)
    if not node_name or node_name != raw_name:
        # reject rather than silently rewrite: an agent sending control
        # bytes or a >128-char name is misconfigured or hostile, and a
        # rewritten identity would split its series mid-stream
        raise WireError("node_name must be 1-128 printable ASCII chars")
    try:
        n_zones = len(zone_names)
        report = NodeReport(
            node_name=node_name,
            zone_deltas_uj=arrays["zone_deltas_uj"],
            zone_valid=arrays["zone_valid"],
            usage_ratio=float(header["usage_ratio"]),
            cpu_deltas=arrays["cpu_deltas"],
            workload_ids=[str(w) for w in header["workload_ids"]],
            node_cpu_delta=float(header["node_cpu_delta"]),
            dt_s=float(header["dt_s"]),
            mode=int(header["mode"]),
            workload_kinds=arrays.get("workload_kinds"),
            meta={str(k): str(v)
                  for k, v in dict(header.get("meta", {})).items()},
        )
    except (KeyError, TypeError) as err:
        raise WireError(f"missing field: {err}") from err
    if report.zone_deltas_uj.shape != (n_zones,):
        raise WireError("zone_deltas/zone_names length mismatch")
    if report.zone_valid.shape != (n_zones,):
        raise WireError("zone_valid/zone_names length mismatch")
    if len(report.workload_ids) != len(report.cpu_deltas):
        raise WireError("workload_ids/cpu_deltas length mismatch")
    if (report.workload_kinds is not None
            and len(report.workload_kinds) != len(report.cpu_deltas)):
        raise WireError("workload_kinds/cpu_deltas length mismatch")
    return report, header
