"""Fleet wire format: NodeReport ⇄ bytes.

The reference has no inter-node plane (SURVEY §2 checklist — Prometheus
scrape is its only aggregation path); this framework adds a DCN leg: node
agents stream per-window feature rows to the cluster aggregator, which
batches them into the `[nodes × pods × features]` tensor (BASELINE.json
north star).

Two versions coexist on the wire, dispatched by magic:

* **Version 1** — a fixed magic, a length-prefixed JSON header (names,
  scalars, array manifest), then the raw little-endian array bytes in
  manifest order. Retained byte-for-byte for old agents.
* **Version 2** (ISSUE 14 ingest fast path) — a fixed-layout struct-packed
  binary header (:class:`WireLayoutV2`): every routing/identity field the
  admitted path touches (seq/run/epoch/owner/acked_through, mode, node
  name, transmit stamps) sits at a struct offset, so
  ``peek_routing``/``peek_identity``/``peek_node_name`` are O(1) reads
  off ONE :func:`parse_header` pass — no JSON anywhere on the admitted
  path. Two frame kinds:

  - **keyframe**: the full report; workload arrays decode as
    ``np.frombuffer`` VIEWS over the request body (bounds-checked, zero
    copy) shaped to land straight in ``pack_reports_into`` staging rows;
  - **delta**: only the workload rows that changed against the last
    acked keyframe (changed-index vector + packed f32 values) plus the
    per-window zone/scalar block — or, when nothing changed at all,
    ``FLAG_SAME`` and an empty payload, so an unchanged node costs one
    header parse and nothing else (the wire-side mirror of the device
    plane's delta-H2D). A delta whose base the aggregator doesn't hold
    is answered with a structured 409 needs-keyframe — resend full,
    never a failure.

No pickle anywhere — payloads arrive over the network and are treated as
untrusted: dtypes come from a whitelist, every length is bounds-checked
before allocation, and a malformed frame is a :class:`WireError`, never
a crash or an out-of-bounds write.
"""

from __future__ import annotations

import json
import math
import struct
from typing import Any

import numpy as np

from kepler_tpu.parallel.fleet import NodeReport

MAGIC = b"KTPUFL1\n"
_HEADER_LEN = struct.Struct("<I")
MAX_HEADER_BYTES = 16 << 20
MAX_ARRAY_BYTES = 256 << 20
# batch envelope (ISSUE 12 batched spool drain): a length-prefixed
# multi-report request so recovery replay ships K spooled records per
# POST instead of one. Each inner record is a full encode_report payload
# — no per-record format fork, and the aggregator runs each through the
# SAME single-report ingest (per-record dedup, quarantine, admission).
BATCH_MAGIC = b"KTPUFB1\n"
_BATCH_COUNT = struct.Struct("<I")
_RECORD_LEN = struct.Struct("<I")
MAX_BATCH_RECORDS = 1024
# node names become Prometheus label values, scoreboard/tracker keys, and
# log fields; the cap matches the scoreboard's name_cap so one contract
# bounds every store keyed on the name
MAX_NODE_NAME = 128

# v2 frame-kind flags (WireLayoutV2 fixed header, `flags` field)
FLAG_DELTA = 1  # delta frame (vs keyframe)
FLAG_KINDS = 2  # keyframe carries a workload_kinds plane
FLAG_REPLAY = 4  # delivery_path == "replay" (transmit-time restamp)
FLAG_SAME = 8  # delta with NOTHING changed: empty payload, base reused


# keplint: sanitizes — the chokepoint that launders a wire-derived node
# name: printable ASCII only (newlines would forge log lines; control
# bytes corrupt label values), length-capped so hostile names can't mint
# unbounded store keys / metric series
def sanitize_node_name(name: str) -> str:
    cleaned = "".join(c for c in name[:MAX_NODE_NAME]
                      if " " <= c <= "\x7e")
    return cleaned.strip()

_DTYPES = {"float32": np.float32, "float64": np.float64,
           "int8": np.int8, "int32": np.int32, "bool": np.bool_}


class WireError(ValueError):
    pass


# keplint: layout-definition — THE v2 frame layout, the single source of
# truth for every struct offset: encoder, decoder, restamp, and the peek
# accessors all derive from this class, so a hand-typed offset can never
# silently diverge (KTL114 forbids raw layout arithmetic outside it).
class WireLayoutV2:
    """Fixed-layout v2 frame.

    ``magic(8) | FIXED | name | run | trace | owner | pad→8`` is the
    header region (``header_len`` bytes, 8-aligned so every f32/f64
    payload offset stays aligned for zero-copy views); the payload
    region follows:

    * keyframe: ``COUNTS_KF (n_zones, n_workloads, zn_len, ids_len,
      meta_len) | zone_deltas f32[Z] | cpu_deltas f32[W] | zone_valid
      u8[Z] | kinds i8[W]? | zone_names blob | ids blob | meta blob``
    * delta: ``COUNTS_DELTA (n_zones, n_changed) | zone_deltas f32[Z] |
      zone_valid u8[Z] | pad→4 | idx i32[n] | val f32[n]`` (all absent
      under ``FLAG_SAME``)

    String blobs are sequences of u16-length-prefixed UTF-8 strings —
    still no JSON anywhere on the frame.
    """

    MAGIC = b"KTPUFL2\n"
    VERSION = 2
    # version u16, flags u16, header_len u32, seq u64, epoch u64,
    # acked_through u64, base_seq u64, mode i32, then six f64s
    # (sent_at, emitted_at, appended_at — NaN = absent — usage_ratio,
    # node_cpu_delta, dt_s), then four u16 string lengths
    # (name, run, trace, owner)
    FIXED = struct.Struct("<HHIQQQQi6d4H")
    COUNTS_KF = struct.Struct("<5I")
    COUNTS_DELTA = struct.Struct("<2I")
    STR_LEN = struct.Struct("<H")
    HDR_ALIGN = 8
    F32 = np.dtype(np.float32).itemsize
    I32 = np.dtype(np.int32).itemsize
    # field caps — every length is validated against these BEFORE any
    # slice or allocation, so hostile frames can't balloon memory
    MAX_NAME = MAX_NODE_NAME
    MAX_RUN = 128
    MAX_TRACE = 128
    MAX_OWNER = 256  # == ring.MAX_PEER_NAME
    MAX_ZONES = 4096
    MAX_WORKLOADS = 1 << 22
    MAX_BLOB = 16 << 20
    MAX_HEADER = 4096

    @classmethod
    def fixed_end(cls) -> int:
        """Offset where the var-length string block starts."""
        return len(cls.MAGIC) + cls.FIXED.size

    @classmethod
    def header_len(cls, name_b: bytes, run_b: bytes, trace_b: bytes,
                   owner_b: bytes) -> int:
        """Total 8-aligned header-region length for these strings."""
        raw = (cls.fixed_end() + len(name_b) + len(run_b) + len(trace_b)
               + len(owner_b))
        pad = (-raw) % cls.HDR_ALIGN
        return raw + pad

    @classmethod
    def pack_header(cls, *, flags: int, seq: int, epoch: int,
                    acked_through: int, base_seq: int, mode: int,
                    sent_at: float, emitted_at: float, appended_at: float,
                    usage_ratio: float, node_cpu_delta: float,
                    dt_s: float, name: str, run: str, trace: str,
                    owner: str) -> bytes:
        """Assemble the full header region (magic through pad)."""
        name_b = name.encode()
        run_b = run.encode()
        trace_b = trace.encode()
        owner_b = owner.encode()
        if len(name_b) > cls.MAX_NAME:
            raise WireError("node_name too long for v2 header")
        if len(run_b) > cls.MAX_RUN or len(trace_b) > cls.MAX_TRACE \
                or len(owner_b) > cls.MAX_OWNER:
            raise WireError("run/trace/owner too long for v2 header")
        hlen = cls.header_len(name_b, run_b, trace_b, owner_b)
        fixed = cls.FIXED.pack(
            cls.VERSION, flags, hlen, seq, epoch, acked_through,
            base_seq, mode, sent_at, emitted_at, appended_at,
            usage_ratio, node_cpu_delta, dt_s,
            len(name_b), len(run_b), len(trace_b), len(owner_b))
        blob = cls.MAGIC + fixed + name_b + run_b + trace_b + owner_b
        return blob + b"\x00" * (hlen - len(blob))


_L2 = WireLayoutV2


def encode_report(report: NodeReport, zone_names: list[str],
                  seq: int = 0, run: str = "",
                  sent_at: float | None = None,
                  trace_id: str = "",
                  emitted_at: float | None = None) -> bytes:
    """Serialize one node's window for the POST /v1/report body.

    ``sent_at`` (agent wall clock, seconds) lets the aggregator detect
    clock-skewed senders; omitted for pre-skew-check agents.
    ``trace_id``/``emitted_at`` open the per-window delivery trace: the
    agent stamps both at WINDOW time (emit), the aggregator closes the
    trace at merge and observes ``received - emitted_at`` into
    ``kepler_fleet_delivery_latency_seconds``. Omitted by pre-telemetry
    agents — the aggregator then simply records no observation."""
    arrays: list[tuple[str, np.ndarray]] = [
        ("zone_deltas_uj", np.ascontiguousarray(
            report.zone_deltas_uj, np.float32)),
        ("zone_valid", np.ascontiguousarray(report.zone_valid, np.bool_)),
        ("cpu_deltas", np.ascontiguousarray(report.cpu_deltas, np.float32)),
    ]
    if report.workload_kinds is not None:
        arrays.append(("workload_kinds", np.ascontiguousarray(
            report.workload_kinds, np.int8)))
    header: dict[str, Any] = {
        "v": 1,
        "seq": seq,
        # per-agent-run nonce: lets the aggregator tell a restarted agent
        # re-sending the same seq value apart from a retransmission
        "run": run,
        "node_name": report.node_name,
        "zone_names": list(zone_names),
        "usage_ratio": float(report.usage_ratio),
        "node_cpu_delta": float(report.node_cpu_delta),
        "dt_s": float(report.dt_s),
        "mode": int(report.mode),
        "workload_ids": list(report.workload_ids),
        "meta": dict(report.meta),
        "arrays": [
            {"name": n, "dtype": str(a.dtype), "shape": list(a.shape)}
            for n, a in arrays
        ],
    }
    if sent_at is not None:
        header["sent_at"] = float(sent_at)
    if trace_id:
        header["trace"] = str(trace_id)
    if emitted_at is not None:
        header["emitted_at"] = float(emitted_at)
    header_bytes = json.dumps(header, separators=(",", ":")).encode()
    parts = [MAGIC, _HEADER_LEN.pack(len(header_bytes)), header_bytes]
    parts += [a.tobytes() for _, a in arrays]
    return b"".join(parts)


def _pack_strs(items: "list[str]") -> bytes:
    parts: list[bytes] = []
    for s in items:
        b = str(s).encode()
        if len(b) > 0xFFFF:
            raise WireError("string too long for v2 blob")
        parts.append(_L2.STR_LEN.pack(len(b)))
        parts.append(b)
    return b"".join(parts)


def _unpack_strs(data: bytes, off: int, end: int,
                 count: "int | None") -> "list[str]":
    """Bounds-checked u16-length-prefixed string blob → list[str]. The
    blob must fill [off, end) exactly; ``count=None`` walks to the end
    instead of expecting a known string count (the meta blob)."""
    out: list[str] = []
    while (off < end) if count is None else (len(out) < count):
        if off + _L2.STR_LEN.size > end:
            raise WireError("truncated v2 string blob")
        (n,) = _L2.STR_LEN.unpack_from(data, off)
        off += _L2.STR_LEN.size
        if off + n > end:
            raise WireError("v2 string overruns its blob")
        out.append(data[off: off + n].decode("utf-8", "replace"))
        off += n
    if off != end:
        raise WireError("trailing bytes in v2 string blob")
    return out


def encode_report_v2(report: NodeReport, zone_names: list[str],
                     seq: int = 0, run: str = "",
                     sent_at: float | None = None,
                     trace_id: str = "",
                     emitted_at: float | None = None) -> bytes:
    """Serialize one node's window as a v2 KEYFRAME (binary header +
    raw little-endian arrays + length-prefixed string blobs). Field
    semantics match :func:`encode_report`; transmit-time fields (owner/
    epoch/acked_through/delivery_path/appended_at) are stamped later by
    :func:`restamp_transmit`."""
    zd = np.ascontiguousarray(report.zone_deltas_uj, np.float32)
    zv = np.ascontiguousarray(report.zone_valid, np.uint8)
    cpu = np.ascontiguousarray(report.cpu_deltas, np.float32)
    kinds = report.workload_kinds
    flags = 0
    kinds_b = b""
    if kinds is not None:
        flags |= FLAG_KINDS
        kinds_b = np.ascontiguousarray(kinds, np.int8).tobytes()
    z, w = int(zd.shape[0]), int(cpu.shape[0])
    if len(zone_names) != z:
        raise WireError("zone_names/zone_deltas length mismatch")
    zn_b = _pack_strs(list(zone_names))
    ids_b = _pack_strs(list(report.workload_ids))
    meta_items: list[str] = []
    for k, v in dict(report.meta).items():
        meta_items.append(str(k))
        meta_items.append(str(v))
    meta_b = _pack_strs(meta_items)
    header = _L2.pack_header(
        flags=flags, seq=int(seq), epoch=0, acked_through=0, base_seq=0,
        mode=int(report.mode),
        sent_at=float(sent_at) if sent_at is not None else math.nan,
        emitted_at=(float(emitted_at) if emitted_at is not None
                    else math.nan),
        appended_at=math.nan,
        usage_ratio=float(report.usage_ratio),
        node_cpu_delta=float(report.node_cpu_delta),
        dt_s=float(report.dt_s),
        name=report.node_name, run=str(run), trace=str(trace_id),
        owner="")
    counts = _L2.COUNTS_KF.pack(z, w, len(zn_b), len(ids_b), len(meta_b))
    return b"".join([header, counts, zd.tobytes(), cpu.tobytes(),
                     zv.tobytes(), kinds_b, zn_b, ids_b, meta_b])


class ParsedHeader:
    """ONE cached header parse, carried from the admission peek through
    ingest: v1 = the JSON header dict (parsed once — ``decode_report``
    reuses it); v2 = the struct fields lifted into the same dict shape,
    so every downstream consumer (skew check, identity coercion, ring
    headers, delivery-trace close) is version-blind."""

    __slots__ = ("version", "header", "flags", "base_seq", "body_off")

    def __init__(self, version: int, header: dict, flags: int,
                 base_seq: int, body_off: int) -> None:
        self.version = version
        self.header = header
        self.flags = flags
        self.base_seq = base_seq
        self.body_off = body_off

    @property
    def is_delta(self) -> bool:
        return bool(self.flags & FLAG_DELTA)

    @property
    def same(self) -> bool:
        return bool(self.flags & FLAG_SAME)

    def routing(self) -> tuple[str, str, int]:
        """Sanitized ``(node_name, delivery_path, mode)`` — the
        admission controller's priority inputs (peek_routing
        semantics)."""
        name = self.header.get("node_name")
        name = sanitize_node_name(name) if isinstance(name, str) else ""
        path = ("replay" if self.header.get("delivery_path") == "replay"
                else "fresh")
        mode = self.header.get("mode")
        if isinstance(mode, bool) or not isinstance(mode, int):
            mode = 0
        return name, path, mode

    def identity(self) -> tuple[str, int]:
        """Coerced ``(run, seq)`` (peek_identity semantics)."""
        seq = self.header.get("seq")
        run = self.header.get("run")
        if isinstance(seq, bool) or not isinstance(seq, int) or seq < 0:
            seq = 0
        if not isinstance(run, str):
            run = ""
        return run, seq


def parse_header(data: bytes) -> ParsedHeader:
    """Version-dispatched single header parse. Raises
    :class:`WireError` on anything that is not a well-formed v1 or v2
    header region (payload regions are validated by the decoders)."""
    if len(data) >= len(_L2.MAGIC) \
            and data[: len(_L2.MAGIC)] == _L2.MAGIC:
        return _parse_header_v2(data)
    if len(data) < len(MAGIC) + _HEADER_LEN.size:
        raise WireError("short payload")
    if data[: len(MAGIC)] != MAGIC:
        raise WireError("bad magic")
    off = len(MAGIC)
    (hlen,) = _HEADER_LEN.unpack_from(data, off)
    off += _HEADER_LEN.size
    if hlen > MAX_HEADER_BYTES or off + hlen > len(data):
        raise WireError("bad header length")
    try:
        header = json.loads(data[off: off + hlen])
    except (json.JSONDecodeError, UnicodeDecodeError) as err:
        raise WireError(f"bad header json: {err}") from err
    if not isinstance(header, dict):
        raise WireError("header is not a mapping")
    return ParsedHeader(1, header, 0, 0, off + hlen)


def _parse_header_v2(data: bytes) -> ParsedHeader:
    fixed_end = _L2.fixed_end()
    if len(data) < fixed_end:
        raise WireError("short v2 payload")
    (version, flags, hlen, seq, epoch, acked, base_seq, mode,
     sent_at, emitted_at, appended_at, ratio, denom, dt,
     name_len, run_len, trace_len, owner_len) = _L2.FIXED.unpack_from(
        data, len(_L2.MAGIC))
    if version != _L2.VERSION:
        raise WireError(f"unsupported wire version {version}")
    if name_len > _L2.MAX_NAME or run_len > _L2.MAX_RUN \
            or trace_len > _L2.MAX_TRACE or owner_len > _L2.MAX_OWNER:
        raise WireError("v2 header string over its cap")
    str_end = fixed_end + name_len + run_len + trace_len + owner_len
    if hlen > _L2.MAX_HEADER or hlen % _L2.HDR_ALIGN \
            or hlen < str_end or hlen > len(data):
        raise WireError("bad v2 header length")
    off = fixed_end
    name = data[off: off + name_len].decode("utf-8", "replace")
    off += name_len
    run = data[off: off + run_len].decode("utf-8", "replace")
    off += run_len
    header: dict[str, Any] = {
        "v": 2,
        "seq": seq,
        "run": run,
        "node_name": name,
        "mode": mode,
        "usage_ratio": ratio,
        "node_cpu_delta": denom,
        "dt_s": dt,
        "epoch": epoch,
        "acked_through": acked,
    }
    if trace_len:
        header["trace"] = data[off: off + trace_len].decode(
            "utf-8", "replace")
    off += trace_len
    if owner_len:
        header["owner"] = data[off: off + owner_len].decode(
            "utf-8", "replace")
    # NaN (x != x) marks an absent stamp — cheaper than math.isnan on
    # the per-record hot path
    if sent_at == sent_at:
        header["sent_at"] = sent_at
    if emitted_at == emitted_at:
        header["emitted_at"] = emitted_at
    if appended_at == appended_at:
        header["appended_at"] = appended_at
    if flags & FLAG_REPLAY:
        header["delivery_path"] = "replay"
    return ParsedHeader(2, header, flags, base_seq, hlen)


def try_parse_header(data: bytes) -> "ParsedHeader | None":
    """Best-effort :func:`parse_header` — None instead of raising (the
    peeks' never-raise contract)."""
    try:
        return parse_header(data)
    except Exception:
        return None


def encode_report_batch(payloads: "list[bytes]") -> bytes:
    """Wrap encoded report payloads in the batch envelope for
    ``POST /v1/reports`` (batched spool drain). Bounded: callers must
    keep batches within :data:`MAX_BATCH_RECORDS`."""
    if not payloads:
        raise WireError("empty report batch")
    if len(payloads) > MAX_BATCH_RECORDS:
        raise WireError(
            f"batch of {len(payloads)} exceeds {MAX_BATCH_RECORDS}")
    parts = [BATCH_MAGIC, _BATCH_COUNT.pack(len(payloads))]
    for p in payloads:
        parts.append(_RECORD_LEN.pack(len(p)))
        parts.append(p)
    return b"".join(parts)


def decode_report_batch(data: bytes) -> "list[bytes]":
    """Split a batch envelope into its per-record payloads (each still
    an opaque ``encode_report`` blob the caller decodes individually).
    The payload arrives over the network: every length is bounds-checked
    before a slice, the record count is capped, and trailing garbage is
    rejected — a malformed envelope is a :class:`WireError`, never an
    allocation or an index error."""
    if len(data) < len(BATCH_MAGIC) + _BATCH_COUNT.size:
        raise WireError("short batch payload")
    if data[: len(BATCH_MAGIC)] != BATCH_MAGIC:
        raise WireError("bad batch magic")
    off = len(BATCH_MAGIC)
    (count,) = _BATCH_COUNT.unpack_from(data, off)
    off += _BATCH_COUNT.size
    if count < 1 or count > MAX_BATCH_RECORDS:
        raise WireError(f"batch count {count} out of range "
                        f"[1, {MAX_BATCH_RECORDS}]")
    out: list[bytes] = []
    for i in range(count):
        if off + _RECORD_LEN.size > len(data):
            raise WireError(f"batch record {i} truncated")
        (rlen,) = _RECORD_LEN.unpack_from(data, off)
        off += _RECORD_LEN.size
        if rlen > MAX_HEADER_BYTES + MAX_ARRAY_BYTES \
                or off + rlen > len(data):
            raise WireError(f"batch record {i} overruns payload")
        out.append(data[off: off + rlen])
        off += rlen
    if off != len(data):
        raise WireError("trailing bytes after batch records")
    return out


# keplint: sanitizes — the node name is laundered through
# sanitize_node_name before it leaves; path/mode collapse to a bounded
# enum, so nothing here can mint hostile store keys or labels
def peek_routing(data: bytes) -> tuple[str, str, int]:
    """Best-effort ``(node_name, delivery_path, mode)`` from a payload —
    the admission controller's pre-decode priority inputs. The name is
    sanitized, the path clamped to ``fresh``/``replay``, the mode to a
    plain int. Never raises; garbage reads as the HIGHEST priority
    class (``("", "fresh", 0)``) so a mangled header is judged by the
    real decode, not shed on a guess."""
    parsed = try_parse_header(data)
    if parsed is None:
        return "", "fresh", 0
    try:
        return parsed.routing()
    except Exception:
        return "", "fresh", 0


def restamp_transmit(data: bytes, sent_at: float,
                     delivery_path: str | None = None,
                     appended_at: float | None = None,
                     owner: str | None = None,
                     epoch: int | None = None,
                     acked_through: int | None = None) -> bytes:
    """Rewrite a report payload's transmit-time header fields in place.

    Spooled records (``fleet.spool``) keep their original ``run``/``seq``
    identity but must carry a TRANSMIT-time ``sent_at``: the aggregator's
    clock-skew quarantine compares ``sent_at`` against its receive time,
    so a backlog replayed hours after the window was measured would look
    like a skewed sender if the append-time stamp rode along.

    ``delivery_path`` ("fresh"/"replay") and ``appended_at`` (the spool's
    original append stamp) are transmit-time properties too — the agent
    only knows at send time whether a window waited out an outage, and
    the aggregator's delivery-latency histogram measures replays from the
    ORIGINAL append time under the ``path="replay"`` label.

    The HA-ingest ring fields are transmit-time as well: ``owner`` (the
    replica the agent believes owns it), ``epoch`` (the agent's known
    ring epoch), and ``acked_through`` (the highest seq the agent has a
    2xx for — any replica's). A spooled record replayed to a NEW owner
    after a hand-off must carry the agent's CURRENT view, not the one
    baked in at append time: ``acked_through`` is how a fresh owner's
    seq tracker seeds without fabricating a leading-gap loss spike for
    windows that were delivered to the previous owner.

    Only the header region is re-serialized — array/payload bytes pass
    through untouched on BOTH versions. Raises :class:`WireError` on a
    payload it cannot parse."""
    if len(data) >= len(_L2.MAGIC) \
            and data[: len(_L2.MAGIC)] == _L2.MAGIC:
        return _restamp_v2(data, sent_at, delivery_path, appended_at,
                           owner, epoch, acked_through)
    if len(data) < len(MAGIC) + _HEADER_LEN.size or \
            data[: len(MAGIC)] != MAGIC:
        raise WireError("bad magic")
    off = len(MAGIC)
    (hlen,) = _HEADER_LEN.unpack_from(data, off)
    off += _HEADER_LEN.size
    if hlen > MAX_HEADER_BYTES or off + hlen > len(data):
        raise WireError("bad header length")
    try:
        header = json.loads(data[off: off + hlen])
    except (json.JSONDecodeError, UnicodeDecodeError) as err:
        raise WireError(f"bad header json: {err}") from err
    if not isinstance(header, dict):
        raise WireError("header is not a mapping")
    header["sent_at"] = float(sent_at)
    if delivery_path is not None:
        header["delivery_path"] = str(delivery_path)
    if appended_at is not None:
        header["appended_at"] = float(appended_at)
    if owner is not None:
        header["owner"] = str(owner)
    if epoch is not None:
        header["epoch"] = int(epoch)
    if acked_through is not None:
        header["acked_through"] = int(acked_through)
    header_bytes = json.dumps(header, separators=(",", ":")).encode()
    return b"".join([MAGIC, _HEADER_LEN.pack(len(header_bytes)),
                     header_bytes, data[off + hlen:]])


def _restamp_v2(data: bytes, sent_at: float,
                delivery_path: str | None, appended_at: float | None,
                owner: str | None, epoch: int | None,
                acked_through: int | None) -> bytes:
    parsed = _parse_header_v2(data)
    hdr = parsed.header
    flags = parsed.flags
    if delivery_path is not None:
        if delivery_path == "replay":
            flags |= FLAG_REPLAY
        else:
            flags &= ~FLAG_REPLAY
    prev_appended = hdr.get("appended_at")
    prev_emitted = hdr.get("emitted_at")
    header = _L2.pack_header(
        flags=flags, seq=hdr["seq"],
        epoch=int(epoch) if epoch is not None else hdr["epoch"],
        acked_through=(int(acked_through) if acked_through is not None
                       else hdr["acked_through"]),
        base_seq=parsed.base_seq, mode=hdr["mode"],
        sent_at=float(sent_at),
        emitted_at=(prev_emitted if isinstance(prev_emitted, float)
                    else math.nan),
        appended_at=(float(appended_at) if appended_at is not None else
                     (prev_appended if isinstance(prev_appended, float)
                      else math.nan)),
        usage_ratio=hdr["usage_ratio"],
        node_cpu_delta=hdr["node_cpu_delta"], dt_s=hdr["dt_s"],
        name=hdr["node_name"], run=hdr["run"],
        trace=hdr.get("trace", ""),
        owner=str(owner) if owner is not None else hdr.get("owner", ""))
    return header + data[parsed.body_off:]


def restamp_sent_at(data: bytes, sent_at: float) -> bytes:
    """Back-compat alias: rewrite only ``sent_at`` (see
    :func:`restamp_transmit`)."""
    return restamp_transmit(data, sent_at)


# keplint: taint-source — the ONLY wire accessor that skips validation
# (the body already failed decoding); callers must sanitize_node_name()
# before the peeked name touches a label, store key, or log line
def peek_node_name(data: bytes) -> str | None:
    """Best-effort node name from a (possibly malformed) payload.

    Used by the aggregator's per-node degradation accounting: when
    ``decode_report`` rejects a body, a salvageable header still tells us
    WHICH node is sending garbage. Never raises; returns None when even
    the header is unreadable."""
    parsed = try_parse_header(data)
    if parsed is None:
        return None
    name = parsed.header.get("node_name")
    return name if isinstance(name, str) and name else None


def peek_identity(data: bytes) -> tuple[str, int]:
    """Best-effort ``(run, seq)`` from a payload (``("", 0)`` when
    unreadable or absent).

    Used by the agent's delivered-watermark accounting: a spooled
    record's identity lives only in its wire header, and the agent
    needs it at ACK time to advance ``acked_through`` — scoped to the
    run, because an old run's replayed seqs say nothing about the
    current run's stream. Never raises."""
    parsed = try_parse_header(data)
    if parsed is None:
        return "", 0
    try:
        return parsed.identity()
    except Exception:
        return "", 0


def _validated_node_name(header: dict) -> str:
    raw = header.get("node_name")
    if not isinstance(raw, str):
        raise WireError("node_name must be a string")
    node_name = sanitize_node_name(raw)
    if not node_name or node_name != raw:
        # reject rather than silently rewrite: an agent sending control
        # bytes or a >128-char name is misconfigured or hostile, and a
        # rewritten identity would split its series mid-stream
        raise WireError("node_name must be 1-128 printable ASCII chars")
    return node_name


# keplint: sanitizes — every field is validated (dtype whitelist, bounds
# checks, node-name charset/length) or the whole report is rejected, so
# decoded output is trusted downstream
def decode_report(data: bytes,
                  parsed: "ParsedHeader | None" = None
                  ) -> tuple[NodeReport, dict[str, Any]]:
    """Parse a report payload → (NodeReport, header). Raises WireError on
    any malformed/oversized input. ``parsed`` (a :func:`parse_header`
    memo) skips the header re-parse — the admitted ingest path parses
    each record's header exactly once.

    v2 KEYFRAMES decode zero-copy: the returned workload arrays are
    read-only ``np.frombuffer`` views over ``data``. v2 DELTA frames
    need base state — use :func:`decode_delta`."""
    if parsed is None:
        parsed = parse_header(data)
    if parsed.version == 2:
        if parsed.is_delta:
            raise WireError("v2 delta frame needs a base keyframe "
                            "(decode_delta)")
        return _decode_keyframe_v2(data, parsed)
    header = parsed.header
    off = parsed.body_off
    if header.get("v") != 1:
        raise WireError(f"unsupported version {header.get('v')!r}")

    arrays: dict[str, np.ndarray] = {}
    for spec in header.get("arrays", []):
        name, dtype_s = spec.get("name"), spec.get("dtype")
        shape = spec.get("shape")
        if dtype_s not in _DTYPES:
            raise WireError(f"dtype {dtype_s!r} not allowed")
        if (not isinstance(shape, list) or len(shape) != 1
                or not isinstance(shape[0], int) or shape[0] < 0):
            raise WireError(f"bad shape {shape!r} for {name!r}")
        dtype = np.dtype(_DTYPES[dtype_s])
        nbytes = shape[0] * dtype.itemsize
        if nbytes > MAX_ARRAY_BYTES or off + nbytes > len(data):
            raise WireError(f"array {name!r} overruns payload")
        arrays[name] = np.frombuffer(
            data, dtype=dtype, count=shape[0], offset=off).copy()
        off += nbytes

    zone_names = header.get("zone_names")
    if (not isinstance(zone_names, list)
            or not all(isinstance(z, str) for z in zone_names)):
        raise WireError("zone_names must be a list of strings")
    node_name = _validated_node_name(header)
    try:
        n_zones = len(zone_names)
        report = NodeReport(
            node_name=node_name,
            zone_deltas_uj=arrays["zone_deltas_uj"],
            zone_valid=arrays["zone_valid"],
            usage_ratio=float(header["usage_ratio"]),
            cpu_deltas=arrays["cpu_deltas"],
            workload_ids=[str(w) for w in header["workload_ids"]],
            node_cpu_delta=float(header["node_cpu_delta"]),
            dt_s=float(header["dt_s"]),
            mode=int(header["mode"]),
            workload_kinds=arrays.get("workload_kinds"),
            meta={str(k): str(v)
                  for k, v in dict(header.get("meta", {})).items()},
        )
    except (KeyError, TypeError) as err:
        raise WireError(f"missing field: {err}") from err
    if report.zone_deltas_uj.shape != (n_zones,):
        raise WireError("zone_deltas/zone_names length mismatch")
    if report.zone_valid.shape != (n_zones,):
        raise WireError("zone_valid/zone_names length mismatch")
    if len(report.workload_ids) != len(report.cpu_deltas):
        raise WireError("workload_ids/cpu_deltas length mismatch")
    if (report.workload_kinds is not None
            and len(report.workload_kinds) != len(report.cpu_deltas)):
        raise WireError("workload_kinds/cpu_deltas length mismatch")
    return report, header


def _kf_section_offsets(data: bytes, parsed: ParsedHeader) -> dict:
    """Validated section offsets of a v2 keyframe payload region —
    every bound checked against ``len(data)`` before any slice, and the
    payload must fill the body exactly (no trailing garbage)."""
    off = parsed.body_off
    if off + _L2.COUNTS_KF.size > len(data):
        raise WireError("truncated v2 keyframe counts")
    z, w, zn_len, ids_len, meta_len = _L2.COUNTS_KF.unpack_from(data, off)
    if z > _L2.MAX_ZONES or w > _L2.MAX_WORKLOADS:
        raise WireError("v2 keyframe zone/workload count over cap")
    if max(zn_len, ids_len, meta_len) > _L2.MAX_BLOB:
        raise WireError("v2 keyframe blob over cap")
    o = off + _L2.COUNTS_KF.size
    sec = {"z": z, "w": w}
    sec["zd"] = o
    o += z * _L2.F32
    sec["cpu"] = o
    o += w * _L2.F32
    sec["zv"] = o
    o += z
    if parsed.flags & FLAG_KINDS:
        sec["kinds"] = o
        o += w
    sec["zn"] = (o, o + zn_len)
    o += zn_len
    sec["ids"] = (o, o + ids_len)
    o += ids_len
    sec["meta"] = (o, o + meta_len)
    o += meta_len
    if o != len(data):
        raise WireError("v2 keyframe payload length mismatch")
    return sec


def _decode_keyframe_v2(data: bytes,
                        parsed: ParsedHeader
                        ) -> tuple[NodeReport, dict[str, Any]]:
    header = parsed.header
    node_name = _validated_node_name(header)
    sec = _kf_section_offsets(data, parsed)
    z, w = sec["z"], sec["w"]
    # zero-copy: read-only views over the request body (the f32 offsets
    # are 4-aligned by the 8-aligned header-region contract), shaped to
    # land straight in pack_reports_into staging rows
    zone_deltas = np.frombuffer(data, np.float32, count=z,
                                offset=sec["zd"])
    cpu_deltas = np.frombuffer(data, np.float32, count=w,
                               offset=sec["cpu"])
    zone_valid = np.frombuffer(data, np.bool_, count=z, offset=sec["zv"])
    kinds = None
    if "kinds" in sec:
        kinds = np.frombuffer(data, np.int8, count=w,
                              offset=sec["kinds"])
    zone_names = _unpack_strs(data, sec["zn"][0], sec["zn"][1], z)
    workload_ids = _unpack_strs(data, sec["ids"][0], sec["ids"][1], w)
    meta_start, meta_end = sec["meta"]
    meta: dict[str, str] = {}
    if meta_end > meta_start:
        flat = _unpack_strs(data, meta_start, meta_end, None)
        if len(flat) % 2:
            raise WireError("v2 meta blob has an odd string count")
        meta = dict(zip(flat[0::2], flat[1::2]))
    # the header dict is this parse's own (one per record): no copy
    header["zone_names"] = zone_names
    header["workload_ids"] = workload_ids
    header["meta"] = meta
    report = NodeReport(
        node_name=node_name,
        zone_deltas_uj=zone_deltas,
        zone_valid=zone_valid,
        usage_ratio=float(header["usage_ratio"]),
        cpu_deltas=cpu_deltas,
        workload_ids=workload_ids,
        node_cpu_delta=float(header["node_cpu_delta"]),
        dt_s=float(header["dt_s"]),
        mode=int(header["mode"]),
        workload_kinds=kinds,
        meta=meta,
    )
    return report, header



# keplint: sanitizes — delta fields are bounds-checked against the base
# (strictly increasing in-range indices, zone count pinned) or the whole
# frame is rejected; merged output reuses already-validated base state
def decode_delta(data: bytes, parsed: ParsedHeader,
                 base_report: NodeReport,
                 base_zone_names: "tuple[str, ...]"
                 ) -> tuple[NodeReport, dict[str, Any], bool]:
    """Merge a v2 DELTA frame against its base keyframe → ``(report,
    header, content_changed)``.

    The caller resolved the base by (node, run, base_seq); this
    function only validates the frame against its shape. A ``FLAG_SAME``
    frame reuses the base arrays outright — the aggregator then keeps
    the node's content identity, and the window engine's delta-H2D
    short-circuits to zero staged rows. Hostile frames (truncated,
    overlong counts, negative/overlapping indices) raise
    :class:`WireError`; nothing is ever written outside the merged
    report."""
    if parsed.version != 2 or not parsed.is_delta:
        raise WireError("not a v2 delta frame")
    header = parsed.header
    # fast path: the base was resolved BY this frame's name, and the
    # base's own name passed keyframe validation — a bytewise match
    # needs no re-sanitization (hot path: every delta, every window)
    raw_name = header.get("node_name")
    if raw_name == base_report.node_name:
        node_name = base_report.node_name
    else:
        node_name = _validated_node_name(header)
        if node_name != base_report.node_name:
            raise WireError("delta node_name does not match its base")
    base_cpu = np.asarray(base_report.cpu_deltas)
    w = int(base_cpu.shape[0])
    off = parsed.body_off
    scalars_same = (
        header["usage_ratio"] == float(base_report.usage_ratio)
        and header["node_cpu_delta"] == float(base_report.node_cpu_delta)
        and header["dt_s"] == float(base_report.dt_s)
        and header["mode"] == int(base_report.mode))
    # the header dict is this parse's own (one per record) — extend in
    # place, sharing the base's already-validated identity planes
    header["zone_names"] = base_zone_names
    header["workload_ids"] = base_report.workload_ids
    header["meta"] = base_report.meta
    if parsed.same:
        if off != len(data):
            raise WireError("FLAG_SAME delta carries payload bytes")
        report = NodeReport(
            node_name=node_name,
            zone_deltas_uj=base_report.zone_deltas_uj,
            zone_valid=base_report.zone_valid,
            usage_ratio=float(header["usage_ratio"]),
            cpu_deltas=base_report.cpu_deltas,
            workload_ids=base_report.workload_ids,
            node_cpu_delta=float(header["node_cpu_delta"]),
            dt_s=float(header["dt_s"]),
            mode=int(header["mode"]),
            workload_kinds=base_report.workload_kinds,
            meta=header["meta"],
        )
        return report, header, not scalars_same
    if off + _L2.COUNTS_DELTA.size > len(data):
        raise WireError("truncated v2 delta counts")
    z, n_changed = _L2.COUNTS_DELTA.unpack_from(data, off)
    if z != len(base_zone_names):
        raise WireError("delta zone count does not match its base")
    if n_changed > w:
        raise WireError("delta changes more rows than the base holds")
    o = off + _L2.COUNTS_DELTA.size
    zd_off = o
    o += z * _L2.F32
    zv_off = o
    o += z
    o += (-o) % _L2.I32  # pad so the index vector stays 4-aligned
    idx_off = o
    o += n_changed * _L2.I32
    val_off = o
    o += n_changed * _L2.F32
    if o != len(data):
        raise WireError("v2 delta payload length mismatch")
    zone_deltas = np.frombuffer(data, np.float32, count=z, offset=zd_off)
    zone_valid = np.frombuffer(data, np.bool_, count=z, offset=zv_off)
    cpu = base_cpu
    if n_changed:
        idx = np.frombuffer(data, np.int32, count=n_changed,
                            offset=idx_off)
        # strictly-increasing in-range check: a Python walk beats numpy
        # at typical delta sizes (a handful of active rows), and numpy
        # takes over past the crossover
        if n_changed <= 64:
            ints = idx.tolist()
            ok = 0 <= ints[0] and ints[-1] < w and all(
                a < b for a, b in zip(ints, ints[1:]))
        else:
            ok = bool(idx[0] >= 0 and idx[-1] < w
                      and (idx[1:] > idx[:-1]).all())
        if not ok:
            raise WireError("delta indices must be strictly increasing "
                            "and inside the base workload range")
        vals = np.frombuffer(data, np.float32, count=n_changed,
                             offset=val_off)
        cpu = base_cpu.copy()
        cpu[idx] = vals
    report = NodeReport(
        node_name=node_name,
        zone_deltas_uj=zone_deltas,
        zone_valid=zone_valid,
        usage_ratio=float(header["usage_ratio"]),
        cpu_deltas=cpu,
        workload_ids=base_report.workload_ids,
        node_cpu_delta=float(header["node_cpu_delta"]),
        dt_s=float(header["dt_s"]),
        mode=int(header["mode"]),
        workload_kinds=base_report.workload_kinds,
        meta=header["meta"],
    )
    return report, header, True


def encode_delta_v2(full: bytes, base: bytes) -> "bytes | None":
    """Derive a v2 DELTA frame: ``full`` (this window's keyframe bytes)
    expressed against ``base`` (the last ACKED keyframe's bytes). Both
    are the agent's OWN payloads, but are still validated structurally.

    Returns None when a delta cannot represent the change — different
    run/name/mode, a changed workload set (ids/kinds), or a changed zone
    axis — in which case the caller ships the keyframe. Bitwise
    comparison throughout, so NaN-carrying rows conservatively count as
    changed instead of flapping."""
    try:
        fp = parse_header(full)
        bp = parse_header(base)
        if fp.version != 2 or bp.version != 2 or fp.is_delta \
                or bp.is_delta:
            return None
        fh, bh = fp.header, bp.header
        if fh["run"] != bh["run"] or not fh["run"] \
                or fh["node_name"] != bh["node_name"] \
                or fh["mode"] != bh["mode"]:
            return None
        fs = _kf_section_offsets(full, fp)
        bs = _kf_section_offsets(base, bp)
        z, w = fs["z"], fs["w"]
        if (z, w) != (bs["z"], bs["w"]):
            return None
        # identity planes must match bytewise: ids, kinds, zone names
        if full[fs["ids"][0]: fs["ids"][1]] \
                != base[bs["ids"][0]: bs["ids"][1]]:
            return None
        if full[fs["zn"][0]: fs["zn"][1]] \
                != base[bs["zn"][0]: bs["zn"][1]]:
            return None
        if ("kinds" in fs) != ("kinds" in bs):
            return None
        if "kinds" in fs and full[fs["kinds"]: fs["kinds"] + w] \
                != base[bs["kinds"]: bs["kinds"] + w]:
            return None
        if full[fs["meta"][0]: fs["meta"][1]] \
                != base[bs["meta"][0]: bs["meta"][1]]:
            return None
        # bitwise row diff (u32 views — NaN-exact)
        cur = np.frombuffer(full, np.uint32, count=w, offset=fs["cpu"])
        prev = np.frombuffer(base, np.uint32, count=w, offset=bs["cpu"])
        changed = np.flatnonzero(cur != prev).astype(np.int32)
        zones_same = (
            full[fs["zd"]: fs["zd"] + z * _L2.F32]
            == base[bs["zd"]: bs["zd"] + z * _L2.F32]
            and full[fs["zv"]: fs["zv"] + z]
            == base[bs["zv"]: bs["zv"] + z])
        scalars_same = (
            fh["usage_ratio"] == bh["usage_ratio"]
            and fh["node_cpu_delta"] == bh["node_cpu_delta"]
            and fh["dt_s"] == bh["dt_s"])
        flags = (fp.flags & FLAG_REPLAY) | FLAG_DELTA
        if changed.size == 0 and zones_same and scalars_same:
            flags |= FLAG_SAME
            payload = b""
        else:
            vals = np.frombuffer(full, np.float32, count=w,
                                 offset=fs["cpu"])[changed]
            zd = full[fs["zd"]: fs["zd"] + z * _L2.F32]
            zv = full[fs["zv"]: fs["zv"] + z]
            head_len = _L2.COUNTS_DELTA.size + len(zd) + len(zv)
            pad = b"\x00" * ((-head_len) % _L2.I32)
            payload = b"".join([
                _L2.COUNTS_DELTA.pack(z, int(changed.size)), zd, zv,
                pad, changed.tobytes(), vals.tobytes()])
        sent = fh.get("sent_at")
        emitted = fh.get("emitted_at")
        appended = fh.get("appended_at")
        header = _L2.pack_header(
            flags=flags, seq=fh["seq"], epoch=fh["epoch"],
            acked_through=fh["acked_through"], base_seq=bh["seq"],
            mode=fh["mode"],
            sent_at=sent if isinstance(sent, float) else math.nan,
            emitted_at=(emitted if isinstance(emitted, float)
                        else math.nan),
            appended_at=(appended if isinstance(appended, float)
                         else math.nan),
            usage_ratio=fh["usage_ratio"],
            node_cpu_delta=fh["node_cpu_delta"], dt_s=fh["dt_s"],
            name=fh["node_name"], run=fh["run"],
            trace=fh.get("trace", ""), owner=fh.get("owner", ""))
        return header + payload
    except WireError:
        return None


def transcode_to_v1(data: bytes) -> bytes:
    """A v2 KEYFRAME re-encoded as a v1 frame (the agent's downgrade
    path against an old replica that answers 415/400 to v2). v1 frames
    pass through untouched; a v2 DELTA cannot be transcoded without its
    base and raises :class:`WireError` — the agent keyframes instead."""
    if data[: len(MAGIC)] == MAGIC:
        return data
    parsed = parse_header(data)
    if parsed.is_delta:
        raise WireError("cannot transcode a v2 delta without its base")
    report, header = _decode_keyframe_v2(data, parsed)
    sent = header.get("sent_at")
    emitted = header.get("emitted_at")
    return encode_report(
        report, list(header["zone_names"]), seq=header["seq"],
        run=header["run"],
        sent_at=sent if isinstance(sent, float) else None,
        trace_id=header.get("trace", ""),
        emitted_at=emitted if isinstance(emitted, float) else None)
