"""Elastic fleet membership: coordinator lease, succession, autoscale.

ROADMAP item 1 makes the fleet's scaling axes elastic: replicas join
and leave the ingest ring at runtime, a host death at ANY mesh size is
healed by exactly one survivor, and replica count follows load instead
of an operator constant. This module holds the pure decision layer —
no sockets, no locks, no clocks — so every rule here is deterministic
and property-testable; ``fleet.aggregator`` wires the decisions to the
ring, the engines, and the ``/v1/membership`` plane.

Three pieces:

* **Succession** (:func:`elect_successor`, :func:`plan_succession`):
  who is entitled to issue the next membership. The rule is a pure
  function of the survivor set — the incumbent lease holder while it
  survives, else the LOWEST surviving peer in sorted order — so every
  survivor computes the same issuer with no coordination protocol,
  and exactly one of them bumps the epoch (no split-brain by
  construction; the epoch monotonicity check at apply catches any
  disagreement a partitioned prober could still produce).
* **:class:`CoordinatorLease`**: the (holder, epoch) pair a replica
  believes in. ``adopt`` enforces epoch monotonicity and rejects an
  equal-epoch holder conflict — a rejoining peer adopts the incumbent
  from the join reply and therefore never self-elects over a live
  lease, even when it sorts lowest.
* **:class:`AutoscalePolicy`**: replica-count recommendations from
  signals the fleet already records (admission load ratio, shed
  deltas, ingest-latency EWMA, scoreboard states). A pure hysteresis
  machine over the observation SEQUENCE — seedable and replayable: the
  same signal trace always yields the same decisions.

Wire laundering: join/leave/apply payloads arrive over HTTP from peers
that are untrusted until proven otherwise. Every field passes the ring
sanitizers (:func:`~kepler_tpu.fleet.ring.sanitize_peer`,
:func:`~kepler_tpu.fleet.ring.coerce_epoch`) or the lease-id one here
(:func:`sanitize_lease_id`) before it can steer membership, become a
log field, or key a metric — the KTL112 contract the ring established
for redirect owners, applied to the lease-registration fields.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Mapping, Sequence

from kepler_tpu.fleet.ring import (
    MAX_PEER_NAME,
    coerce_epoch,
    sanitize_peer,
)
from kepler_tpu.telemetry.hlc import parse_hlc

__all__ = [
    "AutoscaleDecision",
    "AutoscalePolicy",
    "AutoscaleSignals",
    "CoordinatorLease",
    "MembershipDecision",
    "MembershipError",
    "elect_successor",
    "lease_id_of",
    "plan_membership_apply",
    "plan_succession",
    "sanitize_lease_id",
    "validate_membership_payload",
]

# "epoch:holder" — epoch digits + separator + a peer name
MAX_LEASE_ID = MAX_PEER_NAME + 24

#: the membership operations /v1/membership accepts (a bounded set so a
#: hostile op string can never mint a metric label or log vocabulary)
MEMBERSHIP_OPS = ("apply", "join", "leave")


class MembershipError(ValueError):
    """Structured membership rejection. ``reason`` is drawn from a
    bounded vocabulary and keys the
    ``kepler_fleet_membership_rejected_total{reason}`` counter label;
    the message carries the operator-facing detail."""

    def __init__(self, reason: str, detail: str) -> None:
        super().__init__(detail)
        self.reason = reason


# -- lease identity ---------------------------------------------------------

def lease_id_of(holder: str, epoch: int) -> str:
    """The canonical lease id for a (holder, epoch) pair."""
    return f"{epoch}:{holder}"


# keplint: sanitizes — the chokepoint that launders a wire-derived
# lease id ("epoch:holder"): bounded length, a non-negative int epoch,
# and a holder that passes the ring's peer sanitizer — or nothing
def sanitize_lease_id(value: object) -> str | None:
    """``value`` as a canonical lease id, or None when it is not one."""
    if not isinstance(value, str) or not value:
        return None
    if len(value) > MAX_LEASE_ID:
        return None
    epoch_s, sep, holder = value.partition(":")
    if not sep or not epoch_s.isdigit():
        return None
    holder = sanitize_peer(holder)
    if holder is None:
        return None
    return lease_id_of(holder, int(epoch_s))


# -- succession -------------------------------------------------------------

def elect_successor(survivors: Iterable[str]) -> str:
    """The successor among ``survivors``: the lowest peer in sorted
    order. Deterministic and total — any two replicas that agree on
    the survivor set agree on the successor."""
    peers = sorted(set(survivors))
    if not peers:
        raise MembershipError("no_survivors",
                              "cannot elect a successor from an empty "
                              "survivor set")
    return peers[0]


def plan_succession(holder: str, survivors: Iterable[str]) -> str:
    """The ONE peer entitled to issue the next membership over
    ``survivors``: the incumbent lease ``holder`` while it survives
    (a non-holder death never re-elects), else the elected successor.
    Every survivor evaluates this identically, so on any host death
    exactly one of them bumps the epoch."""
    alive = set(survivors)
    if holder in alive:
        return holder
    return elect_successor(alive)


class CoordinatorLease:
    """The coordinator lease one replica believes in: who may issue
    membership, and at which epoch that belief was established.

    The lease is NOT an extra consensus protocol — it is derived state,
    advanced in lock-step with the ring epoch by ``apply_membership``.
    ``adopt`` enforces the two invariants that make succession safe:
    the epoch never moves backwards, and two writers at the SAME epoch
    naming different holders are a conflict, never a silent overwrite.
    A rejoining peer adopts the incumbent holder from the join reply —
    it never self-elects over a live lease, even when it sorts lowest
    (succession only runs when the holder is among the dead)."""

    __slots__ = ("_holder", "_epoch")

    # keplint: protocol-transition — birth of a lease belief
    def __init__(self, holder: str, epoch: int = 1) -> None:
        cleaned = sanitize_peer(holder)
        if cleaned is None:
            raise MembershipError("bad_peer",
                                  f"invalid lease holder {holder!r}")
        ep = coerce_epoch(epoch)
        if ep is None or ep < 1:
            raise MembershipError("bad_epoch",
                                  f"lease epoch must be an int >= 1, "
                                  f"got {epoch!r}")
        self._holder = cleaned
        self._epoch = ep

    @property
    def holder(self) -> str:
        return self._holder

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def lease_id(self) -> str:
        return lease_id_of(self._holder, self._epoch)

    def issuer_for(self, survivors: Iterable[str]) -> str:
        """Who issues the next membership over ``survivors``."""
        return plan_succession(self._holder, survivors)

    # keplint: protocol-transition — the ONLY way a lease belief moves
    def adopt(self, holder: str, epoch: int) -> None:
        """Advance the lease to ``(holder, epoch)``. Monotonic: a stale
        epoch is rejected, and an equal-epoch HOLDER conflict (two
        writers won the same epoch) is rejected loudly rather than
        letting the later writer silently win."""
        cleaned = sanitize_peer(holder)
        if cleaned is None:
            raise MembershipError("bad_peer",
                                  f"invalid lease holder {holder!r}")
        ep = coerce_epoch(epoch)
        if ep is None:
            raise MembershipError("bad_epoch",
                                  f"invalid lease epoch {epoch!r}")
        if ep < self._epoch:
            raise MembershipError(
                "stale_epoch",
                f"lease epoch {ep} is behind the adopted epoch "
                f"{self._epoch}")
        if ep == self._epoch and cleaned != self._holder:
            raise MembershipError(
                "equal_epoch_conflict",
                f"lease at epoch {ep} already names holder "
                f"{self._holder!r}; a second writer named {cleaned!r}")
        self._holder = cleaned
        self._epoch = ep

    def describe(self) -> dict:
        return {"holder": self._holder, "epoch": self._epoch,
                "lease_id": self.lease_id}


# -- membership apply -------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MembershipDecision:
    """The pure verdict on one membership proposal against the current
    ring: apply it (at ``epoch`` over ``peers``, possibly retiring this
    replica) or treat it as an idempotent replay. Rejections are raised,
    never returned — a decision object always means "safe to act"."""

    action: str  # "apply" | "replay"
    epoch: int
    peers: tuple[str, ...]
    retired: bool = False


def plan_membership_apply(current_epoch: int,
                          current_peers: Sequence[str],
                          current_digest: str,
                          epoch: object, peers: Iterable[object],
                          self_peer: str,
                          source: str) -> MembershipDecision:
    """Decide one membership proposal. Pure: the whole epoch/peer-set
    state machine — epoch coercion, peer laundering + order-preserving
    dedupe, the stale/replay/equal-epoch-conflict ladder, and the
    retirement-vs-typo rule for a set that excludes ``self_peer`` —
    with no ring, lock, or counter in sight, so kepmc can walk every
    proposal order a fleet of replicas could produce.

    Raises :class:`MembershipError` (``bad_epoch`` / ``bad_peer`` /
    ``stale_epoch`` / ``equal_epoch_conflict`` / ``self_excluded``) on
    any proposal that must not touch the ring."""
    ep = coerce_epoch(epoch)
    if ep is None or ep < 1:
        raise MembershipError(
            "bad_epoch",
            f"membership epoch must be a positive int, got {epoch!r}")
    cleaned: list[str] = []
    for raw in peers:
        peer = sanitize_peer(raw)
        if peer is None:
            raise MembershipError(
                "bad_peer", f"invalid membership peer {raw!r}")
        if peer not in cleaned:
            cleaned.append(peer)
    if not cleaned:
        raise MembershipError("bad_peer",
                              "membership needs at least one peer")
    if ep < current_epoch:
        raise MembershipError(
            "stale_epoch",
            f"membership epoch {ep} is behind the current epoch "
            f"{current_epoch}")
    if ep == current_epoch:
        if set(cleaned) == set(current_peers):
            # idempotent replay: a re-delivered broadcast, or an
            # operator re-running the change they already made
            return MembershipDecision(action="replay", epoch=ep,
                                      peers=tuple(cleaned))
        raise MembershipError(
            "equal_epoch_conflict",
            f"membership at epoch {ep} already applied with a "
            f"DIFFERENT peer set (digest {current_digest}); a second "
            f"writer proposed {sorted(set(cleaned))!r}")
    retired = self_peer not in cleaned
    if retired and source == "operator":
        raise MembershipError(
            "self_excluded",
            f"self peer {self_peer!r} is not in the new membership "
            f"{sorted(cleaned)!r}")
    return MembershipDecision(action="apply", epoch=ep,
                              peers=tuple(cleaned), retired=retired)


# -- membership wire payloads ----------------------------------------------

# keplint: sanitizes — the /v1/membership chokepoint: every field of a
# join/leave/apply payload (op, peers, epoch, issuer/holder, lease id)
# is wire input and is laundered here before the aggregator lets it
# steer the ring, reach a log line, or key a metric label
def validate_membership_payload(payload: object) -> dict:
    """Launder one ``/v1/membership`` payload (or join reply) into a
    normalized dict. Raises :class:`MembershipError` with a bounded
    ``reason`` (``bad_payload`` / ``bad_op`` / ``bad_peer`` /
    ``bad_epoch`` / ``bad_lease``) on the first malformed field."""
    if not isinstance(payload, Mapping):
        raise MembershipError("bad_payload",
                              "membership payload must be a JSON object")
    out: dict = {}
    op = payload.get("op")
    if op is not None:
        if op not in MEMBERSHIP_OPS:
            raise MembershipError(
                "bad_op", f"membership op must be one of "
                f"{list(MEMBERSHIP_OPS)}")
        out["op"] = op
    peers = payload.get("peers")
    if peers is not None:
        if not isinstance(peers, Sequence) or isinstance(peers, (str,
                                                                 bytes)):
            raise MembershipError("bad_peer",
                                  "membership peers must be a list")
        cleaned = []
        for raw in peers:
            peer = sanitize_peer(raw)
            if peer is None:
                raise MembershipError(
                    "bad_peer", f"invalid membership peer {raw!r}")
            cleaned.append(peer)
        out["peers"] = cleaned
    for field in ("peer", "issuer", "holder"):
        raw = payload.get(field)
        if raw is None:
            continue
        peer = sanitize_peer(raw)
        if peer is None:
            raise MembershipError("bad_peer",
                                  f"invalid membership {field} {raw!r}")
        out[field] = peer
    raw_epoch = payload.get("epoch")
    if raw_epoch is not None:
        epoch = coerce_epoch(raw_epoch)
        if epoch is None:
            raise MembershipError(
                "bad_epoch",
                f"membership epoch must be a non-negative int, got "
                f"{raw_epoch!r}")
        out["epoch"] = epoch
    raw_lease = payload.get("lease")
    if raw_lease is not None:
        lease = sanitize_lease_id(raw_lease)
        if lease is None:
            raise MembershipError("bad_lease",
                                  f"invalid lease id {raw_lease!r}")
        out["lease"] = lease
    raw_hlc = payload.get("hlc")
    if raw_hlc is not None:
        # the black-box HLC piggyback: laundered to a parsed stamp (the
        # observer's drift clamp bounds it further); a malformed stamp
        # rejects the payload like every other hostile field
        hlc = parse_hlc(raw_hlc)
        if hlc is None:
            raise MembershipError(
                "bad_payload",
                f"invalid membership hlc stamp {raw_hlc!r:.64}")
        out["hlc"] = hlc
    # a bool flag, clamped (any other JSON type reads as absent/false —
    # it steers only whether a mesh restore is ATTEMPTED, which is
    # further gated on local topology state)
    out["mesh"] = payload.get("mesh") is True
    return out


# -- autoscale --------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AutoscaleSignals:
    """One window's recorded inputs to the autoscale policy — all of
    them signals the fleet already measures (admission controller,
    scoreboard, ring), so a decision trace is replayable from metrics
    alone."""

    #: admission load ratio (max of inflight/latency pressure; 1.0 = at
    #: budget, >= 1.0 sheds) — 0.0 with admission off
    load: float = 0.0
    #: reports shed since the previous observation
    shed_delta: int = 0
    #: admission ingest-latency EWMA (seconds)
    ingest_latency_s: float = 0.0
    #: nodes in the live report store this window
    live_nodes: int = 0
    #: scoreboard rows out of the healthy state
    flagged_nodes: int = 0
    #: current ring membership size
    replicas: int = 1


@dataclasses.dataclass(frozen=True)
class AutoscaleDecision:
    """One observation's outcome: the recommended replica count, which
    way it moved, and the operator-facing reason."""

    replicas: int
    direction: str  # "up" | "down" | "hold"
    reason: str
    streak: int = 0


class AutoscalePolicy:
    """Hysteresis replica-count policy over recorded fleet signals.

    Pure in the sense that matters for replay: ``observe`` is a
    deterministic function of the constructor parameters and the
    SEQUENCE of :class:`AutoscaleSignals` fed so far — no wall clock,
    no RNG, no hidden I/O. Feeding the same recorded trace to a fresh
    policy reproduces the same decisions, which is what the tests pin.

    Hysteresis is asymmetric by default: scaling up needs
    ``up_windows`` CONSECUTIVE overloaded observations (load at or
    past ``scale_up_load``, or any shedding), scaling down needs
    ``down_windows`` consecutive idle ones (load at or under
    ``scale_down_load`` and no shedding) — so flapping load never
    thrashes the mesh, and a recommendation is always one step at a
    time. A streak resets after it fires: the next step needs fresh
    evidence at the new size."""

    def __init__(self, scale_up_load: float = 1.0,
                 scale_down_load: float = 0.25,
                 up_windows: int = 3, down_windows: int = 12,
                 min_replicas: int = 1,
                 max_replicas: int = 0) -> None:
        if scale_up_load <= 0:
            raise ValueError("scale_up_load must be > 0")
        if not 0 <= scale_down_load < scale_up_load:
            raise ValueError(
                "scale_down_load must be >= 0 and below scale_up_load")
        if up_windows < 1 or down_windows < 1:
            raise ValueError("hysteresis windows must be >= 1")
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < 0:
            raise ValueError("max_replicas must be >= 0 (0 = unbounded)")
        self._up_load = float(scale_up_load)
        self._down_load = float(scale_down_load)
        self._up_windows = int(up_windows)
        self._down_windows = int(down_windows)
        self._min = int(min_replicas)
        self._max = int(max_replicas)
        self._up_streak = 0
        self._down_streak = 0

    def observe(self, sig: AutoscaleSignals) -> AutoscaleDecision:
        """Fold one window's signals into the streaks and answer the
        current recommendation."""
        overloaded = sig.load >= self._up_load or sig.shed_delta > 0
        idle = (sig.load <= self._down_load and sig.shed_delta == 0
                and sig.flagged_nodes == 0)
        if overloaded:
            self._up_streak += 1
            self._down_streak = 0
        elif idle:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # the hysteresis dead band: neither streak advances, both
            # survive — a single mid-band window never erases evidence
            pass
        cap = self._max if self._max > 0 else sig.replicas + 1
        if (self._up_streak >= self._up_windows
                and sig.replicas < cap):
            streak, self._up_streak = self._up_streak, 0
            return AutoscaleDecision(
                replicas=sig.replicas + 1, direction="up",
                reason=(f"load {sig.load:.2f} >= {self._up_load:g} "
                        f"(shed {sig.shed_delta}) for {streak} "
                        f"window(s)"),
                streak=streak)
        if (self._down_streak >= self._down_windows
                and sig.replicas > self._min):
            streak, self._down_streak = self._down_streak, 0
            return AutoscaleDecision(
                replicas=sig.replicas - 1, direction="down",
                reason=(f"load {sig.load:.2f} <= {self._down_load:g} "
                        f"for {streak} window(s)"),
                streak=streak)
        return AutoscaleDecision(
            replicas=sig.replicas, direction="hold",
            reason=(f"load {sig.load:.2f}, streaks "
                    f"up={self._up_streak}/{self._up_windows} "
                    f"down={self._down_streak}/{self._down_windows}"),
            streak=max(self._up_streak, self._down_streak))

    def describe(self) -> dict:
        return {
            "scale_up_load": self._up_load,
            "scale_down_load": self._down_load,
            "up_windows": self._up_windows,
            "down_windows": self._down_windows,
            "min_replicas": self._min,
            "max_replicas": self._max,
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
        }
