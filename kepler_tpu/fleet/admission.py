"""Ingest admission control: shed load BEFORE it becomes decode work.

PR 11 made the ingest tier survive a replica crash; this module makes the
*survivors* survive the crash's aftermath. When 1 of N replicas dies,
every displaced agent fails over to a survivor simultaneously and replays
its spool backlog — a thundering herd the un-protected ingest path would
absorb at full decode cost until latency (and the fleet window behind it)
collapsed. The spool + idempotent ``(run, seq)`` dedup make shedding
SAFE: a throttled record stays durable on the agent's disk and replays
later, so answering ``429 + Retry-After`` costs a little latency and
never a window. Graceful degradation is pure upside — this controller is
the valve.

Two load signals, one ladder:

- **Inflight budget.** Admitted ingest requests currently being decoded/
  merged, against ``max_inflight``. The cheap, instantaneous signal.
- **Latency budget.** An EWMA of per-record ingest service time against
  ``latency_budget``. The smoothed, "the tier is sinking" signal. The
  EWMA also decays with a fixed half-life while nothing is being
  admitted/observed, so a burst that was fully shed cannot pin the
  controller in a shed state forever.

``load`` is the max of the two ratios. Shedding is PRIORITY-AWARE so the
fleet's live attribution accuracy degrades LAST:

==========  =======================================  ==============
 priority    class                                    shed at load
==========  =======================================  ==============
 0           fresh window, RAPL ground truth,         ≥ 2.0
             healthy scoreboard node
 1           fresh window, model-estimated node       ≥ 1.5
             (or a scoreboard-flagged reporter)
 2           replay backlog, ground-truth node        ≥ 1.25
 3           replay backlog, model-estimated node     ≥ 1.0
==========  =======================================  ==============

A deep replay backlog is the first thing to wait (it is, by
construction, already safe on disk) and live measured watts are the last
— so a herd event costs backlog drain time, not attribution accuracy.

``Retry-After`` is load-derived (base × load), clamped to
``[retry_after, retry_after_max]``, and jittered ±50% from a seeded RNG
so a thousand throttled agents do not re-arrive in phase.
"""

from __future__ import annotations

# keplint: monotonic-only — budget/EWMA/decay math must survive NTP steps.

import math
import random
import threading
import time as _time
from typing import Callable

# priority classes (see the table above)
PRIORITY_FRESH_GROUND = 0
PRIORITY_FRESH_MODEL = 1
PRIORITY_REPLAY_GROUND = 2
PRIORITY_REPLAY_MODEL = 3
N_PRIORITIES = 4

# load at which each priority class starts shedding (index = priority)
SHED_THRESHOLDS = (2.0, 1.5, 1.25, 1.0)

# shed-reason label values (bounded set — these become metric labels)
REASON_INFLIGHT = "inflight"
REASON_LATENCY = "latency"

# idle half-life of the latency EWMA: with nothing admitted (total shed),
# the remembered latency halves this often, guaranteeing recovery probes
_EWMA_HALFLIFE_S = 5.0


def clamp_priority(priority: int) -> int:
    """Coerce an externally derived priority into the ladder's range."""
    if not isinstance(priority, int) or isinstance(priority, bool):
        return PRIORITY_FRESH_GROUND
    return min(max(priority, PRIORITY_FRESH_GROUND), N_PRIORITIES - 1)


class AdmissionController:
    """Inflight + latency budgets in front of the ingest path.

    Thread-safe: ``admit``/``done`` run on every ingest handler thread;
    all state lives behind one lock (a handful of float ops per call —
    three orders of magnitude below the decode work being protected).
    """

    def __init__(
        self,
        max_inflight: int = 64,
        latency_budget: float = 0.25,
        retry_after: float = 1.0,
        retry_after_max: float = 30.0,
        ewma_alpha: float = 0.2,
        degraded_ttl: float = 60.0,
        jitter_seed: int | None = None,
        monotonic: Callable[[], float] | None = None,
    ) -> None:
        self._max_inflight = max(1, int(max_inflight))
        self._latency_budget = max(0.0, float(latency_budget))
        self._retry_after = max(1e-3, float(retry_after))
        self._retry_after_max = max(self._retry_after,
                                    float(retry_after_max))
        self._alpha = min(1.0, max(1e-3, float(ewma_alpha)))
        self._degraded_ttl = max(0.0, float(degraded_ttl))
        self._rng = random.Random(jitter_seed)
        self._monotonic = monotonic or _time.monotonic
        self._lock = threading.Lock()
        self._inflight = 0  # keplint: guarded-by=_lock
        self._ewma = 0.0  # keplint: guarded-by=_lock
        self._ewma_at: float | None = None  # keplint: guarded-by=_lock
        self._last_shed_at: float | None = None  # keplint: guarded-by=_lock
        self._shed_by_reason: dict[str, int] = {  # keplint: guarded-by=_lock
            REASON_INFLIGHT: 0, REASON_LATENCY: 0}

    # -- admission ---------------------------------------------------------

    def admit(self, priority: int) -> float | None:
        """One pre-decode admission check. Returns ``None`` when the
        request is admitted (the caller MUST pair it with :meth:`done`)
        or the Retry-After seconds to answer the 429 with.

        The check and the inflight increment are atomic, so a admitted
        request can never race past the cap."""
        priority = clamp_priority(priority)
        with self._lock:
            now = self._monotonic()
            inflight_load, latency_load = self._loads_locked(now)
            load = max(inflight_load, latency_load)
            if load < SHED_THRESHOLDS[priority]:
                self._inflight += 1
                return None
            reason = (REASON_INFLIGHT if inflight_load >= latency_load
                      else REASON_LATENCY)
            self._shed_by_reason[reason] += 1
            self._last_shed_at = now
            return self._retry_after_locked(load)

    def done(self, latency_s: float) -> None:
        """An admitted request finished after ``latency_s`` of service
        time: release its inflight slot and fold the observation into
        the latency EWMA."""
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            if latency_s >= 0.0 and math.isfinite(latency_s):
                now = self._monotonic()
                decayed = self._decayed_ewma_locked(now)
                self._ewma = (decayed
                              + self._alpha * (latency_s - decayed))
                self._ewma_at = now

    # -- internals ---------------------------------------------------------

    # keplint: requires-lock=_lock
    def _decayed_ewma_locked(self, now: float) -> float:
        """The EWMA with idle decay applied: while nothing is being
        observed (e.g. everything is shed before decode), the remembered
        latency halves every ``_EWMA_HALFLIFE_S`` — a fully-shed burst
        must not pin the controller in a shed state forever."""
        if self._ewma_at is None or self._ewma <= 0.0:
            return self._ewma
        idle = max(0.0, now - self._ewma_at)
        if idle <= 0.0:
            return self._ewma
        return self._ewma * (0.5 ** (idle / _EWMA_HALFLIFE_S))

    # keplint: requires-lock=_lock
    def _loads_locked(self, now: float) -> tuple[float, float]:
        inflight_load = self._inflight / self._max_inflight
        latency_load = 0.0
        if self._latency_budget > 0.0:
            latency_load = (self._decayed_ewma_locked(now)
                            / self._latency_budget)
        return inflight_load, latency_load

    # keplint: requires-lock=_lock
    def _retry_after_locked(self, load: float) -> float:
        """Load-derived, clamped, jittered backoff hint: heavier
        overload asks agents to stay away longer; the ±50% jitter keeps
        a shed herd from re-arriving in phase."""
        base = min(self._retry_after * max(1.0, load),
                   self._retry_after_max)
        jittered = base * self._rng.uniform(0.5, 1.5)
        return round(min(max(jittered, 0.05), self._retry_after_max), 3)

    # -- introspection -----------------------------------------------------

    def load(self) -> float:
        with self._lock:
            return max(*self._loads_locked(self._monotonic()))

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def latency_ewma(self) -> float:
        with self._lock:
            return self._decayed_ewma_locked(self._monotonic())

    def shed_by_reason(self) -> dict[str, int]:
        with self._lock:
            return dict(self._shed_by_reason)

    def health(self) -> dict:
        """``fleet-ingest`` probe for /healthz: degraded while shedding
        (a shed within ``degraded_ttl``) — the operator's "the ingest
        tier is actively re-pacing its agents" signal. It recovers on
        its own once load falls back under budget and throttled agents
        stop being turned away."""
        with self._lock:
            now = self._monotonic()
            inflight_load, latency_load = self._loads_locked(now)
            load = max(inflight_load, latency_load)
            shed_total = sum(self._shed_by_reason.values())
            last_shed = self._last_shed_at
            shedding = (last_shed is not None
                        and now - last_shed <= self._degraded_ttl)
            out = {
                "ok": not shedding,
                "shedding": shedding,
                "inflight": self._inflight,
                "max_inflight": self._max_inflight,
                "latency_ewma_s": round(
                    self._decayed_ewma_locked(now), 6),
                "latency_budget_s": self._latency_budget,
                "load": round(load, 4),
                "shed_total": shed_total,
                "shed_by_reason": dict(self._shed_by_reason),
            }
            if last_shed is not None:
                out["last_shed_age_s"] = round(now - last_shed, 3)
            return out
