"""Fleet black box: the HLC-stamped causal event journal.

Every fleet state transition — rung ladder moves, lease adoptions,
membership applies, autoscale enactments, quarantine/shed onset, agent
breaker flips, spool rewinds, watchdog stalls — is emitted through ONE
chokepoint (:meth:`EventJournal.emit` / module :func:`emit`) with a
``kind`` drawn from the closed :data:`KIND_CATALOG` registry. The fence
test (tests/test_journal_fence.py) pins catalog ↔ emit-site agreement in
both directions and ``hack/gen_journal_docs.py`` renders the catalog
into docs/developer/observability.md, so an event kind cannot exist
without documentation or documentation without an emitter — the same
teeth ``fault.SITE_CATALOG`` has.

Storage is a bounded in-memory ring (``telemetry.journal.ringSize``)
plus an optional spool-framed durable file (``telemetry.journal.dir``,
length-prefixed CRC32 frames like fleet/spool.py, capped at
``telemetry.journal.maxBytes`` with one rotation) so a crashed replica's
last events survive for the incident bundle.

Cost contract (same as ``telemetry.spans``): module-level :func:`emit`
against the default disabled journal is one global read and one
attribute check — pinned < 1 µs/event by tests — so emission points are
safe in ingest and send paths.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import struct
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Iterator

from kepler_tpu.telemetry.hlc import (
    DEFAULT_MAX_DRIFT_S,
    HLC,
    HlcClock,
    parse_hlc,
)

log = logging.getLogger("kepler.journal")

__all__ = [
    "DEFAULT_RING_SIZE",
    "EventJournal",
    "KIND_CATALOG",
    "KNOWN_KINDS",
    "active",
    "canonical_json",
    "collector",
    "emit",
    "install",
    "install_from_config",
    "installed",
    "make_journal_handler",
    "read_frames",
]

# Canonical event kinds: ``(kind, emitting layer, meaning)``. The single
# source of truth — the fence test, the generated observability.md
# catalog table, and the blackbox CLI's rendering all derive from it.
KIND_CATALOG: tuple[tuple[str, str, str], ...] = (
    ("admission.shed", "aggregator",
     "admission control began shedding (accepting → shedding edge); "
     "per-request 429s are counters, the onset is the incident marker"),
    ("autoscale.enact", "aggregator",
     "the lease holder enacted a scale decision (standby promote / "
     "member retire) — fields name direction, peer, and new epoch"),
    ("breaker.close", "agent",
     "the agent's send circuit breaker closed (probe or send "
     "succeeded; deliveries resume)"),
    ("breaker.open", "agent",
     "the agent's send circuit breaker opened after consecutive "
     "failures (sends stop; spool keeps accumulating)"),
    ("lease.adopt", "aggregator",
     "this replica adopted a coordinator lease (holder, epoch) — "
     "succession and join grants land here"),
    ("membership.apply", "aggregator",
     "an ingest-ring membership change was applied (epoch, peers, "
     "source, dropped/retired shards)"),
    ("quarantine.onset", "aggregator",
     "a node entered the degraded set (first strike of this spell: "
     "malformed / clock-skew / flapping quarantine)"),
    ("rung.transition", "aggregator",
     "the degradation ladder moved (demotion or repromotion) — the "
     "journal twin of the /debug/window rung timeline entry"),
    ("spool.rewind", "agent",
     "the agent rewound its durable spool cursor for hand-off replay "
     "(unacked frames will be redelivered to the new owner)"),
    ("watchdog.stall", "monitor",
     "the monitor watchdog detected a stalled refresh loop (first "
     "detection of this stall, not the per-check repeat)"),
)

KNOWN_KINDS: tuple[str, ...] = tuple(k for k, _, _ in KIND_CATALOG)
_KNOWN_SET = frozenset(KNOWN_KINDS)

DEFAULT_RING_SIZE = 512
DEFAULT_MAX_BYTES = 4_000_000

# durable frame: little-endian (payload length, crc32) then the JSON
# payload — fleet/spool.py's framing, so a torn tail is detected, not
# parsed
_FRAME = struct.Struct("<II")


def canonical_json(obj: Any) -> bytes:
    """Canonical (sorted-key, no-whitespace) JSON bytes: the bundle /
    merged-timeline determinism contract — same content, same SHA-256."""
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode()


class EventJournal:
    """Bounded-ring (+ optional durable) journal with an embedded
    :class:`HlcClock`. One per process in production; one per replica in
    the chaos harness (each on the conductor's virtual clock)."""

    def __init__(self, *, enabled: bool = False, node: str = "",
                 ring_size: int = DEFAULT_RING_SIZE,
                 dir: str = "", max_bytes: int = DEFAULT_MAX_BYTES,
                 clock: Callable[[], float] = time.time,
                 max_drift_s: float = DEFAULT_MAX_DRIFT_S) -> None:
        self._enabled = bool(enabled)
        self.hlc = HlcClock(node, clock=clock, max_drift_s=max_drift_s)
        self._lock = threading.Lock()
        self._ring: deque[dict[str, Any]] = deque(
            maxlen=max(1, int(ring_size)))
        self._counts: dict[str, int] = {k: 0 for k in KNOWN_KINDS}
        self._dir = dir
        self._max_bytes = max(4096, int(max_bytes))
        self._path = ""
        self._file: Any = None
        self._write_errors = 0
        if enabled and dir:
            self._open_spool()

    # -- emission chokepoint ----------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def node(self) -> str:
        return self.hlc.node

    def emit(self, kind: str, **fields: Any) -> HLC | None:
        """THE chokepoint. ``kind`` must be cataloged — an unknown kind
        raises so a typo'd emitter fails its first test, exactly like
        ``FaultPlan.from_config`` rejecting unknown sites."""
        if not self._enabled:
            return None
        if kind not in _KNOWN_SET:
            raise ValueError(
                f"journal kind {kind!r} is not in KIND_CATALOG — add it "
                "to kepler_tpu/fleet/journal.py (and run "
                "python hack/gen_journal_docs.py)")
        stamp = self.hlc.now()
        entry: dict[str, Any] = {"hlc": stamp.to_dict(), "kind": kind,
                                 "fields": fields}
        with self._lock:
            self._ring.append(entry)
            self._counts[kind] += 1
            if self._file is not None:
                self._append_frame(entry)
        return stamp

    # -- HLC piggyback surface --------------------------------------------

    def header(self) -> str | None:
        """Outbound ``X-Kepler-HLC`` value (advances the clock), or
        ``None`` when the journal is disabled (no header emitted)."""
        if not self._enabled:
            return None
        return self.hlc.now().encode()

    def observe(self, remote: HLC) -> HLC | None:
        """Merge an inbound (already laundered) stamp."""
        if not self._enabled:
            return None
        return self.hlc.observe(remote)

    def observe_text(self, text: object) -> bool:
        """Launder + merge a wire-borne stamp. Returns False when the
        value is present but hostile (caller decides 400 vs ignore);
        True for absent/valid."""
        if text is None or not self._enabled:
            return True
        remote = parse_hlc(text)
        if remote is None:
            return False
        self.hlc.observe(remote)
        return True

    # -- views ------------------------------------------------------------

    def snapshot(self, since: HLC | None = None,
                 limit: int | None = None) -> list[dict[str, Any]]:
        """Ring contents in HLC order, strictly after ``since``."""
        with self._lock:
            entries = list(self._ring)
        if since is not None:
            key = (since.phys_us, since.logical, since.node)
            entries = [e for e in entries
                       if (e["hlc"]["phys_us"], e["hlc"]["logical"],
                           e["hlc"]["node"]) > key]
        if limit is not None and limit >= 0:
            entries = entries[:limit]
        return entries

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            events = sum(self._counts.values())
            ring_len = len(self._ring)
        return {"enabled": self._enabled, "node": self.node,
                "events_total": events, "ring": ring_len,
                "spool": self._path, "write_errors": self._write_errors,
                "hlc_clamped_total": self.hlc.clamped_total(),
                "hlc_drift_seconds": self.hlc.drift_seconds()}

    # -- prometheus -------------------------------------------------------

    def collect(self) -> Iterator[Any]:
        """prometheus_client custom-collector hook (kepler_fleet_*)."""
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )
        counts = self.counts()
        events = CounterMetricFamily(
            "kepler_fleet_journal_events_total",
            "Fleet black-box journal events emitted, by event kind "
            "(closed registry: journal.KIND_CATALOG)",
            labels=["kind"])
        for kind in KNOWN_KINDS:
            events.add_metric([kind], counts.get(kind, 0))
        yield events
        drift = GaugeMetricFamily(
            "kepler_fleet_hlc_drift_seconds",
            "Signed physical-clock offset (remote minus local wall) of "
            "the last HLC stamp observed from a peer")
        drift.add_metric([], self.hlc.drift_seconds())
        yield drift
        clamped = CounterMetricFamily(
            "kepler_fleet_hlc_clamped_total",
            "Inbound HLC stamps whose physical component exceeded the "
            "aggregator.hlcMaxDrift bound and was clamped (hostile or "
            "badly skewed peer clock)")
        clamped.add_metric([], self.hlc.clamped_total())
        yield clamped

    # -- durable spool ----------------------------------------------------

    def _open_spool(self) -> None:
        try:
            os.makedirs(self._dir, exist_ok=True)
            safe = "".join(ch if (ch.isalnum() or ch in "._-") else "_"
                           for ch in (self.node or "journal"))
            self._path = os.path.join(self._dir, f"{safe}.kepj")
            self._file = open(self._path, "ab")
        except OSError as err:
            self._write_errors += 1
            self._file = None
            log.warning("journal spool unavailable (%s); ring only", err)

    def _append_frame(self, entry: dict[str, Any]) -> None:
        payload = canonical_json(entry)
        frame = _FRAME.pack(len(payload),
                            zlib.crc32(payload)) + payload
        try:
            if self._file.tell() + len(frame) > self._max_bytes:
                self._rotate()
            if self._file is not None:
                self._file.write(frame)
                self._file.flush()
        except (OSError, ValueError):
            self._write_errors += 1
            self._file = None

    def _rotate(self) -> None:
        self._file.close()
        os.replace(self._path, self._path + ".1")
        self._file = open(self._path, "ab")

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                with contextlib.suppress(OSError, ValueError):
                    self._file.close()
                self._file = None


def read_frames(path: str) -> list[dict[str, Any]]:
    """Read a durable journal file; a torn tail or a CRC mismatch ends
    the scan cleanly (kill -9 mid-append is the expected case)."""
    entries: list[dict[str, Any]] = []
    try:
        data = open(path, "rb").read()
    except OSError:
        return entries
    off = 0
    while off + _FRAME.size <= len(data):
        length, crc = _FRAME.unpack_from(data, off)
        off += _FRAME.size
        payload = data[off:off + length]
        if len(payload) != length or zlib.crc32(payload) != crc:
            break
        off += length
        try:
            entries.append(json.loads(payload))
        except ValueError:
            break
    return entries


# ---------------------------------------------------------------------------
# module-level installed journal (agent/monitor processes; the
# aggregator holds a per-instance journal so chaos replicas stay apart)
# ---------------------------------------------------------------------------

# starts DISABLED: library imports and unit tests pay only the fast path
_active = EventJournal(enabled=False)


def active() -> EventJournal:
    return _active


def install(jnl: EventJournal) -> EventJournal:
    global _active
    _active = jnl
    return jnl


def emit(kind: str, **fields: Any) -> HLC | None:
    """The process-global emission point. Disabled cost: one global
    read, one attribute check, return — pinned < 1 µs by tests."""
    jnl = _active
    if not jnl._enabled:
        return None
    return jnl.emit(kind, **fields)


def install_from_config(cfg: Any, *, node: str = "",
                        max_drift_s: float = DEFAULT_MAX_DRIFT_S
                        ) -> EventJournal:
    """Build + install from a ``TelemetryConfig`` (cfg.journal holds the
    leaves). Shared by both binaries."""
    j = cfg.journal
    jnl = EventJournal(enabled=j.enabled, node=node,
                       ring_size=j.ring_size, dir=j.dir,
                       max_bytes=j.max_bytes, max_drift_s=max_drift_s)
    return install(jnl)


@contextlib.contextmanager
def installed(jnl: EventJournal) -> Iterator[EventJournal]:
    """Test helper: install for a with-block, always restoring."""
    prev = _active
    install(jnl)
    try:
        yield jnl
    finally:
        install(prev)


class JournalCollector:
    """Registry adapter following the INSTALLED journal at scrape time
    (same contract as telemetry.SelfMetricsCollector)."""

    def __init__(self, jnl: EventJournal | None = None) -> None:
        self._jnl = jnl

    def collect(self) -> Iterator[Any]:
        yield from (self._jnl or _active).collect()


def collector(jnl: EventJournal | None = None) -> JournalCollector:
    return JournalCollector(jnl)


# ---------------------------------------------------------------------------
# /debug/journal endpoint
# ---------------------------------------------------------------------------


def make_journal_handler(jnl: EventJournal | None = None
                         ) -> Callable[[Any],
                                       tuple[int, dict[str, str], bytes]]:
    """APIServer handler: ``GET /debug/journal`` → ``{"node", "enabled",
    "hlc", "events", "cursor"}``. ``?since=<phys:logical:node>`` resumes
    strictly after that stamp (cursor pagination — pass the previous
    response's ``cursor``); ``?limit=N`` bounds the page."""
    from urllib.parse import parse_qs, urlparse

    # keplint: thread-role=http-handler
    def handler(request: Any) -> tuple[int, dict[str, str], bytes]:
        journal = jnl if jnl is not None else _active
        qs = parse_qs(urlparse(request.path).query)
        since: HLC | None = None
        raw_since = qs.get("since", [None])[0]
        if raw_since is not None:
            since = parse_hlc(raw_since)
            if since is None:
                return (400, {"Content-Type": "application/json"},
                        b'{"error": "bad since cursor"}')
        limit: int | None = None
        raw_limit = qs.get("limit", [None])[0]
        if raw_limit is not None:
            try:
                limit = max(0, int(raw_limit))
            except ValueError:
                return (400, {"Content-Type": "application/json"},
                        b'{"error": "bad limit"}')
        events = journal.snapshot(since=since, limit=limit)
        cursor = ""
        if events:
            last = events[-1]["hlc"]
            cursor = HLC(last["phys_us"], last["logical"],
                         last["node"]).encode()
        payload = {"node": journal.node, "enabled": journal.enabled,
                   "stats": journal.stats(), "events": events,
                   "cursor": cursor}
        return (200, {"Content-Type": "application/json"},
                json.dumps(payload).encode())

    return handler
