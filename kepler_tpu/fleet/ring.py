"""Consistent-hash ring for the replicated ingest tier.

One aggregator behind one HTTP endpoint is the fleet-size ceiling
(ROADMAP item 1): a single crash or partition stalls every agent. The
HA ingest tier shards agents across N aggregator replicas by
consistent-hash of ``node_name`` — each replica accepts only the nodes
it owns and answers everyone else with a structured ``421 + owner +
epoch`` redirect the agent follows. Because the PR-3 delivery plane is
already at-least-once with idempotent ``(run, seq)`` ingest, a
membership change is **replay, not loss**: displaced agents re-deliver
their spool tail to the new owner and the dedup window absorbs the
overlap.

Design constraints:

- **Deterministic across processes.** Ownership is a pure function of
  the (sorted) peer set and the key — two replicas configured with the
  same ``aggregator.peers`` list always agree, with no coordination
  protocol. Hashing is ``blake2b`` (stable everywhere), never Python's
  salted ``hash()``.
- **Minimal disruption.** Virtual nodes (``vnodes`` points per peer)
  mean removing a replica moves ONLY the departed replica's keys to
  the survivors; everyone else's owner is untouched. Adding one steals
  only the keys the newcomer now owns. (Property-tested in
  ``tests/test_hash_ring.py``.)
- **Versioned membership.** The ring carries a monotonically
  increasing ``epoch``; replicas advertise it on every redirect and
  accept, so agents learn the ring lazily and re-resolve on a bump.
  The ring object itself is immutable — a membership change builds a
  NEW ring (``Aggregator.apply_membership``), so readers never need a
  lock.

Peer names arrive from config on the happy path but ALSO from the wire
(an agent adopts the ``owner`` a redirect names; a replica validates
the ``owner`` header agents echo back) — they are untrusted input
until they pass :func:`sanitize_peer` / :func:`coerce_epoch`.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Sequence

__all__ = ["HashRing", "MeshRing", "RingError", "MAX_PEER_NAME",
           "ring_from_mesh", "sanitize_peer", "coerce_epoch"]

# peer names become redirect payloads, log fields, and /debug/ring
# entries; the cap bounds every store keyed on them (the node-name
# contract, applied to the peer axis)
MAX_PEER_NAME = 256

DEFAULT_VNODES = 64


class RingError(ValueError):
    pass


# keplint: sanitizes — the chokepoint that launders a wire-derived peer
# name (redirect bodies, echoed owner headers): printable ASCII only,
# length-capped, never empty — hostile values can't forge log lines or
# mint unbounded redirect targets
def sanitize_peer(name: object) -> str | None:
    """``name`` as a safe peer id, or None when it is not one."""
    if not isinstance(name, str) or not name:
        return None
    if len(name) > MAX_PEER_NAME:
        return None
    if any(not (" " <= c <= "\x7e") for c in name):
        return None
    return name


# keplint: sanitizes — epoch/acked_through values off the wire: a
# non-bool, non-negative int or nothing
def coerce_epoch(value: object) -> int | None:
    """``value`` as a non-negative int epoch/watermark, else None."""
    if isinstance(value, bool) or not isinstance(value, int):
        return None
    if value < 0:
        return None
    return value


def _point(data: str) -> int:
    """64-bit ring coordinate for a string (stable across processes)."""
    return int.from_bytes(
        hashlib.blake2b(data.encode(), digest_size=8).digest(), "big")


class HashRing:
    """Immutable consistent-hash ring over a static peer set.

    ``peers`` is the replica membership (each entry a dialable
    endpoint like ``"127.0.0.1:28283"`` — but opaque to the ring);
    ``epoch`` versions the membership. Two rings built from the same
    peer SET (any order) and vnode count produce identical ownership.
    """

    __slots__ = ("_peers", "_epoch", "_vnodes", "_points", "_owners")

    # keplint: protocol-transition — a ring (and its epoch) is born
    # immutable; with_members builds a NEW ring at a HIGHER epoch
    def __init__(self, peers: Iterable[str], epoch: int = 1,
                 vnodes: int = DEFAULT_VNODES) -> None:
        cleaned: list[str] = []
        for raw in peers:
            peer = sanitize_peer(raw)
            if peer is None:
                raise RingError(
                    f"invalid ring peer {raw!r}: peers must be 1-"
                    f"{MAX_PEER_NAME} printable ASCII chars")
            cleaned.append(peer)
        if not cleaned:
            raise RingError("ring needs at least one peer")
        if len(set(cleaned)) != len(cleaned):
            raise RingError(f"duplicate ring peers in {cleaned!r}")
        if coerce_epoch(epoch) is None or epoch < 1:
            raise RingError(f"ring epoch must be an int >= 1, got {epoch!r}")
        if not isinstance(vnodes, int) or vnodes < 1:
            raise RingError(f"ring vnodes must be an int >= 1, got {vnodes!r}")
        self._peers = tuple(sorted(cleaned))
        self._epoch = int(epoch)
        self._vnodes = int(vnodes)
        pts: list[tuple[int, str]] = []
        for peer in self._peers:
            for v in range(self._vnodes):
                pts.append((_point(f"{peer}#{v}"), peer))
        pts.sort()
        self._points = [p for p, _ in pts]
        self._owners = [o for _, o in pts]

    # -- membership --------------------------------------------------------

    @property
    def peers(self) -> tuple[str, ...]:
        return self._peers

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def vnodes(self) -> int:
        return self._vnodes

    @property
    def membership_digest(self) -> str:
        """Short stable digest of the peer SET (order-free) — lets two
        replicas cheaply check they applied the SAME membership at an
        epoch (the equal-epoch split-brain detector's log/debug
        evidence) without printing full peer lists."""
        return hashlib.blake2b("\x1f".join(self._peers).encode(),
                               digest_size=4).hexdigest()

    def __contains__(self, peer: str) -> bool:
        return peer in self._peers

    def __len__(self) -> int:
        return len(self._peers)

    def with_members(self, peers: Sequence[str], epoch: int) -> "HashRing":
        """A NEW ring for a membership change. ``epoch`` must advance —
        redirects from stale and fresh replicas are only orderable
        because the epoch is monotonic."""
        if coerce_epoch(epoch) is None or epoch <= self._epoch:
            raise RingError(
                f"membership epoch must increase past {self._epoch}, "
                f"got {epoch!r}")
        return HashRing(peers, epoch=epoch, vnodes=self._vnodes)

    # -- ownership ---------------------------------------------------------

    def owner(self, key: str) -> str:
        """The peer owning ``key`` (first ring point at or after the
        key's coordinate, wrapping)."""
        i = bisect.bisect_left(self._points, _point(key))
        if i >= len(self._points):
            i = 0
        return self._owners[i]

    def ownership_ratio(self, peer: str) -> float:
        """Fraction of the hash space ``peer`` owns (arc lengths of its
        ring points) — the ownership gauge's value. 0.0 for a peer not
        in the ring."""
        if peer not in self._peers:
            return 0.0
        if len(self._peers) == 1:
            return 1.0
        space = float(1 << 64)
        total = 0
        pts, owners = self._points, self._owners
        for i, point in enumerate(pts):
            if owners[i] != peer:
                continue
            prev = pts[i - 1] if i else pts[-1] - (1 << 64)
            total += point - prev
        return total / space

    def describe(self, self_peer: str = "") -> dict:
        """``/debug/ring`` payload fragment (the aggregator adds its
        redirect counters)."""
        return {
            "epoch": self._epoch,
            "peers": list(self._peers),
            "vnodes": self._vnodes,
            "self": self_peer,
            "digest": self.membership_digest,
            "ownership_ratio": (round(self.ownership_ratio(self_peer), 6)
                                if self_peer else None),
        }


class MeshRing(HashRing):
    """Ingest ring whose ownership is DERIVED from the device mesh's
    shard map — the multi-host co-location contract (ISSUE 15): a node
    hashes to a global mesh shard (``blake2b(node) % n_shards``), and
    its owner is the peer of the PROCESS whose local devices host that
    shard. Each host's aggregator replica therefore ingests exactly the
    agents whose packed rows live on its local devices — wire-v2
    zero-copy decode lands in host-local staging with zero cross-host
    bytes on the ingest path.

    Deterministic across processes for the same (peers-by-process,
    shard→process, epoch) inputs, like the vnode ring. ``with_members``
    intentionally DEGRADES to a plain :class:`HashRing`: a membership
    change away from the mesh map (host death, operator rebalance) is
    exactly the moment mesh-derived ownership stops being true.
    """

    __slots__ = ("_shard_owner", "_n_shards")

    def __init__(self, peers_by_process: Sequence[str],
                 shard_processes: Sequence[int], epoch: int = 1) -> None:
        if not shard_processes:
            raise RingError("mesh ring needs at least one shard")
        if any(not isinstance(p, int) or isinstance(p, bool)
               or not 0 <= p < len(peers_by_process)
               for p in shard_processes):
            raise RingError(
                f"shard process ids must index peers_by_process "
                f"(0..{len(peers_by_process) - 1}); got "
                f"{list(shard_processes)!r}")
        # the vnode point set is unused for ownership but kept valid so
        # every HashRing surface (peers, describe, epoch checks) holds
        super().__init__(peers_by_process, epoch=epoch, vnodes=1)
        cleaned = [sanitize_peer(p) for p in peers_by_process]
        self._shard_owner = tuple(cleaned[p] for p in shard_processes)
        self._n_shards = len(shard_processes)

    @property
    def n_shards(self) -> int:
        return self._n_shards

    def shard_of(self, key: str) -> int:
        """The global mesh shard ``key``'s packed row hashes to."""
        return _point(key) % self._n_shards

    def owner(self, key: str) -> str:
        return self._shard_owner[self.shard_of(key)]

    def ownership_ratio(self, peer: str) -> float:
        if peer not in self._peers:
            return 0.0
        owned = sum(1 for o in self._shard_owner if o == peer)
        return owned / self._n_shards

    def with_members(self, peers: Sequence[str], epoch: int) -> HashRing:
        """Membership change → a PLAIN consistent-hash ring over the
        survivors (the mesh map no longer describes reality once a host
        left it). Epoch must advance, as on the base ring."""
        if coerce_epoch(epoch) is None or epoch <= self.epoch:
            raise RingError(
                f"membership epoch must increase past {self.epoch}, "
                f"got {epoch!r}")
        return HashRing(peers, epoch=epoch, vnodes=DEFAULT_VNODES)

    def describe(self, self_peer: str = "") -> dict:
        out = super().describe(self_peer)
        out["mesh_derived"] = True
        out["n_shards"] = self._n_shards
        return out


def ring_from_mesh(peers_by_process: Sequence[str],
                   shard_processes: Sequence[int],
                   epoch: int = 1) -> MeshRing:
    """Build the mesh-co-located ingest ring (ISSUE 15).

    ``peers_by_process[p]`` is process ``p``'s dialable replica endpoint
    (``aggregator.peers`` ordered by ``jax.process_index``);
    ``shard_processes[k]`` is the process whose local device hosts
    global mesh shard ``k`` (``[d.process_index for d in
    mesh.devices.flat]`` on the 1-D node mesh). Every process builds the
    identical ring with no coordination — the same determinism contract
    as :class:`HashRing`, with the shard map as the hash space.
    """
    return MeshRing(peers_by_process, shard_processes, epoch=epoch)
