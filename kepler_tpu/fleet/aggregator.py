"""Cluster aggregator: ingest node reports, attribute the whole fleet on TPU.

The aggregator half of the DCN plane (BASELINE.json north star, SURVEY §7
step 9): node agents POST per-window feature rows; every ``interval`` the
aggregator runs one fleet window over the latest report from each node
and publishes:

- ``GET /v1/results[?node=…]`` — attributed watts scattered back per node
  (JSON), the pull leg for non-RAPL nodes that want their estimates;
- ``GET /metrics`` — cluster-level Prometheus families
  (``kepler_fleet_…``), the same scrape plane the reference leans on.

The default window path is DEVICE-RESIDENT and PIPELINED
(``kepler_tpu.fleet.window``): the padded packed-f16 batch lives on
device, each window scatter-updates only the rows whose report changed
(delta H2D through a donated in-place program), and with
``pipeline_depth`` ≥ 2 the fetch/scatter of window N overlaps window
N+1's host assembly and dispatch — steady-state cadence approaches
max(assembly, device) instead of their sum, at the cost of results
being at most ``pipeline_depth − 1`` intervals stale. Shutdown (and an
emptied fleet) deterministically drains in-flight windows.

The serial einsum-f32 path — full assemble + one sharded dispatch + a
multi-array fetch per window — is retained for ``accuracy_mode`` (the
configuration the 0.5% budget is validated under), temporal mode (whose
feature-history tensor has no packed layout), and training-dump capture
(which needs the assembled host batch).

Late/missing nodes: a node whose latest report is older than
``stale_after`` falls out of the batch (its row just isn't assembled) —
the batched analog of the reference's per-zone skip-on-error.
"""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import queue
import threading
import time as _time
from dataclasses import dataclass
from typing import Any, Callable, Iterator, Mapping, Sequence

import numpy as np

from kepler_tpu import fault, telemetry
from kepler_tpu.fleet.admission import (
    PRIORITY_FRESH_GROUND,
    PRIORITY_FRESH_MODEL,
    PRIORITY_REPLAY_GROUND,
    AdmissionController,
)
from kepler_tpu.fleet.delivery import (
    SeqTracker,
    delta_base_matches,
    reseed_on_ownership_return,
    seed_fresh_tracker,
)
from kepler_tpu.fleet.journal import (
    EventJournal,
    canonical_json,
    make_journal_handler,
)
from kepler_tpu.fleet.membership import (
    AutoscaleDecision,
    AutoscalePolicy,
    AutoscaleSignals,
    CoordinatorLease,
    MembershipError,
    elect_successor,
    plan_membership_apply,
    plan_succession,
    validate_membership_payload,
)
from kepler_tpu.fleet.ring import (HashRing, RingError, coerce_epoch,
                                   ring_from_mesh, sanitize_peer)
from kepler_tpu.fleet.wire import (
    ParsedHeader,
    WireError,
    decode_delta,
    decode_report,
    decode_report_batch,
    peek_node_name,
    peek_routing,
    sanitize_node_name,
    try_parse_header,
)
from kepler_tpu.fleet.scoreboard import STATE_NAMES, FleetScoreboard
from kepler_tpu.fleet.window import (DeviceWindowError, FusedFlush,
                                     FusedWindowEngine,
                                     MultiHostWindowEngine,
                                     PackedWindowEngine, RowInput,
                                     ShardedWindowEngine, WindowMeta,
                                     align_zone_matrices)
from kepler_tpu.monitor.history import HistoryBuffer
from kepler_tpu.telemetry import DEFAULT_DELIVERY_BUCKETS, Histogram
from kepler_tpu.parallel.aggregator_core import (
    make_fleet_program,
    make_temporal_fleet_program,
    run_fleet_attribution,
)
from kepler_tpu.parallel.fleet import (MODE_MODEL, NodeReport,
                                       assemble_fleet_batch)
from kepler_tpu.parallel.mesh import make_mesh, submesh_for_processes
from kepler_tpu.server.http import APIServer
from kepler_tpu.service.lifecycle import CancelContext
from kepler_tpu.utils.rowstore import RowStore

log = logging.getLogger("kepler.fleet.aggregator")

# upper bound for one report POST (64 MiB ≫ any real fleet window: 10k
# workloads ≈ 50 KiB of arrays + ids) — enforced by the server before the
# body is buffered
MAX_REPORT_BYTES = 64 << 20

# TEST-ONLY chaos regression seed: when flipped (monkeypatched by the
# kepchaos shrinking-proof test, never set in production code), the
# membership fan-out stamps this replica as the issuer instead of the
# current lease holder — the historical holder-self-leave bug, where
# receivers adopt the DEPARTED peer as lease holder. kepchaos must
# catch this from a randomized schedule and shrink it to the minimal
# repro; see tests/test_chaos_conductor.py.
_BUG_BROADCAST_SELF_ISSUER = False

# degradation-ladder rungs for the window's device leg
# (docs/developer/resilience.md "Device-plane faults"): every device
# failure demotes ONE rung; `repromote_after` consecutive clean windows
# at a lower rung retry the rung above (hysteresis, like the breaker's
# half-open probe and the bucket ladder's shrink window). The bottom
# rung touches no jax API at all, so the aggregator keeps publishing
# with the device plane completely dead.
RUNG_PIPELINED = 0  # packed-f16 resident batch, pipelineDepth in flight
RUNG_PACKED_SERIAL = 1  # packed-f16 resident batch, depth 1
RUNG_EINSUM = 2  # serial einsum-f32 (full assemble + dense dispatch)
RUNG_NUMPY = 3  # pure-NumPy host fallback (no device, no jax)
RUNG_NAMES = ("packed-pipelined", "packed-serial", "einsum-serial",
              "numpy-host")
# rung 0's name when the window is sharded over a multi-device node
# mesh (ShardedWindowEngine): a single shard's device failure demotes
# to the single-device rungs above, so only rung 0 has a sharded form
RUNG_NAME_SHARDED = "packed-sharded-pipelined"
# rung 0's names on a multi-host mesh (MultiHostWindowEngine): healthy,
# and after the "mesh minus one host" demotion (the surviving process's
# own single-host sharded engine — sticky for the process lifetime, a
# dead jax.distributed peer cannot rejoin a running job)
RUNG_NAME_MULTIHOST = "packed-multihost-pipelined"
RUNG_NAME_MESH_DEGRADED = "packed-sharded-mesh-minus-host"
# rung 0's name when the fused device-resident window loop is active
# (FusedWindowEngine, aggregator.fusedWindowK > 1): one lax.scan
# dispatch + one fetch per K windows. A device failure at this tier
# demotes WITHIN rung 0 to the packed-pipelined engine (the fused flag
# flips, like the mesh demotion) before the ordinary ladder applies.
RUNG_NAME_FUSED = "packed-fused-scan"

# per-mode checkpoint layout: required keys, and which key's last axis is
# the zone count Z. Temporal params serve through the dedicated history
# program (make_temporal_fleet_program), not the single-tick predictor
# registry — the aggregator accretes each workload's window itself.
_REQUIRED_PARAM_KEYS = {
    "mlp": ("w0", "b0", "w1", "b1", "w2", "b2", "w_skip"),
    "linear": ("weight", "bias"),
    "moe": ("gate_w", "w0", "b0", "w1", "b1", "w_skip"),
    "deep": ("in_proj", "in_bias", "blocks", "w_head", "b_head", "w_skip"),
    "temporal": ("in_proj", "pos_emb", "wq", "wk", "wv", "wo",
                 "w_mlp0", "w_mlp1", "w_head", "b_head", "w_skip"),
}
_OUTPUT_BIAS_KEY = {"mlp": "b2", "linear": "bias", "moe": "b1",
                    "deep": "b_head", "temporal": "b_head"}


@dataclass
class _Stored:
    report: NodeReport
    zone_names: tuple[str, ...]
    received: float
    seq: int
    run: str = ""  # agent-run nonce (empty for pre-nonce agents)
    # seq at which the report CONTENT last changed (wire v2 FLAG_SAME
    # deltas bump seq but keep this, so the window engine's per-row
    # identity short-circuits to zero staged bytes for unchanged nodes);
    # 0 = unknown → fall back to seq (v1 agents restage every window)
    content_seq: int = 0
    wire_version: int = 1


@dataclass
class _BaseRow:
    """One node's resident delta base: the last v2 keyframe accepted
    from it (count-capped LRU beside the seq trackers). Immutable once
    stored — replaced wholesale by the next keyframe, so delta merges
    read it without the store lock."""

    run: str
    seq: int
    report: NodeReport
    zone_names: tuple[str, ...]


def _primary_introspect(snap: Mapping[str, dict]) -> dict | None:
    """The engine snapshot the shard/staleness/skew metrics should read:
    the one actively holding resident rows. After a demotion both
    engines were reset and the DEMOTED rung's engine re-packs — the
    rung-0 engine reads empty until re-promotion, so preferring it
    unconditionally would blank the flight recorder exactly while the
    plane is degraded."""
    fused = snap.get("fused")
    pipelined = snap.get("pipelined")
    serial = snap.get("serial")
    if fused and fused["resident"]["rows"]:
        return fused
    if pipelined and pipelined["resident"]["rows"]:
        return pipelined
    if serial and serial["resident"]["rows"]:
        return serial
    return fused or pipelined or serial


def _report_power_w(report: NodeReport) -> float:
    """The node's self-reported power this window (valid zone energy
    over the window interval), the scoreboard's anomaly signal. Returns
    NaN when the report carries no usable window (the scoreboard skips
    non-finite magnitudes)."""
    dt = float(report.dt_s)
    if dt <= 0.0:
        return float("nan")
    valid = np.asarray(report.zone_valid, bool)
    deltas = np.asarray(report.zone_deltas_uj, np.float64)
    if valid.shape != deltas.shape or not valid.any():
        return float("nan")
    return float(deltas[valid].sum()) / dt / 1e6


@dataclass
class _Pending:
    """One dispatched, not-yet-published window in the pipeline.

    Everything here was SNAPSHOTTED at dispatch: fetching and publishing
    window N after window N+1 changed the fleet must never mix rows —
    the metadata (and, on the packed path, the resident batch version the
    program read) is this window's own.
    """

    kind: str  # "packed" | "legacy"
    out: object  # device handle(s): packed f16 array, or FleetResult
    meta: WindowMeta | None  # packed path row layout
    now: float  # publication timestamp (dispatch-time clock)
    assembly_ms: float
    dispatch_ms: float
    h2d_rows: int
    compiled: bool
    # packed path: per-shard H2D breakdown + shard count ((), 1 when the
    # dispatching engine was unsharded; legacy/numpy paths leave 1)
    h2d_shards: tuple = ()
    shards: int = 1
    # publish-fetch override from the dispatching engine's plan:
    # per-shard addressable fetch (owned shards only on the multi-host
    # engine). None = np.asarray of the whole output.
    fetch: Callable | None = None
    # fused path (kind "fused"): `out` is already a HOST slice of the
    # batch fetch. The whole batch's device cost is carried by its LAST
    # window (`dispatch_ms`; earlier windows publish with 0 — the K−1
    # free rides are the amortization), and sync_per_window_ms is the
    # honest averaged figure (−1 on non-fused windows).
    sync_per_window_ms: float = -1.0
    fused_fetch_ms: float = 0.0
    # legacy path extras (training dump + dense scatter)
    batch: object = None
    aligned: list | None = None
    zone_names: list | None = None
    feat_hist: object = None
    t_valid: object = None


class _FetchWorker:
    """One persistent daemon thread running window fetches, so the
    dispatch-timeout watchdog can bound them without spawning a thread
    per window (the healthy hot path publishes every interval forever).
    A fetch that exceeds its timeout abandons the WORKER — it stays
    parked in native code on the hung handle, which the ladder's ring
    re-seed guarantees nothing else reads — and the aggregator lazily
    replaces it on the next fetch."""

    __slots__ = ("_requests", "_thread")

    def __init__(self) -> None:
        self._requests: queue.Queue = queue.Queue()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="kepler-window-fetch")
        self._thread.start()

    # keplint: thread-role=fetch-worker
    def _loop(self) -> None:
        while True:
            fn, out = self._requests.get()
            if fn is None:
                return
            try:
                out.put(("value", fn()))
            except BaseException as err:  # relayed to the caller thread
                out.put(("error", err))

    def alive(self) -> bool:
        return self._thread.is_alive()

    def stop(self) -> None:
        self._requests.put((None, None))

    def run(self, fn: "Callable[[], object]",
            timeout: float) -> "tuple[str, object] | None":
        """→ ("value", result) | ("error", exc) | None on timeout (the
        worker is then permanently occupied — abandon it)."""
        out: queue.Queue = queue.Queue(maxsize=1)
        self._requests.put((fn, out))
        try:
            return out.get(timeout=timeout)
        except queue.Empty:
            return None


# the dedup/gap tracker moved to the PURE decision layer
# (fleet/delivery.py) so the kepmc protocol checker drives the exact
# observe/seed transitions this ingest path runs; the old private name
# stays as the module-local spelling
_SeqTracker = SeqTracker


class FleetResults:
    """One published fleet window, column-oriented.

    Publication is a handful of array references — no per-workload (or
    even per-node) Python happens per window; JSON materializes lazily
    per ``/v1/results`` request via :meth:`render_node`.

    Arrays are indexed by ROW via ``rows[name]`` — on the packed
    resident path nodes sit at stable row indices with holes, so
    ``names`` is the key list, never an implicit index order.

    On the packed path the per-workload matrices arrive as ONE f16
    watts array; the µW/µJ f32 materialization (two [N, W, Z] passes)
    is deferred to first access (``wl_power_uw``/``wl_energy_uj``
    properties) so the window hot loop never pays it — renders slice
    per row straight from the f16 plane."""

    __slots__ = ("timestamp", "zones", "names", "rows", "mode",
                 "node_power_uw", "node_energy_uj", "node_joules_total",
                 "workload_ids", "workload_kinds", "counts", "dt",
                 "_wl_watts_f16", "_wl_power_uw", "_wl_energy_uj")

    def __init__(self, timestamp: float, zones: list[str],
                 names: list[str], rows: dict[str, int], mode: np.ndarray,
                 node_power_uw: np.ndarray, node_energy_uj: np.ndarray,
                 node_joules_total: np.ndarray, workload_ids: list,
                 workload_kinds: list, counts: list,
                 wl_power_uw: np.ndarray | None = None,
                 wl_energy_uj: np.ndarray | None = None,
                 wl_watts_f16: np.ndarray | None = None,
                 dt: np.ndarray | None = None) -> None:
        self.timestamp = timestamp
        self.zones = zones
        self.names = names
        self.rows = rows
        self.mode = mode
        self.node_power_uw = node_power_uw
        self.node_energy_uj = node_energy_uj
        self.node_joules_total = node_joules_total
        self.workload_ids = workload_ids
        self.workload_kinds = workload_kinds
        self.counts = counts
        self.dt = dt
        self._wl_watts_f16 = wl_watts_f16
        self._wl_power_uw = wl_power_uw
        self._wl_energy_uj = wl_energy_uj

    def __contains__(self, name: str) -> bool:
        return name in self.rows

    @property
    def wl_power_uw(self) -> np.ndarray:
        if self._wl_power_uw is None:
            self._wl_power_uw = np.multiply(
                self._wl_watts_f16, 1e6, dtype=np.float32)
        return self._wl_power_uw

    @property
    def wl_energy_uj(self) -> np.ndarray:
        if self._wl_energy_uj is None:
            self._wl_energy_uj = self.wl_power_uw * self.dt[:, None, None]
        return self._wl_energy_uj

    def _row_wl(self, i: int, w: int) -> tuple[np.ndarray, np.ndarray]:
        """(power_uw [w, Z], energy_uj [w, Z]) for one row — slices the
        f16 plane directly when the full f32 planes were never forced."""
        if self._wl_power_uw is not None:
            return self._wl_power_uw[i, :w], self.wl_energy_uj[i, :w]
        power = np.multiply(self._wl_watts_f16[i, :w], 1e6,
                            dtype=np.float32)
        return power, power * float(self.dt[i])

    def render_node(self, name: str) -> dict:
        """The node's JSON payload (wire schema unchanged from the
        per-window-dict era)."""
        i = self.rows[name]
        w = self.counts[i]
        kinds = self.workload_kinds[i]
        power, energy = self._row_wl(i, w)
        return {
            "timestamp": self.timestamp,
            "zones": list(self.zones),
            "mode": int(self.mode[i]),
            "node_power_uw": self.node_power_uw[i].tolist(),
            "node_energy_uj": self.node_energy_uj[i].tolist(),
            "node_joules_total": self.node_joules_total[i].tolist(),
            "workloads": [
                {
                    "id": wid,
                    "kind": int(kinds[k]) if kinds is not None else -1,
                    "power_uw": p,
                    "energy_uj": e,
                }
                for k, (wid, p, e) in enumerate(zip(
                    self.workload_ids[i],
                    power.tolist(),
                    energy.tolist()))
            ],
        }


class Aggregator:
    """Service: report store + periodic sharded attribution."""

    # keplint: protocol-transition — ingest-state birth
    def __init__(
        self,
        server: APIServer,
        interval: float = 5.0,
        stale_after: float = 15.0,
        model_mode: str | None = "mlp",
        model_params: Mapping[str, np.ndarray] | None = None,
        node_bucket: int = 8,
        workload_bucket: int = 256,
        backend: str = "einsum",
        accuracy_mode: bool = False,
        history_window: int = 16,
        training_dump_dir: str = "",
        training_dump_max_files: int = 1000,
        skew_tolerance: float = 120.0,
        degraded_ttl: float = 60.0,
        dedup_window: int = 1024,
        delivery_buckets: Sequence[float] | None = None,
        pipeline_depth: int = 1,
        fused_window_k: int = 1,
        bucket_shrink_after: int = 16,
        fallback_enabled: bool = True,
        repromote_after: int = 8,
        dispatch_timeout: float = 30.0,
        mesh_shape: Sequence[int] | None = None,
        mesh_axes: Sequence[str] | None = None,
        multihost_enabled: bool = False,
        multihost_takeover: bool = True,
        multihost_topology: Mapping[str, Any] | None = None,
        membership_auto_apply: bool = False,
        membership_autoscale: bool = False,
        membership_scale_up_load: float = 1.0,
        membership_scale_down_load: float = 0.25,
        membership_up_windows: int = 3,
        membership_down_windows: int = 12,
        membership_min_replicas: int = 1,
        membership_max_replicas: int = 0,
        membership_standby_peers: Sequence[str] | None = None,
        membership_probe_timeout: float = 2.0,
        membership_topology: Mapping[str, Any] | None = None,
        scoreboard_cap: int = 1024,
        anomaly_z: float = 4.0,
        peers: Sequence[str] | None = None,
        self_peer: str = "",
        ring_epoch: int = 1,
        ring_vnodes: int = 64,
        admission_enabled: bool = False,
        admission_max_inflight: int = 64,
        admission_latency_budget: float = 0.25,
        admission_retry_after: float = 1.0,
        admission_retry_after_max: float = 30.0,
        admission_jitter_seed: int | None = None,
        base_row_cache: int = 1024,
        clock: Callable[[], float] | None = None,
        mesh: Any = None,
        journal: EventJournal | None = None,
        hlc_max_drift: float = 60.0,
    ) -> None:
        self._server = server
        self._interval = interval
        self._stale_after = stale_after
        self._model_mode = model_mode
        self._params = model_params
        self._node_bucket = node_bucket
        self._workload_bucket = workload_bucket
        self._backend = backend
        # serve estimators at f32/highest precision (the configuration the
        # 0.5% accuracy budget is validated under); bf16 = throughput mode
        self._accuracy_mode = accuracy_mode
        self._clock = clock or _time.time
        # fleet black box: every state transition below goes through the
        # journal chokepoint; the default is a disabled per-instance
        # journal (one attribute check per emission) on this replica's
        # clock seam, so library/test construction costs nothing and
        # chaos replicas never share clocks
        self._journal = journal if journal is not None else EventJournal(
            enabled=False, node=str(self_peer or ""), clock=self._clock,
            max_drift_s=hlc_max_drift)
        # admission-shed ONSET edge (False→True) is a journal event; the
        # return to admitting resets the edge detector — steady-state
        # shedding emits nothing (the journal records transitions, rates
        # live in the admission controller's own counters)
        self._shedding = False  # keplint: guarded-by=_lock
        # /debug/bundle stamps a config fingerprint so two bundles from
        # "the same fleet" are checkably from the same rollout
        self._config_fingerprint = hashlib.sha256(canonical_json({
            "self_peer": str(self_peer or ""),
            "interval": float(interval),
            "stale_after": float(stale_after),
            "model_mode": str(model_mode or ""),
            "multihost": bool(multihost_enabled),
            "hlc_max_drift": float(hlc_max_drift),
        })).hexdigest()[:16]
        self._mesh = mesh
        # aggregator.meshShape/meshAxes: the device mesh the packed
        # window path actually runs on ([] = all devices, 1-D node axis
        # — the sharded production shape)
        self._mesh_shape = list(mesh_shape or [])
        self._mesh_axes = list(mesh_axes or [])
        # -- multi-host SPMD tier (ISSUE 15): with multihost enabled and
        # a mesh spanning > 1 process, rung 0 runs the
        # MultiHostWindowEngine (host-local rings + one SPMD dispatch)
        # and ingest ownership derives from the mesh shard map
        # (ring_from_mesh). A cross-host failure demotes STICKY to the
        # surviving single-host engine ("mesh minus one host" — a dead
        # jax.distributed peer cannot rejoin a running job), bumping the
        # ring epoch so displaced agents follow 421s to the new owner.
        self._multihost_enabled = bool(multihost_enabled)
        self._multihost_takeover = bool(multihost_takeover)
        topo = dict(multihost_topology or {})
        self._mh_process_index: int | None = topo.get("process_index")
        self._mh_device_process = topo.get("device_process")
        self._mh_fabric = topo.get("fabric")
        self._mesh_degraded = False  # keplint: guarded-by=_results_lock
        self._engine_mesh: Any = None  # mesh the packed engines run on
        # temporal mode: per-node feature-history ring buffers, fed on
        # report receipt so the window advances at each node's own cadence.
        # Each node's buffer carries its OWN lock: ingest for node A never
        # stalls on the [N, W, T, F] assembly reading node B, and the
        # assembly never holds the report-store lock at all (VERDICT r3
        # weak #4: history assembly used to stall every /v1/report POST).
        self._history_window = history_window
        self._history: dict[str, tuple[threading.Lock, "HistoryBuffer"]] = {}
        # training-data capture: RAPL nodes' windows + their ratio watts
        # become (features, labels) files for cmd/train (the
        # kepler-model-server train→serve loop, BASELINE configs 3-4)
        self._dump_dir = training_dump_dir
        self._dump_max_files = max(1, training_dump_max_files)
        self._dump_seq = 0
        self._dump_files: list[str] | None = None  # seeded on first dump

        # report quarantine: a malformed or clock-skewed report is rejected
        # BEFORE it can poison the batch, and the offense is charged to the
        # sending node so operators see WHICH node degrades (the reference
        # only ages bad nodes out silently). Entries decay after
        # ``degraded_ttl`` of good behavior.
        self._skew_tolerance = skew_tolerance
        self._degraded_ttl = degraded_ttl
        self._degraded: dict[str, dict] = {}
        # names come from (possibly hostile) malformed payloads: bound the
        # table (oldest offender evicted) and the per-name length so a
        # garbage flood can't grow memory or log volume without limit
        self._degraded_cap = 64
        self._degraded_name_cap = 128

        self._lock = threading.Lock()
        self._reports: dict[str, _Stored] = {}  # keplint: guarded-by=_lock
        # per-node run nonces superseded by restarts: a network-delayed
        # straggler from ANY previous agent run must not be re-classified
        # as yet another restart (that would overwrite the fresher run's
        # report, push a spurious temporal history window, and mark the
        # LIVE run as superseded — going dark until the next restart).
        # A bounded per-node list (oldest dropped) keeps memory O(nodes).
        self._superseded_runs: dict[str, list[str]] = {}
        self._superseded_cap = 16
        # idempotent ingest + loss accounting: per-node seq trackers for
        # the CURRENT run (spool replays dedupe; seq jumps become
        # kepler_fleet_windows_lost_total). Trackers deliberately OUTLIVE
        # batch staleness: a partition longer than stale_after followed
        # by a spool replay must resume from max_seen, not fabricate a
        # loss spike and re-ingest delivered windows. Bounded by count
        # instead (least-recently-observed evicted at the cap), like the
        # cumulative loss table.
        self._dedup_window = max(1, dedup_window)
        # end-to-end delivery latency: the agent stamps a trace id +
        # emitted_at at window emit; the accepted (non-duplicate) ingest
        # closes the trace here. Replays measure from the spool's
        # original appended_at under their own label so outage backlogs
        # never pollute the fresh-delivery signal.
        self._delivery_hist: dict[str, Histogram] = {  # keplint: guarded-by=_lock
            path: Histogram(delivery_buckets or DEFAULT_DELIVERY_BUCKETS)
            for path in ("fresh", "replay")}
        self._seq_trackers: dict[str, _SeqTracker] = {}  # keplint: guarded-by=_lock
        self._tracker_cap = 512
        # wire v2 delta bases: per-node last accepted keyframe, the
        # state a delta frame merges against. Count-capped LRU (dict
        # order = recency; oldest evicted) beside the seq trackers — a
        # delta whose base was evicted is answered with a structured
        # 409 needs-keyframe and the agent resends full, so eviction is
        # a round-trip, never corruption or loss.
        self._base_rows: dict[str, _BaseRow] = {}  # keplint: guarded-by=_lock
        self._base_row_cache = max(1, int(base_row_cache))
        self._lost_by_node: dict[str, int] = {}  # keplint: guarded-by=_lock
        self._lost_node_cap = 256
        # fleet scoreboard: one synthesized health row per node (state
        # machine + rolling power z-score), LRU-capped, updated at ingest
        # and served via /debug/fleet + kepler_fleet_node_state
        self._scoreboard = FleetScoreboard(  # keplint: guarded-by=_lock
            cap=scoreboard_cap, anomaly_z=anomaly_z,
            flag_ttl=degraded_ttl)
        # HA ingest ring (ISSUE 11): with peers configured, this replica
        # accepts only the nodes the consistent-hash ring assigns it and
        # answers everyone else with a structured 421 owner redirect.
        # The ring object is IMMUTABLE — a membership change swaps in a
        # new one wholesale (apply_membership), so the ingest hot path
        # reads it without the store lock.
        self._ring: HashRing | None = None
        self._self_peer = str(self_peer or "")
        self._ring_vnodes = max(1, int(ring_vnodes))
        # config-ORDER peer list (HashRing sorts; the mesh ring needs
        # process-index order: peers[p] = process p's endpoint)
        self._config_peers = list(peers or [])
        self._ring_epoch_cfg = max(1, int(ring_epoch))
        if peers:
            if not self._self_peer:
                raise ValueError(
                    "aggregator.selfPeer must name this replica when "
                    "aggregator.peers is set")
            self._ring = HashRing(peers, epoch=max(1, int(ring_epoch)),
                                  vnodes=self._ring_vnodes)
            if self._self_peer not in self._ring:
                raise ValueError(
                    f"aggregator.selfPeer {self_peer!r} is not in "
                    f"aggregator.peers {list(self._ring.peers)!r}")
        self._last_redirect_at: float | None = None  # keplint: guarded-by=_lock
        self._last_membership_at: float | None = None  # keplint: guarded-by=_lock
        # -- elastic membership (ISSUE 16): coordinator lease +
        # deterministic succession + runtime join/leave + autoscale.
        # The lease is DERIVED state, advanced in lock-step with the
        # ring epoch by apply_membership; its initial holder is the
        # lowest configured peer, so every replica starts agreeing.
        # Succession (plan_succession) replaces the old 2-host-only
        # takeover gate: on a host death at ANY mesh size exactly one
        # survivor — the incumbent holder while it lives, else the
        # lowest surviving peer — issues the survivor membership.
        self._lease: CoordinatorLease | None = None
        if self._ring is not None:
            self._lease = CoordinatorLease(
                elect_successor(self._config_peers),
                epoch=self._ring.epoch)
        mtopo = dict(membership_topology or {})
        # test seams for the liveness probe and the membership POST
        # (defaults: HTTP /healthz GET and /v1/membership POST)
        self._peer_alive_fn = mtopo.get("peer_alive")
        self._deliver_fn = mtopo.get("deliver")
        self._membership_probe_timeout = max(
            0.1, float(membership_probe_timeout))
        self._membership_auto_apply = bool(membership_auto_apply)
        self._standby_peers = list(membership_standby_peers or [])
        # "degraded, awaiting membership": a survivor that is NOT the
        # succession issuer (or has succession disabled) holds position
        # until the issuer's membership broadcast arrives — surfaced by
        # the fleet-window probe and the awaiting gauge
        self._awaiting_membership = False  # keplint: guarded-by=_results_lock
        # armed fabric incarnation for the next mesh-path membership (a
        # rejoin's fresh HostLocalFabric; production analog: restart the
        # jax.distributed job before re-applying the full set)
        self._mesh_arm: Any = None
        self._mesh_elastic: Any = None  # live (possibly sub-) mesh
        self._membership_rejected: dict[str, int] = {}  # keplint: guarded-by=_lock
        self._membership_applied: dict[str, int] = {}  # keplint: guarded-by=_lock
        self._autoscale: AutoscalePolicy | None = None
        if membership_autoscale:
            self._autoscale = AutoscalePolicy(
                scale_up_load=membership_scale_up_load,
                scale_down_load=membership_scale_down_load,
                up_windows=membership_up_windows,
                down_windows=membership_down_windows,
                min_replicas=membership_min_replicas,
                max_replicas=membership_max_replicas)
        self._autoscale_last: AutoscaleDecision | None = None  # keplint: guarded-by=_results_lock
        self._autoscale_decisions: dict[str, int] = {}  # keplint: guarded-by=_results_lock
        self._autoscale_shed_seen = 0
        # overload control (ISSUE 12): an AdmissionController in front of
        # the ingest path sheds with 429 + Retry-After BEFORE decode work
        # when the inflight or latency budget is blown — priority-aware,
        # so replay backlogs wait first and live RAPL ground truth sheds
        # last. Disabled (None) keeps the pre-admission ingest path
        # byte-for-byte: shedding off ≡ old behavior.
        self._admission: AdmissionController | None = None
        if admission_enabled:
            self._admission = AdmissionController(
                max_inflight=admission_max_inflight,
                latency_budget=admission_latency_budget,
                retry_after=admission_retry_after,
                retry_after_max=admission_retry_after_max,
                degraded_ttl=degraded_ttl,
                jitter_seed=admission_jitter_seed)
        self._results_lock = threading.Lock()
        self._results: FleetResults | None = None  # keplint: guarded-by=_results_lock
        self._last_window_at: float | None = None
        self._stats = {"reports_total": 0, "rejected_total": 0,
                       "quarantined_total": 0, "malformed_total": 0,
                       "clock_skew_total": 0,
                       "reports_redirected_total": 0,
                       # wire v2: deltas answered 409 needs-keyframe
                       # (missing/mismatched base — agent resends full)
                       "keyframe_requests_total": 0,
                       "duplicates_total": 0, "windows_lost_total": 0,
                       "attributions_total": 0, "last_batch_nodes": 0,
                       "last_batch_workloads": 0,
                       # whole-window cost (sum of the legs below — in
                       # pipelined mode wall time spans two calls, so the
                       # sum is the honest per-window figure)
                       "last_attribution_ms": 0.0,
                       # its legs, so a regression is attributable
                       "last_assembly_ms": 0.0,
                       "last_device_ms": 0.0,
                       "last_scatter_ms": 0.0,
                       # pipelined-window legs + delta-H2D accounting
                       "last_dispatch_ms": 0.0,
                       "last_wait_ms": 0.0,
                       # publish-fetch leg alone (per-shard addressable
                       # D2H materialization inside the pipeline wait)
                       "last_fetch_ms": 0.0,
                       # fused tier: device sync cost averaged over the
                       # windows of the last flushed batch (0 until the
                       # fused tier publishes)
                       "last_sync_per_window_ms": 0.0,
                       "last_h2d_rows": 0,
                       # sharded window: device shards the last window ran
                       # over (1 = unsharded engine or demoted rung) and
                       # the per-shard H2D breakdown
                       "window_shards": 0,
                       "last_h2d_shards": [],
                       # sticky-map load skew: max/mean per-shard row
                       # occupancy (1.0 = balanced, 0 = no rows yet)
                       "shard_skew": 0.0,
                       "window_compiles_total": 0,
                       # degradation ladder (0 = healthy full path)
                       "window_rung": 0,
                       "window_demotions_total": 0,
                       "window_repromotions_total": 0}
        # ingest payload bytes by wire version (the v1↔v2 byte-savings
        # evidence: kepler_fleet_ingest_bytes_total{version})
        self._ingest_bytes: dict[int, int] = {1: 0, 2: 0}  # keplint: guarded-by=_lock
        # cumulative per-node energy for _total counters: a shared dense
        # RowStore (the same machinery as the monitor's per-workload
        # accumulators) whose columns follow the canonical zone axis and
        # remap BY NAME when it changes. Survives a node briefly falling
        # out of the batch, pruned after _cum_retention of total silence.
        self._cum = RowStore(0, initial_rows=0)
        self._cum_zones: list[str] = []
        self._cum_last_seen: dict[str, float] = {}
        self._cum_retention = max(stale_after * 20.0, 600.0)
        self._program = None  # legacy-path jit; jax caches per input shape
        # untrained fallbacks per zone count — never clobber trained params
        self._fallback_params: dict[int, object] = {}
        # -- window pipeline (fleet.window) --------------------------------
        # depth 1 = serial (dispatch then fetch in the same call, the
        # library-call contract every aggregate_once() test relies on);
        # depth D ≥ 2 keeps D−1 windows in flight: the fetch/scatter of
        # window N overlaps window N+1's assembly+dispatch, and published
        # results are at most D−1 intervals stale. The deque normally
        # belongs to the aggregation loop alone, but shutdown() may drain
        # it from the lifecycle thread when the runner overruns its join
        # timeout — _pipeline_lock serializes those drains (uncontended
        # in steady state; never held during dispatch).
        self._pipeline_depth = max(1, int(pipeline_depth))
        self._bucket_shrink_after = max(1, int(bucket_shrink_after))
        self._pipeline_lock = threading.Lock()
        self._inflight: collections.deque[_Pending] = collections.deque()  # keplint: guarded-by=_pipeline_lock
        # rung-0 engine: ShardedWindowEngine on a multi-device 1-D node
        # mesh (per-shard rings, sticky assignment), PackedWindowEngine
        # otherwise; _engine_serial is the single-device demotion engine
        # the ladder's packed-serial rung uses when rung 0 is sharded
        self._engine: PackedWindowEngine | None = None
        self._engine_serial: PackedWindowEngine | None = None
        self._shard_count = 1  # set in init() from the mesh shape
        # -- fused device-resident window loop (aggregator.fusedWindowK):
        # K > 1 replaces the rung-0 tier with the FusedWindowEngine —
        # host-only staging per interval, ONE lax.scan dispatch + one
        # batched fetch per K windows. Published windows stay within the
        # ladder's ≤ depth−1 staleness contract with K as the depth.
        # Single-host only: the multi-host tier has its own ring story.
        self._fused_window_k = max(1, int(fused_window_k))
        self._engine_fused: FusedWindowEngine | None = None
        # a device failure at the fused tier flips this (rung 0 stays,
        # its engine drops to packed-pipelined — the mesh demotion's
        # shape); repromote_after clean windows at rung 0 clear it
        self._fused_degraded = False  # keplint: guarded-by=_results_lock
        # per-un-flushed-window aggregation snapshots, oldest first,
        # parallel to the fused engine's pending ring: (stored_sorted,
        # zone_names, now, t_win). Popped as the flush publishes; after
        # a failure resets the engine these are ORPHANED and
        # _replay_fused_pending republishes them at the demoted tier —
        # the zero-gaps invariant. Aggregation-loop-only state.
        self._fused_pending: list[tuple] = []
        # -- device-plane degradation ladder (fleet.window faults) ---------
        # state is written only by the aggregation loop; reads from the
        # probe/metrics threads snapshot under _results_lock
        self._fallback_enabled = bool(fallback_enabled)
        self._repromote_after = max(1, int(repromote_after))
        self._dispatch_timeout = max(0.0, float(dispatch_timeout))
        self._rung = RUNG_PIPELINED  # keplint: guarded-by=_results_lock
        self._clean_windows = 0  # consecutive clean at the current rung
        self._windows_since_failure = 0
        # rung timeline: a bounded ring of ladder transitions (rung,
        # reason, monotonic + wall time, windows spent at the previous
        # rung) behind the ladder — the flight recorder's "when did we
        # degrade, why, and for how long" answer, served by the probe
        # and /debug/window. Published windows tick _windows_at_rung.
        self._rung_timeline: collections.deque[dict] = collections.deque(  # keplint: guarded-by=_results_lock
            maxlen=64)
        self._windows_at_rung = 0
        # per-window engine introspection snapshot (computed by the
        # publish path, read by /debug/window + collect off-thread)
        self._introspect_cache: dict = {}  # keplint: guarded-by=_results_lock
        # failed-probe backoff (the breaker's doubling cooldown, ladder-
        # shaped): a demotion that lands before a just-promoted rung
        # proves itself doubles the clean-window threshold for the next
        # probe (capped), so probing a permanently wedged device — each
        # stall probe abandons one fetch worker — has a DECAYING cadence,
        # not a constant leak rate. Reset on reaching full health.
        self._probe_penalty = 1
        self._probe_penalty_cap = 64
        self._just_promoted = False
        self._last_window_failure = ""
        self._demotions_by_reason: dict[str, int] = {}  # keplint: guarded-by=_results_lock
        # lazy, replaced after a stall abandons it; used only by the
        # publish path (serialized by _pipeline_lock)
        self._fetch_worker: _FetchWorker | None = None

    def name(self) -> str:
        return "fleet-aggregator"

    # -- lifecycle ---------------------------------------------------------

    def init(self) -> None:
        if self._mesh is None:
            from kepler_tpu.parallel.mesh import NODE_AXIS

            self._mesh = make_mesh(self._mesh_shape,
                                   self._mesh_axes or (NODE_AXIS,))
        n_dev = self._mesh.devices.size
        # the node axis shards over the mesh: round the bucket up so padded
        # batches always divide evenly across devices
        if self._node_bucket % n_dev:
            self._node_bucket = ((self._node_bucket // n_dev) + 1) * n_dev
        self._shard_count = self._mesh_shard_count()
        if self._ring is not None and self._multihost_active():
            # co-locate ingest with compute (ISSUE 15): ownership derives
            # from the mesh shard map — each replica ingests exactly the
            # agents whose packed rows live on its local devices.
            # aggregator.peers is ordered by jax process index here.
            proc = self._device_process_fn()
            shard_procs = [proc(d) for d in self._mesh.devices.flat]
            n_hosts = len(set(shard_procs))
            if len(self._config_peers) != n_hosts:
                raise ValueError(
                    f"aggregator.peers has {len(self._config_peers)} "
                    f"entries but the multi-host mesh spans {n_hosts} "
                    "processes — one peer endpoint per process, in "
                    "process-index order")
            me = self._self_process()
            if (0 <= me < len(self._config_peers)
                    and self._config_peers[me] != self._self_peer):
                # a misordered list would silently INVERT ownership:
                # every replica ingesting exactly the OTHER host's
                # agents — fail loudly instead
                raise ValueError(
                    f"aggregator.peers[{me}] is "
                    f"{self._config_peers[me]!r} but this replica "
                    f"(process {me}) is aggregator.selfPeer "
                    f"{self._self_peer!r} — the list must be ordered "
                    "by jax process index")
            self._ring = ring_from_mesh(self._config_peers, shard_procs,
                                        epoch=self._ring_epoch_cfg)
            log.info("ingest ring derived from the mesh shard map: "
                     "%d shards over %d hosts, epoch %d, self owns "
                     "%.3f of the shard space", self._ring.n_shards,
                     n_hosts, self._ring.epoch,
                     self._ring.ownership_ratio(self._self_peer))
        if self._model_mode:
            if self._model_mode != "temporal":
                from kepler_tpu.models.estimator import predictor

                # fail at startup on unservable mode; temporal serves via
                # its dedicated history program instead of the registry
                predictor(self._model_mode)
            self._check_params_shape()
            if self._params is None:
                log.warning("no trained %s params given; estimates will use "
                            "untrained initialization", self._model_mode)
        self._server.register("/v1/report", "Fleet ingest",
                              "POST node window reports", self._handle_report,
                              max_body=MAX_REPORT_BYTES)
        self._server.register("/v1/reports", "Fleet batch ingest",
                              "POST a batch of node window reports "
                              "(length-prefixed envelope; per-record "
                              "status in the JSON response) — the "
                              "spool-drain replay path",
                              self._handle_report_batch,
                              max_body=MAX_REPORT_BYTES)
        self._server.register("/v1/results", "Fleet results",
                              "attributed watts per node", self._handle_results)
        self._server.register("/debug/window", "Window introspection",
                              "device-plane engine state: rung + "
                              "timeline, shards, bucket ladders, "
                              "compile-cache cost stats",
                              self._handle_window_debug)
        self._server.register("/debug/fleet", "Fleet scoreboard",
                              "per-node health state table",
                              self._handle_fleet_debug)
        self._server.register("/debug/ring", "Ingest ring",
                              "consistent-hash ingest ring: membership "
                              "epoch, peers, ownership share, redirect "
                              "counters", self._handle_ring_debug)
        self._server.register("/debug/journal", "Fleet black box",
                              "HLC-stamped causal event journal "
                              "(?since=<hlc cursor>&limit=N paginates)",
                              make_journal_handler(self._journal))
        self._server.register("/debug/bundle", "Incident bundle",
                              "one-shot incident snapshot: journal + "
                              "rung timeline + scoreboard + ring + "
                              "config fingerprint (canonical JSON — "
                              "feed to python -m kepler_tpu.blackbox)",
                              self._handle_bundle_debug)
        if self._ring is not None:
            self._server.register("/v1/membership", "Elastic membership",
                                  "POST apply/join/leave membership "
                                  "operations (coordinator-lease gated)",
                                  self._handle_membership)
        health = getattr(self._server, "health", None)
        if health is not None:
            health.register_probe("fleet-aggregator", self.health)
            health.register_probe("fleet-window", self.window_health)
            if self._ring is not None:
                health.register_probe("fleet-ring", self.ring_health)
            if self._admission is not None:
                # degraded while shedding — the "ingest tier is actively
                # re-pacing its agents" signal; recovers on its own
                health.register_probe("fleet-ingest",
                                      self._admission.health)
            # ready once init completed: endpoints registered, mesh built,
            # params validated — an empty fleet is still a ready aggregator
            health.register_readiness("fleet-aggregator",
                                      lambda: {"ok": True})
        log.info("aggregator: mesh=%s devices=%d model=%s interval=%.1fs",
                 dict(self._mesh.shape), n_dev, self._model_mode,
                 self._interval)

    def _mesh_shard_count(self, mesh: Any = None) -> int:
        """Shards the packed window runs over: the node-axis size when
        the mesh is 1-D over ``node`` (every device an independent
        shard with its own resident ring). Single-device and 2-D
        (node × model) meshes run the unsharded engine — their batch
        still shards via NamedSharding, but H2D stays whole-batch."""
        from kepler_tpu.parallel.mesh import NODE_AXIS

        mesh = mesh if mesh is not None else self._mesh
        if mesh is None:
            return 1
        n_dev = mesh.devices.size
        if n_dev > 1 and dict(mesh.shape).get(NODE_AXIS, 0) == n_dev:
            return n_dev
        return 1

    # -- multi-host topology -----------------------------------------------

    def _device_process_fn(self) -> Callable[[Any], int]:
        if self._mh_device_process is not None:
            return self._mh_device_process
        return lambda d: int(getattr(d, "process_index", 0))

    def _self_process(self) -> int:
        if self._mh_process_index is not None:
            return int(self._mh_process_index)
        import jax

        return int(jax.process_index())

    def _multihost_active(self) -> bool:
        """True when rung 0 should run the multi-host engine: multihost
        enabled, a 1-D node mesh, and devices spanning > 1 process
        (real ``jax.distributed`` processes, or the injected virtual
        topology the tests/bench drive in one process)."""
        if not self._multihost_enabled or self._mesh is None:
            return False
        from kepler_tpu.parallel.mesh import NODE_AXIS

        mesh = self._live_mesh()
        n_dev = mesh.devices.size
        if n_dev < 2 or dict(mesh.shape).get(NODE_AXIS, 0) != n_dev:
            return False
        proc = self._device_process_fn()
        return len({proc(d) for d in mesh.devices.flat}) > 1

    def _live_mesh(self) -> Any:
        """The mesh the multi-host tier currently runs on: the full
        configured mesh, or the elastic submesh the last mesh-path
        membership restored over a peer subset."""
        return (self._mesh_elastic if self._mesh_elastic is not None
                else self._mesh)

    def _local_mesh(self) -> Any:
        """The surviving single-host mesh after a mesh demotion: this
        process's own devices, 1-D over node."""
        return submesh_for_processes(self._mesh, [self._self_process()],
                                     self._device_process_fn())

    def _multihost_host_count(self) -> int:
        if self._mesh is None:
            return 1
        proc = self._device_process_fn()
        return len({proc(d) for d in self._live_mesh().devices.flat})

    def _demote_mesh(self, reason: str) -> None:
        """The "mesh minus one host" tier: a cross-host window failure
        (dead peer, broken collective, fabric loss) retires the
        multi-host engine in this process — the survivors' rung 0
        becomes their own single-host sharded engine (full ring
        re-seed via the engine rebuild). Within the current fabric
        incarnation the demotion is sticky; a rejoin
        (``/v1/membership`` join + :meth:`arm_mesh`) restores the
        multi-host tier under a NEW incarnation.

        Ring healing runs by DETERMINISTIC SUCCESSION at any mesh
        size (ISSUE 16; the old 2-host-only takeover gate is
        retired): every survivor probes the peer set and computes the
        same entitled issuer — the incumbent lease holder while it
        survives, else the lowest surviving peer. Exactly ONE
        survivor therefore bumps the epoch and broadcasts the
        survivor membership; the rest hold position "degraded,
        awaiting membership" until the broadcast lands. The
        equal-epoch conflict check at apply stays as the backstop a
        partitioned prober could still trip. Displaced agents follow
        421s to the new owners and replay their spool tails — the
        existing hand-off machinery, zero windows lost."""
        self._engine = None  # next window rebuilds over the local mesh
        self._engine_serial = None  # its pinned device must be LOCAL
        self._mesh_elastic = None  # the elastic submesh died with the peer
        log.error("multi-host mesh degraded (%s): demoting to the "
                  "single-host engine over this process's devices; "
                  "displaced agents will be redirected by epoch bump",
                  reason)
        if self._ring is None:
            return
        if not self._multihost_takeover:
            # succession disabled: the operator owns the rebalance —
            # flag the wait so the probe says WHY ingest is degraded
            with self._results_lock:
                self._awaiting_membership = True
            return
        survivors = self._probe_survivors()
        if set(survivors) == set(self._ring.peers):
            # the issuer's broadcast landed BEFORE this process noticed
            # the death: membership already reflects the survivor set,
            # so there is neither a bump to issue nor one to await
            return
        holder = self._lease.holder if self._lease is not None else ""
        issuer = plan_succession(holder, survivors)
        if issuer != self._self_peer:
            with self._results_lock:
                self._awaiting_membership = True
            log.warning(
                "mesh demotion: membership succession belongs to "
                "surviving peer %s (lease %s) — holding position, "
                "awaiting its membership broadcast", issuer,
                self._lease.lease_id if self._lease is not None
                else "?")
            return
        epoch = self._ring.epoch + 1
        try:
            self.apply_membership(survivors, epoch,
                                  source="succession",
                                  issuer=self._self_peer)
        except ValueError as err:
            log.error("mesh-demotion succession failed: %s", err)
            with self._results_lock:
                self._awaiting_membership = True
            return
        self._broadcast_membership(survivors, epoch)

    def run(self, ctx: CancelContext) -> None:
        while not ctx.cancelled():
            if ctx.wait(self._interval):
                break
            try:
                self.aggregate_once()
            except Exception:
                log.exception("fleet aggregation failed")
        # deterministic drain: every dispatched window is published before
        # the loop exits — no result is abandoned in flight on shutdown
        try:
            self._drain_pipeline()
        except Exception:
            log.exception("fleet pipeline drain failed")

    # keplint: thread-role=shutdown
    def shutdown(self) -> None:
        # idempotent with the run()-exit drain (the deque is empty then);
        # covers direct aggregate_once() users who never ran the loop
        self._drain_pipeline()
        worker, self._fetch_worker = self._fetch_worker, None
        if worker is not None:
            worker.stop()
        self._journal.close()

    # -- ingest ------------------------------------------------------------

    def _handle_report(
            self, request: Any) -> tuple[int, dict[str, str], bytes]:
        # one telemetry cycle per ingest POST, with the decode and merge
        # legs as stages — the receive half of the delivery trace the
        # agent opened at window emit
        with telemetry.span("aggregator.ingest"):
            ctrl = self._admission
            if request.command != "POST":
                return self._ingest_report(request)
            if not self._observe_request_hlc(request):
                return self._bad_hlc_response()
            # ONE header parse per record, carried from the admission
            # peek through _ingest_payload (v1 used to re-parse the
            # same JSON up to four times; v2 makes this a struct read)
            parsed = try_parse_header(request.body)
            if ctrl is None:
                return self._ingest_report(request, parsed)
            # admission runs BEFORE any decode work: over budget the
            # request is turned away at header-peek cost, and the spool
            # on the agent side makes that loss-free — the record stays
            # durable and replays after the Retry-After hint
            retry = ctrl.admit(self._priority_of(request.body, parsed))
            if retry is not None:
                return self._throttle_response(retry)
            self._note_admitted()
            t0 = _time.perf_counter()
            try:
                return self._ingest_report(request, parsed)
            finally:
                ctrl.done(_time.perf_counter() - t0)

    def _handle_report_batch(
            self, request: Any) -> tuple[int, dict[str, str], bytes]:
        """``POST /v1/reports``: the batched spool-drain path. Each
        record runs through the SAME single-report ingest internals
        (per-record admission, dedup, quarantine, redirect), and the
        response carries a per-record status list — so one request
        replays K spooled records while every delivery/loss/dedup
        invariant stays per-record. Once admission sheds mid-batch, the
        remaining records are answered 429 without being looked at (the
        whole point is to stop paying decode cost)."""
        with telemetry.span("aggregator.ingest"):
            if request.command != "POST":
                return 405, {"Content-Type": "text/plain"}, b"POST only\n"
            if not self._observe_request_hlc(request):
                return self._bad_hlc_response()
            if fault.fire("replica.down") is not None:
                return (503, {"Content-Type": "text/plain"},
                        b"replica down (fault injection)\n")
            try:
                payloads = decode_report_batch(request.body)
            except WireError as err:
                with self._lock:
                    self._stats["rejected_total"] += 1
                    self._stats["malformed_total"] += 1
                return (400, {"Content-Type": "text/plain"},
                        f"{err}\n".encode())
            ctrl = self._admission
            results: list[dict[str, Any]] = []
            shed_retry: float | None = None
            for body in payloads:
                if shed_retry is not None:
                    # stop paying even peek cost once shedding started
                    results.append({"status": 429,
                                    "retry_after": shed_retry})
                    continue
                parsed = try_parse_header(body)
                if ctrl is not None:
                    retry = ctrl.admit(self._priority_of(body, parsed))
                    if retry is not None:
                        shed_retry = retry
                        self._note_shed_onset(retry)
                        results.append({"status": 429,
                                        "retry_after": retry})
                        continue
                    self._note_admitted()
                t0 = _time.perf_counter()
                try:
                    status, resp_headers, resp_body = \
                        self._ingest_payload(body, parsed)
                finally:
                    if ctrl is not None:
                        ctrl.done(_time.perf_counter() - t0)
                row: dict[str, Any] = {"status": status}
                if status == 421 or (
                        status == 409
                        and resp_headers.get(
                            "X-Kepler-Needs-Keyframe")):
                    # structured responses (owner redirect, needs-
                    # keyframe) keep their JSON shape per record, so
                    # the agent's guards see the same fields as on the
                    # single-record path
                    try:
                        row.update(json.loads(resp_body))
                    except ValueError:
                        pass
                elif status >= 400:
                    row["error"] = resp_body.decode(
                        errors="replace").strip()[:200]
                results.append(row)
            headers = {"Content-Type": "application/json",
                       **self._epoch_headers()}
            if shed_retry is not None:
                headers["Retry-After"] = f"{shed_retry:g}"
            return (200, headers,
                    json.dumps({"results": results}).encode())

    def _throttle_response(
            self, retry: float) -> tuple[int, dict[str, str], bytes]:
        self._note_shed_onset(retry)
        body = json.dumps({"retry_after": retry}).encode()
        return (429, {"Content-Type": "application/json",
                      "Retry-After": f"{retry:g}",
                      **self._epoch_headers()}, body)

    def _note_shed_onset(self, retry: float) -> None:
        """Journal the admission-shed ONSET (False→True edge only —
        steady-state shedding is a rate, not an event)."""
        with self._lock:
            onset = not self._shedding
            self._shedding = True
        if onset:
            self._journal.emit("admission.shed",
                               retry_after=round(float(retry), 3))

    def _note_admitted(self) -> None:
        """An admitted request closes the shed episode: the NEXT shed
        is a fresh onset."""
        if self._shedding:
            with self._lock:
                self._shedding = False

    def _priority_of(self, body: bytes,
                     parsed: "ParsedHeader | None" = None) -> int:
        """Admission priority from a CHEAP header peek (no array decode):
        replay backlogs behind fresh windows, model-estimated nodes
        behind RAPL ground truth, scoreboard-flagged reporters behind
        healthy ones — live attribution accuracy degrades last. With a
        ``parsed`` memo the peek is a dict read, not a re-parse."""
        if parsed is not None:
            name, path, mode = parsed.routing()
        else:
            name, path, mode = peek_routing(body)
        if path == "replay":
            p = PRIORITY_REPLAY_GROUND
        else:
            p = PRIORITY_FRESH_GROUND
        if mode == MODE_MODEL:
            p += 1
        if p == PRIORITY_FRESH_GROUND and name:
            with self._lock:
                flagged = self._scoreboard.flagged(name, self._clock())
            if flagged:
                p = PRIORITY_FRESH_MODEL
        return p

    def _ingest_report(
            self, request: Any,
            parsed: "ParsedHeader | None" = None
            ) -> tuple[int, dict[str, str], bytes]:
        if request.command != "POST":
            return 405, {"Content-Type": "text/plain"}, b"POST only\n"
        if fault.fire("replica.down") is not None:
            # chaos stand-in for a dying/overloaded replica: a 5xx the
            # agent counts as a send failure (failover + spool), never
            # as a permanent rejection
            return (503, {"Content-Type": "text/plain"},
                    b"replica down (fault injection)\n")
        return self._ingest_payload(request.body, parsed)

    # keplint: protocol-transition — base-row LRU touch
    def _delta_base_for(self, parsed: "ParsedHeader"
                        ) -> "_BaseRow | None":
        """Resolve a v2 delta frame's base keyframe. None = answer a
        structured 409 needs-keyframe (missing base after hand-off or
        eviction, run change, base-seq mismatch) — the agent resends
        full, nothing is charged or stored. A hostile node name raises
        into the ordinary quarantine path instead."""
        raw = parsed.header.get("node_name")
        name = sanitize_node_name(raw) if isinstance(raw, str) else ""
        if not name or name != raw:
            raise WireError("node_name must be 1-128 printable ASCII "
                            "chars")
        run = parsed.header.get("run")
        with self._lock:
            base = self._base_rows.get(name)
            if (base is None or not isinstance(run, str)
                    or not delta_base_matches(base.run, base.seq,
                                              run, parsed.base_seq)):
                self._stats["keyframe_requests_total"] += 1
                return None
            self._base_rows[name] = self._base_rows.pop(name)  # LRU touch
        return base

    def _needs_keyframe_response(
            self, parsed: "ParsedHeader"
            ) -> tuple[int, dict[str, str], bytes]:
        body = json.dumps({"needs_keyframe": True,
                           "base_seq": parsed.base_seq}).encode()
        return (409, {"Content-Type": "application/json",
                      "X-Kepler-Needs-Keyframe": "1",
                      **self._epoch_headers()}, body)

    # keplint: requires-lock=_lock
    # keplint: protocol-transition — keyframe plants the delta base
    def _store_base_locked(self, name: str, run: str, seq: int,
                           report: NodeReport,
                           zones: tuple[str, ...]) -> None:
        """Adopt a decoded v2 keyframe as the node's delta base (LRU:
        dict order = recency, oldest evicted at the cap). Runs for
        DUPLICATE keyframes too: a hand-off replay judged dup by the
        seeded tracker must still plant the base, or the agent's next
        delta would 409 forever."""
        self._base_rows.pop(name, None)
        while len(self._base_rows) >= self._base_row_cache:
            self._base_rows.pop(next(iter(self._base_rows)))
        self._base_rows[name] = _BaseRow(run=run, seq=seq,
                                         report=report,
                                         zone_names=zones)

    def _ingest_payload(
            self, body: bytes,
            parsed: "ParsedHeader | None" = None
            ) -> tuple[int, dict[str, str], bytes]:
        spec = fault.fire("aggregator.ingest_slow")
        if spec is not None:
            # chaos stand-in for a sinking ingest path (GC stall, slow
            # disk, CPU-starved replica): inflates the admission
            # controller's latency EWMA the honest way — by being slow
            _time.sleep(float(spec.arg or 0.05))
        if parsed is None:
            parsed = try_parse_header(body)
        if parsed is not None:
            # clamp to the two known versions: the counter keys a metric
            # label and must never grow with hostile frame contents
            version = 2 if parsed.version == 2 else 1
            with self._lock:
                self._ingest_bytes[version] = \
                    self._ingest_bytes.get(version, 0) + len(body)
        content_changed = True
        try:
            with telemetry.span("aggregator.decode"):
                if (parsed is not None and parsed.version == 2
                        and parsed.is_delta):
                    base = self._delta_base_for(parsed)
                    if base is None:
                        return self._needs_keyframe_response(parsed)
                    report, header, content_changed = decode_delta(
                        body, parsed, base.report, base.zone_names)
                else:
                    # v1 (the pinned JSON path — decoded off the ONE
                    # parse_header memo) or a v2 keyframe (zero-copy
                    # frombuffer views over the request body)
                    report, header = decode_report(body, parsed)
        except (WireError, ValueError) as err:
            # quarantine, charged to the sender when the header survives.
            # The header work runs OFF the store lock — a burst of
            # large malformed bodies must not stall ingest/aggregation.
            # The peeked name is UNVALIDATED wire input (the body already
            # failed decoding): sanitize before it becomes a degradation
            # key, scoreboard row, metric label, or log field (KTL112)
            if parsed is not None:
                raw = parsed.header.get("node_name")
                node = (sanitize_node_name(raw)
                        if isinstance(raw, str) else "")
            else:
                node = sanitize_node_name(peek_node_name(body) or "")
            with self._lock:
                self._stats["rejected_total"] += 1
                self._stats["quarantined_total"] += 1
                self._stats["malformed_total"] += 1
                if node:
                    self._record_degraded_locked(node, "malformed", str(err))
            return 400, {"Content-Type": "text/plain"}, f"{err}\n".encode()
        received = self._clock()
        sent_at = header.get("sent_at")
        if (self._skew_tolerance > 0
                and isinstance(sent_at, (int, float))
                and not isinstance(sent_at, bool)
                and abs(received - float(sent_at)) > self._skew_tolerance):
            # a skewed sender's reports would corrupt staleness aging and
            # cumulative-energy timestamps — quarantine instead of ingest
            skew = float(sent_at) - received
            with self._lock:
                self._stats["rejected_total"] += 1
                self._stats["quarantined_total"] += 1
                self._stats["clock_skew_total"] += 1
                self._record_degraded_locked(
                    report.node_name, "clock_skew",
                    f"sender clock skewed {skew:+.1f}s")
            return (422, {"Content-Type": "text/plain"},
                    f"report clock skewed {skew:+.1f}s beyond tolerance "
                    f"{self._skew_tolerance:g}s\n".encode())
        # header identity coercion is VALIDATING, not converting: a report
        # whose seq/run carry the wrong JSON type (a string seq, a list
        # run) is malformed input from an untrusted network — quarantine
        # and charge the sender, never raise into a 500
        seq_raw = header.get("seq", 0)
        run_raw = header.get("run", "")
        if (isinstance(seq_raw, bool) or not isinstance(seq_raw, int)
                or seq_raw < 0 or not isinstance(run_raw, str)):
            with self._lock:
                self._stats["rejected_total"] += 1
                self._stats["quarantined_total"] += 1
                self._stats["malformed_total"] += 1
                self._record_degraded_locked(
                    report.node_name, "malformed",
                    f"bad header identity: seq={seq_raw!r} run={run_raw!r}")
            return (400, {"Content-Type": "text/plain"},
                    b"seq must be a non-negative integer and run a string\n")
        # ring-header coercion, hardened exactly like run/seq: the
        # owner/epoch/acked_through fields steer redirect handling and
        # loss accounting, so hostile values (non-int, negative, bool,
        # overlong/non-printable owner) are a 400 quarantine charged to
        # the node — never a 500, never silently honored
        owner_raw = header.get("owner", "")
        epoch_val = coerce_epoch(header.get("epoch", 0))
        acked_through = coerce_epoch(header.get("acked_through", 0))
        owner_ok = owner_raw == "" or sanitize_peer(owner_raw) == owner_raw
        if epoch_val is None or acked_through is None or not owner_ok:
            with self._lock:
                self._stats["rejected_total"] += 1
                self._stats["quarantined_total"] += 1
                self._stats["malformed_total"] += 1
                self._record_degraded_locked(
                    report.node_name, "malformed",
                    f"bad ring header: owner={owner_raw!r} "
                    f"epoch={header.get('epoch')!r} "
                    f"acked_through={header.get('acked_through')!r}")
            return (400, {"Content-Type": "text/plain"},
                    b"owner must be a printable string, epoch and "
                    b"acked_through non-negative integers\n")
        # ownership: a report for a node the ring assigns elsewhere is
        # answered with a structured redirect (the agent follows it and
        # re-delivers there) — not stored, not charged, not tracked
        ring = self._ring
        if ring is not None:
            owner = ring.owner(report.node_name)
            if owner != self._self_peer:
                with self._lock:
                    self._stats["reports_redirected_total"] += 1
                    self._last_redirect_at = received
                body = json.dumps({"owner": owner,
                                   "epoch": ring.epoch}).encode()
                return (421, {"Content-Type": "application/json",
                              "X-Kepler-Owner": owner,
                              "X-Kepler-Epoch": str(ring.epoch)}, body)
        stored = _Stored(report=report,
                         zone_names=tuple(header["zone_names"]),
                         received=received,
                         seq=seq_raw,
                         run=run_raw,
                         content_seq=seq_raw,
                         wire_version=(2 if parsed is not None
                                       and parsed.version == 2 else 1))
        # scoreboard input, computed OFF the store lock: the node's
        # self-reported power this window (valid zone energy over dt)
        report_power_w = _report_power_w(report)
        with telemetry.span("aggregator.merge"), self._lock:
            prev = self._reports.get(report.node_name)
            # When BOTH sides carry a run nonce the cases are unambiguous:
            # different nonce = fresh agent process (restart), same nonce +
            # seq regression = network reorder or spool redelivery (the
            # dedup window sorts those out). A nonce that matches any run
            # a previous restart superseded is a delayed straggler from a
            # dead run — reject it outright rather than honoring it as
            # another restart (which would also wrongly mark the live run
            # as superseded).
            superseded = self._superseded_runs.get(report.node_name, [])
            if stored.run and stored.run in superseded:
                self._stats["rejected_total"] += 1
                return (409, {"Content-Type": "text/plain"},
                        b"stale run nonce (superseded by a newer agent run)\n")
            has_nonces = (prev is not None and bool(stored.run)
                          and bool(prev.run))
            restarted = has_nonces and stored.run != prev.run
            # wire v2: adopt an accepted keyframe as the node's delta
            # base BEFORE dedup (a duplicate keyframe is still a valid
            # base — see _store_base_locked) but AFTER the superseded-
            # run check, so a dead run can never plant base state
            if (parsed is not None and parsed.version == 2
                    and not parsed.is_delta and stored.run
                    and stored.seq > 0):
                self._store_base_locked(
                    report.node_name, stored.run, stored.seq, report,
                    stored.zone_names)
            # content identity: a FLAG_SAME delta asserts (and decode
            # verified) that this window's content EQUALS the base
            # keyframe's — so the content seq is the BASE's seq, not
            # this window's. Steady state pins every unchanged window
            # to the keyframe identity (zero staged rows); a node that
            # changed and then reverted gets the keyframe identity
            # back, which correctly restages it over the changed row.
            if (not content_changed and parsed is not None
                    and parsed.is_delta and parsed.base_seq > 0):
                stored.content_seq = parsed.base_seq
            if restarted:
                runs = self._superseded_runs.setdefault(
                    report.node_name, [])
                runs.append(prev.run)
                del runs[:-self._superseded_cap]
            # idempotent ingest + loss accounting (nonce-carrying agents
            # only — a pre-nonce agent's seq space restarts unannounced,
            # so gap math on it would fabricate loss). seq 0 means "no
            # sequencing" (encode_report's default): real agents number
            # from 1, and deduping a stream of constant zeros would
            # freeze the node's data on its first window forever.
            lost_windows = 0
            if stored.run and stored.seq > 0:
                tracker = self._seq_trackers.get(report.node_name)
                if tracker is None or tracker.run != stored.run:
                    # the cap tracks the LIVE fleet (2× headroom, floor
                    # for small fleets): a fixed cap below the fleet size
                    # would thrash — every round-robin arrival evicting a
                    # peer's tracker, disabling dedup and fabricating
                    # lost-window counts on every report. Memory is
                    # operator-bounded via aggregator.dedupWindow.
                    cap = max(self._tracker_cap, 2 * len(self._reports))
                    if (report.node_name not in self._seq_trackers
                            and len(self._seq_trackers) >= cap):
                        self._seq_trackers.pop(min(
                            self._seq_trackers,
                            key=lambda n: self._seq_trackers[n].touched))
                    tracker = _SeqTracker(stored.run, self._dedup_window)
                    # hand-off / restart seeding from the agent's
                    # delivered watermark (pure rule: fleet/delivery.py)
                    seed_fresh_tracker(tracker, acked_through,
                                       stored.seq)
                    self._seq_trackers[report.node_name] = tracker
                tracker.touched = received
                # ownership RETURN (elastic membership): the PR 16
                # re-seed rule — the away period's windows were 2xx'd
                # by the interim owner, not lost (pure rule:
                # fleet/delivery.py, model-checked by kepmc)
                ring_epoch = (self._ring.epoch
                              if self._ring is not None else 0)
                reseed_on_ownership_return(tracker, ring_epoch,
                                           acked_through, stored.seq)
                dup, lost = tracker.observe(stored.seq)
                if dup:
                    # at-least-once redelivery (spool replay, LB retry):
                    # acknowledge so the sender's cursor advances, ingest
                    # nothing — the earlier copy already counted. The
                    # duplicate still PROVES the sender is alive: refresh
                    # liveness, or a replay longer than stale_after would
                    # prune this tracker mid-stream and the rest of the
                    # backlog would re-ingest as fresh windows
                    if prev is not None and prev.run == stored.run:
                        prev.received = received
                    self._stats["duplicates_total"] += 1
                    self._stats["reports_total"] += 1
                    self._scoreboard.observe_duplicate(report.node_name,
                                                       received)
                    return 204, self._epoch_headers(), b""
                if lost:
                    lost_windows = lost
                    self._stats["windows_lost_total"] += lost
                    # pop-and-reinsert keeps dict order = recency of last
                    # loss, so cap eviction drops the node that stopped
                    # losing longest ago — never an actively-firing
                    # series (a mid-series counter reset breaks rate()
                    # alerting on exactly this signal)
                    total = self._lost_by_node.pop(report.node_name,
                                                   0) + lost
                    if len(self._lost_by_node) >= self._lost_node_cap:
                        self._lost_by_node.pop(
                            next(iter(self._lost_by_node)))
                    self._lost_by_node[report.node_name] = total
                    log.warning("node %s: %d window(s) lost before seq %d "
                                "(never delivered)", report.node_name,
                                lost, stored.seq)
            # NOTE: the legacy `seq == 1` restart heuristic is gone — a
            # spool replay legitimately starts at seq 1 of an OLD run and
            # must not double-ingest as a "restart"; nonce-carrying agents
            # signal restarts explicitly, and pre-nonce agents simply age
            # out via stale_after before their fresh reports land again.
            if prev is None or restarted or stored.seq >= prev.seq:
                self._reports[report.node_name] = stored
                # history push is NOT idempotent (a dup would shift the
                # window) → require a seq change OR a run change (an agent
                # restart that happens to re-send the previous run's seq
                # value is still a new window). Ratio nodes' estimator
                # output is discarded, so their windows matter only as
                # TRAINING data — accrete them when a dump dir is set.
                # The push happens HERE, under the store lock: acceptance
                # order must equal buffer order (a deferred push could let
                # a concurrent seq=N+1 land before seq=N, derailing the
                # window's time axis) — the append itself is one tiny row
                # per workload; the expensive [N, W, T, F] ASSEMBLY is
                # what runs off this lock (_history_windows).
                if (self._model_mode == "temporal"
                        and (report.mode == MODE_MODEL or self._dump_dir)
                        and (prev is None or restarted
                             or stored.seq != prev.seq)):
                    self._push_history(report)
            self._scoreboard.observe_report(report.node_name, received,
                                            report_power_w,
                                            lost=lost_windows)
            self._observe_delivery_locked(report.node_name, header,
                                          received)
            self._stats["reports_total"] += 1
        return 204, self._epoch_headers(), b""

    def _epoch_headers(self) -> dict[str, str]:
        """Accepts advertise the ring epoch so settled agents notice a
        membership bump lazily (no extra round-trips); with the journal
        enabled they ALSO carry this replica's HLC stamp, so agents'
        clocks chain causally to the aggregator's (piggyback — never an
        extra round-trip, absent entirely when the journal is off)."""
        headers: dict[str, str] = {}
        hlc_text = self._journal.header()
        if hlc_text is not None:
            headers["X-Kepler-HLC"] = hlc_text
        ring = self._ring
        if ring is not None:
            headers["X-Kepler-Epoch"] = str(ring.epoch)
        return headers

    def _observe_request_hlc(self, request: Any) -> bool:
        """Merge an inbound ``X-Kepler-HLC`` stamp into this replica's
        clock. Returns False ONLY for a present-but-hostile stamp (the
        caller answers 400) — absent headers and chaos/test stand-in
        requests without a ``headers`` attribute are fine. The clamp
        in :meth:`HlcClock.observe` bounds how far a valid-but-vaulted
        stamp can advance us (KTL112: laundered, never trusted)."""
        headers = getattr(request, "headers", None)
        if headers is None:
            return True
        raw = headers.get("X-Kepler-HLC")
        if raw is None:
            return True
        return self._journal.observe_text(raw)

    def _bad_hlc_response(self) -> tuple[int, dict[str, str], bytes]:
        with self._lock:
            self._stats["rejected_total"] += 1
            self._stats["malformed_total"] += 1
        return (400, {"Content-Type": "text/plain"},
                b"malformed X-Kepler-HLC header\n")

    # -- ingest ring (HA ingest tier) --------------------------------------

    def apply_membership(self, peers: Sequence[str], epoch: int, *,
                         source: str = "operator", issuer: str = "",
                         mesh: bool = False) -> int:
        """Adopt a new replica membership — the operator action it has
        always been (config rollout, chaos rebalance), and now ALSO
        the elastic plane's one write path: succession after a host
        death, join/leave fan-out from the lease holder, autoscale
        enactment. Swaps in a NEW ring at a HIGHER epoch and drops
        stored reports for nodes this replica no longer owns — their
        agents get redirected on their next send and replay their
        spool tails to the new owner. Seq trackers are KEPT (bounded
        by their cap): if ownership bounces back, dedup continuity
        absorbs the re-delivered overlap.

        Epoch semantics (ISSUE 16): re-applying the SAME peer set at
        the CURRENT epoch is an idempotent replay (returns 0 — a
        re-delivered broadcast, indistinguishable from a no-op); the
        same epoch with a DIFFERENT set is the split-brain detector
        firing — rejected loudly as ``equal_epoch_conflict`` and
        counted in ``kepler_fleet_membership_rejected_total``. A
        lower epoch is ``stale_epoch``. ``source`` labels the
        applied/rejected counters; ``issuer`` (default: succession
        over the new set) advances the coordinator lease in lock-step
        with the ring.

        A non-operator membership that EXCLUDES this replica retires
        it: the new ring is adopted anyway, every stored node is
        dropped, and all future ingest answers 421 toward the real
        owners — the scale-down path. The operator path keeps the
        strict self-in-set check (excluding yourself by hand is
        almost certainly a typo). ``mesh=True`` asks for the
        mesh-derived ring (and multi-host engine) to be restored over
        the new set — the rejoin path; it needs the peers to be a
        process-ordered subset of the configured list (and, after a
        fabric loss, a fresh incarnation via :meth:`arm_mesh`), and
        falls back to the plain hash ring otherwise.

        Returns the number of nodes handed off. Raises
        :class:`MembershipError` (a ``ValueError``) on rejection."""
        try:
            return self._apply_membership_checked(
                peers, epoch, source=source, issuer=issuer, mesh=mesh)
        except MembershipError as err:
            with self._lock:
                self._membership_rejected[err.reason] = \
                    self._membership_rejected.get(err.reason, 0) + 1
            log.error("membership rejected (%s, source=%s): %s",
                      err.reason, source, err)
            raise

    def _apply_membership_checked(self, peers: Sequence[str],
                                  epoch: int, *, source: str,
                                  issuer: str, mesh: bool) -> int:
        if self._ring is None:
            raise MembershipError(
                "ring_disabled",
                "ingest ring is not enabled (aggregator.peers is empty)")
        current = self._ring
        # the whole epoch/peer-set state machine is the PURE decision
        # (fleet/membership.py, model-checked by kepmc); this method
        # only wires its verdict to the ring/lease/stores
        decision = plan_membership_apply(
            current.epoch, current.peers, current.membership_digest,
            epoch, peers, self._self_peer, source)
        ep = decision.epoch
        if decision.action == "replay":
            log.info("membership replay at epoch %d ignored (same "
                     "peer set, digest %s)", ep,
                     current.membership_digest)
            return 0
        retired = decision.retired
        new = self._build_ring(list(decision.peers), ep, mesh=mesh)
        who = issuer or plan_succession(
            self._lease.holder if self._lease is not None else "",
            new.peers)
        with self._lock:
            self._ring = new
            # the lease advances in lock-step with the ring epoch —
            # adopt cannot conflict here (ep > current epoch by the
            # checks above), so succession state never splits from
            # membership state
            if self._lease is not None:
                self._lease.adopt(who, ep)
            else:
                self._lease = CoordinatorLease(who, ep)
            dropped = [n for n in self._reports
                       if retired or new.owner(n) != self._self_peer]
            for name in dropped:
                del self._reports[name]
                self._history.pop(name, None)
                self._superseded_runs.pop(name, None)
                # the new owner holds no base for it either — dropping
                # ours keeps "409 → keyframe" the one hand-off story
                self._base_rows.pop(name, None)
                # the node reports to its NEW owner now — a row left
                # here would age into a permanent false 'stale' signal
                self._scoreboard.drop(name)
            self._last_membership_at = self._clock()
            self._membership_applied[source] = \
                self._membership_applied.get(source, 0) + 1
        # black box: the apply and the lock-step lease adopt are TWO
        # events — timeline readers correlate successions across
        # replicas by the adopt, membership churn by the apply
        self._journal.emit("membership.apply", epoch=ep,
                           peers=sorted(new.peers), source=source,
                           dropped=len(dropped), retired=retired)
        self._journal.emit("lease.adopt", holder=who, epoch=ep,
                           source=source)
        if self._multihost_enabled:
            # elastic rebuild, the PR-6 ladder-reset invariant: sticky
            # maps cleared, rings re-seeded — the next window does a
            # full re-pack over the new member set
            self._engine = None
            self._engine_serial = None
        with self._results_lock:
            self._awaiting_membership = False
        log.warning("ingest ring membership changed: epoch %d, %d "
                    "peer(s) (digest %s, issuer %s, source %s), %d "
                    "node(s) handed off%s", new.epoch, len(new),
                    new.membership_digest, who, source, len(dropped),
                    (" — this replica RETIRED (owns nothing, redirects "
                     "everything)" if retired else ""))
        return len(dropped)

    def _build_ring(self, peers: list[str], epoch: int,
                    mesh: bool) -> HashRing:
        """The new ring for a membership change: the mesh-derived ring
        when a mesh restore was requested AND the topology can honor
        it — the peers must be a >=2-process subset of the configured
        process-ordered list (ownership co-location is only true for
        processes the device mesh actually contains); otherwise the
        plain consistent-hash ring."""
        if mesh and self._multihost_enabled and self._mesh is not None:
            want = set(peers)
            procs = [i for i, p in enumerate(self._config_peers)
                     if p in want]
            if len(procs) == len(want) and len(procs) >= 2:
                armed, self._mesh_arm = self._mesh_arm, None
                if armed is not None:
                    # a rejoin's fresh fabric incarnation (the old
                    # one's barriers died with the departed peer)
                    self._mh_fabric = armed
                proc = self._device_process_fn()
                sub = submesh_for_processes(self._mesh, procs, proc)
                order = {p: k for k, p in enumerate(procs)}
                shard_procs = [order[int(proc(d))]
                               for d in sub.devices.flat]
                peers_by_proc = [self._config_peers[p] for p in procs]
                self._mesh_elastic = sub
                with self._results_lock:
                    self._mesh_degraded = False
                log.info("mesh-derived ring restored over %d process(es) "
                         "(%d shards) at epoch %d", len(procs),
                         len(shard_procs), epoch)
                return ring_from_mesh(peers_by_proc, shard_procs,
                                      epoch=epoch)
            log.warning("mesh-path membership cannot be honored (peers "
                        "%r are not a >=2-process subset of the "
                        "configured process-ordered list); falling back "
                        "to the plain hash ring", sorted(want))
        if self._multihost_enabled:
            # a non-mesh membership while the multi-host tier runs
            # means the mesh no longer describes ownership: survivors
            # serve their ring share from their own single-host
            # engines until a mesh-path membership restores the tier
            self._mesh_elastic = None
            with self._results_lock:
                if self._multihost_active():
                    self._mesh_degraded = True
        try:
            return self._ring.with_members(peers, epoch)
        except RingError as err:
            raise MembershipError("bad_peer", str(err))

    # -- elastic membership plane (ISSUE 16) -------------------------------

    def arm_mesh(self, fabric: Any) -> None:
        """Arm a fresh fabric incarnation for the NEXT mesh-path
        membership (the rejoin/restore handshake): the virtual
        topology passes its new :class:`HostLocalFabric`; production's
        analog is restarting the ``jax.distributed`` job before
        re-applying the full membership (a dead peer cannot rejoin a
        RUNNING job — see docs/developer/resilience.md). One-shot:
        consumed by the next ``apply_membership(..., mesh=True)``."""
        self._mesh_arm = fabric

    def _peer_alive(self, peer: str) -> bool:
        """Liveness probe for one peer: the injected seam, or an HTTP
        GET of its ``/healthz`` — ANY HTTP answer (even 503) proves a
        listener; only transport failures read as death."""
        probe = self._peer_alive_fn
        if probe is not None:
            try:
                return bool(probe(peer))
            except Exception:
                return False
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(
                    f"http://{peer}/healthz",
                    timeout=self._membership_probe_timeout):
                return True
        except urllib.error.HTTPError:
            return True
        except Exception:
            return False

    def _probe_survivors(self) -> list[str]:
        """The current peer set filtered by liveness (self is alive by
        definition). Every survivor runs the same probe over the same
        set, so — probe flakes aside, which the equal-epoch conflict
        check backstops — they compute the same survivor list and
        therefore the same succession issuer."""
        ring = self._ring
        if ring is None:
            return [self._self_peer]
        return [peer for peer in ring.peers
                if peer == self._self_peer or self._peer_alive(peer)]

    def _deliver_membership(self, peer: str,
                            payload: Mapping[str, Any]) -> dict:
        """POST one membership payload to ``peer`` (the injected seam,
        or HTTP ``/v1/membership``) and return its JSON reply.
        Transport failures return a structured not-ok reply instead of
        raising — broadcast is best-effort; a replica a broadcast
        misses converges via the epoch headers and the equal-epoch
        replay guard."""
        deliver = self._deliver_fn
        if deliver is not None:
            try:
                reply = deliver(peer, dict(payload))
            except Exception as err:
                return {"ok": False, "reason": "unreachable",
                        "detail": str(err)[:240]}
            if isinstance(reply, Mapping):
                return dict(reply)
            return {"ok": False, "reason": "bad_reply"}
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"http://{peer}/v1/membership",
            data=json.dumps(dict(payload)).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urllib.request.urlopen(
                    req, timeout=self._membership_probe_timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as err:
            try:
                return json.loads(err.read() or b"{}")
            except Exception:
                return {"ok": False, "reason": "unreachable",
                        "detail": f"http {err.code}"}
        except Exception as err:
            return {"ok": False, "reason": "unreachable",
                    "detail": str(err)[:240]}

    def _broadcast_membership(self, peers: Sequence[str], epoch: int,
                              extra: Sequence[str] = (),
                              mesh: bool = False) -> None:
        """Fan the just-applied membership out to every OTHER member
        (plus ``extra`` — e.g. a peer the membership just removed, so
        it retires instead of serving a stale ring)."""
        # the issuer is the CURRENT lease holder, not necessarily this
        # replica: a holder retiring itself (leave) hands the lease to
        # its successor in the local apply, and the fan-out must carry
        # that successor or receivers would adopt the departed holder
        issuer = self._self_peer
        if not _BUG_BROADCAST_SELF_ISSUER \
                and self._lease is not None and self._lease.holder:
            issuer = self._lease.holder
        payload: dict[str, Any] = {
            "op": "apply", "peers": list(peers), "epoch": int(epoch),
            "issuer": issuer, "mesh": bool(mesh)}
        if self._lease is not None:
            payload["lease"] = self._lease.lease_id
        hlc_text = self._journal.header()
        if hlc_text is not None:
            # the HLC piggyback: receivers' journals order their apply
            # AFTER the issuer's (causal chain through the broadcast)
            payload["hlc"] = hlc_text
        for peer in sorted(set(peers) | set(extra)):
            if peer == self._self_peer:
                continue
            reply = self._deliver_membership(peer, payload)
            if not reply.get("ok", False):
                log.warning("membership broadcast to %s not applied: %s",
                            peer, reply.get("reason", "unknown"))

    def request_join(self, *, mesh: bool = False, via: str = "") -> dict:
        """Rejoin/new-host registration, run on the JOINING replica:
        register with the lease holder (``via`` overrides the first
        peer to ask), follow ``not_leader`` redirects, then adopt the
        returned membership — ring at the granted epoch, INCUMBENT
        holder from the reply (a rejoining peer therefore never
        self-elects over a live lease, even when it sorts lowest), and
        with ``mesh=True`` the mesh-derived ring + multi-host engine
        over the restored set. Returns the holder's reply."""
        if self._ring is None:
            raise MembershipError(
                "ring_disabled",
                "ingest ring is not enabled (aggregator.peers is empty)")
        payload = {"op": "join", "peer": self._self_peer,
                   "mesh": bool(mesh)}
        candidates: list[str] = []
        if via and via != self._self_peer:
            candidates.append(via)
        holder = self._lease.holder if self._lease is not None else ""
        if holder and holder != self._self_peer \
                and holder not in candidates:
            candidates.append(holder)
        for p in self._ring.peers:
            if p != self._self_peer and p not in candidates:
                candidates.append(p)
        reply: dict = {"ok": False, "reason": "unreachable",
                       "detail": "no peer to register with"}
        hops = 0
        max_hops = len(self._ring.peers) + 2
        while candidates and hops < max_hops:
            target = candidates.pop(0)
            hops += 1
            reply = self._deliver_membership(target, payload)
            if reply.get("reason") == "not_leader":
                nxt = sanitize_peer(reply.get("holder"))
                if nxt and nxt != self._self_peer \
                        and nxt != target:
                    candidates.insert(0, nxt)
                continue
            if reply.get("ok"):
                break
        if not reply.get("ok"):
            with self._lock:
                self._membership_rejected["join_failed"] = \
                    self._membership_rejected.get("join_failed", 0) + 1
            raise MembershipError(
                "join_failed",
                f"no lease holder accepted the join: "
                f"{reply.get('reason', 'unreachable')}")
        peers = [sanitize_peer(p) for p in reply.get("peers", [])]
        epoch = coerce_epoch(reply.get("epoch"))
        granted_holder = sanitize_peer(reply.get("holder")) or ""
        if epoch is None or not peers or any(p is None for p in peers):
            raise MembershipError(
                "bad_payload",
                "join reply did not carry a valid membership")
        try:
            self.apply_membership(peers, epoch, source="join",
                                  issuer=granted_holder, mesh=mesh)
        except MembershipError as err:
            # the holder's broadcast may have raced ahead of the reply
            # (our epoch already advanced) — that is convergence, not
            # failure; anything else propagates
            if err.reason != "stale_epoch":
                raise
        if granted_holder and self._lease is not None and epoch is not None:
            try:
                # an equal-epoch replay above skips the lease adopt —
                # take the incumbent from the reply explicitly
                before = (self._lease.holder, self._lease.epoch)
                self._lease.adopt(granted_holder, epoch)
                if (self._lease.holder, self._lease.epoch) != before:
                    self._journal.emit("lease.adopt",
                                       holder=granted_holder,
                                       epoch=epoch, source="join_reply")
            except MembershipError:
                pass  # a fresher lease was already adopted locally
        return reply

    def _membership_join(self, peer: str, mesh: bool
                         ) -> tuple[int, dict[str, str], bytes]:
        """Lease-holder handling of a join registration: fold the peer
        into the membership at epoch+1, fan out, and answer the joiner
        with the full adopted state (peers, epoch, holder) — the
        joiner ADOPTS the incumbent lease from this reply."""
        ring, lease = self._ring, self._lease
        if peer in ring.peers:
            # idempotent re-registration: answer the current state
            body = {"ok": True, "epoch": ring.epoch,
                    "peers": list(ring.peers),
                    "holder": lease.holder if lease else "",
                    "lease": lease.lease_id if lease else "",
                    "already_member": True}
            return (200, {"Content-Type": "application/json"},
                    json.dumps(body).encode())
        peers = sorted(set(ring.peers) | {peer})
        epoch = ring.epoch + 1
        try:
            self.apply_membership(peers, epoch, source="join",
                                  issuer=self._self_peer, mesh=mesh)
        except MembershipError as err:
            body = {"ok": False, "reason": err.reason,
                    "error": str(err)}
            return (409, {"Content-Type": "application/json"},
                    json.dumps(body).encode())
        self._broadcast_membership(peers, epoch, mesh=mesh)
        ring, lease = self._ring, self._lease
        body = {"ok": True, "epoch": ring.epoch,
                "peers": list(ring.peers),
                "holder": lease.holder if lease else "",
                "lease": lease.lease_id if lease else ""}
        return (200, {"Content-Type": "application/json"},
                json.dumps(body).encode())

    def _membership_leave(self, peer: str
                          ) -> tuple[int, dict[str, str], bytes]:
        """Lease-holder handling of a graceful leave: drop the peer at
        epoch+1 and fan out — INCLUDING to the leaver, whose wire
        apply retires it (it keeps the new ring it is not in and
        redirects everything)."""
        ring = self._ring
        if peer not in ring.peers:
            body = {"ok": True, "epoch": ring.epoch,
                    "peers": list(ring.peers), "already_left": True}
            return (200, {"Content-Type": "application/json"},
                    json.dumps(body).encode())
        remaining = sorted(set(ring.peers) - {peer})
        epoch = ring.epoch + 1
        try:
            # issuer defaults to succession over the remaining set, so
            # the holder leaving ITSELF hands the lease to the lowest
            # survivor in the same apply
            self.apply_membership(remaining, epoch, source="leave")
        except MembershipError as err:
            body = {"ok": False, "reason": err.reason,
                    "error": str(err)}
            return (409, {"Content-Type": "application/json"},
                    json.dumps(body).encode())
        self._broadcast_membership(remaining, epoch, extra=[peer])
        ring, lease = self._ring, self._lease
        body = {"ok": True, "epoch": ring.epoch,
                "peers": list(ring.peers),
                "holder": lease.holder if lease else "",
                "lease": lease.lease_id if lease else ""}
        return (200, {"Content-Type": "application/json"},
                json.dumps(body).encode())

    def _membership_reject(self, status: int, reason: str, detail: str
                           ) -> tuple[int, dict[str, str], bytes]:
        with self._lock:
            self._membership_rejected[reason] = \
                self._membership_rejected.get(reason, 0) + 1
        body = {"ok": False, "reason": reason, "error": detail}
        return (status, {"Content-Type": "application/json"},
                json.dumps(body).encode())

    def _handle_membership(
            self, request: Any) -> tuple[int, dict[str, str], bytes]:
        """``POST /v1/membership``: the elastic-membership wire plane.
        Ops: ``apply`` (adopt an issuer's membership — the broadcast
        receiver), ``join`` (a rejoining/new replica registers with
        the lease holder), ``leave`` (graceful scale-down). Every
        field is laundered by ``validate_membership_payload`` before
        it can steer the ring, reach a log line, or key a metric; a
        non-holder answers join/leave with a structured ``not_leader``
        redirect naming the holder (the membership plane's 421)."""
        if request.command != "POST":
            return (405, {"Content-Type": "text/plain"},
                    b"POST membership operations\n")
        try:
            raw = json.loads(request.body or b"{}")
        except ValueError:
            return self._membership_reject(
                400, "bad_payload", "membership body must be JSON")
        try:
            cleaned = validate_membership_payload(raw)
        except MembershipError as err:
            return self._membership_reject(400, err.reason, str(err))
        if "hlc" in cleaned:
            # already laundered to an HLC by the validator; the observe
            # clamps a vaulted physical clock (KTL112)
            self._journal.observe(cleaned["hlc"])
        op = cleaned.get("op")
        if op == "apply":
            if "peers" not in cleaned or "epoch" not in cleaned:
                return self._membership_reject(
                    400, "bad_payload",
                    "membership apply needs peers and epoch")
            try:
                dropped = self.apply_membership(
                    cleaned["peers"], cleaned["epoch"], source="wire",
                    issuer=cleaned.get("issuer", ""),
                    mesh=cleaned["mesh"])
            except MembershipError as err:
                # already counted by apply_membership's wrapper
                body = {"ok": False, "reason": err.reason,
                        "error": str(err),
                        "epoch": (self._ring.epoch
                                  if self._ring is not None else 0)}
                return (409, {"Content-Type": "application/json"},
                        json.dumps(body).encode())
            ring, lease = self._ring, self._lease
            body = {"ok": True, "dropped": dropped,
                    "epoch": ring.epoch if ring is not None else 0,
                    "holder": lease.holder if lease else ""}
            return (200, {"Content-Type": "application/json"},
                    json.dumps(body).encode())
        if op in ("join", "leave"):
            if self._ring is None:
                return self._membership_reject(
                    409, "ring_disabled",
                    "ingest ring is not enabled on this replica")
            peer = cleaned.get("peer")
            if not peer:
                return self._membership_reject(
                    400, "bad_payload", f"membership {op} needs peer")
            lease = self._lease
            if lease is None or lease.holder != self._self_peer:
                body = {"ok": False, "reason": "not_leader",
                        "holder": lease.holder if lease else "",
                        "epoch": self._ring.epoch}
                return (421, {"Content-Type": "application/json"},
                        json.dumps(body).encode())
            if op == "join":
                return self._membership_join(peer, cleaned["mesh"])
            return self._membership_leave(peer)
        return self._membership_reject(
            400, "bad_op", "membership payload needs an op "
            "(apply | join | leave)")

    # -- autoscale (ISSUE 16) ----------------------------------------------

    def _autoscale_tick(self) -> None:
        """One autoscale observation per aggregation interval: fold
        the fleet's already-recorded signals (admission load, shed
        deltas, ingest-latency EWMA, scoreboard states) into the
        hysteresis policy. Recommendations are always surfaced (gauge
        + log); they are ENACTED — through the same apply_membership
        plane as every other change — only when
        ``aggregator.membership.autoApply`` is on AND this replica
        holds the lease, so ``autoApply=false`` keeps operator-driven
        behavior byte-for-byte."""
        policy = self._autoscale
        if policy is None or self._ring is None:
            return
        ctrl = self._admission
        shed_total = (sum(ctrl.shed_by_reason().values())
                      if ctrl is not None else 0)
        now = self._clock()
        with self._lock:
            live_nodes = len(self._reports)
            states = self._scoreboard.states(now, self._stale_after)
        flagged = sum(1 for code in states.values() if code != 0)
        sig = AutoscaleSignals(
            load=ctrl.load() if ctrl is not None else 0.0,
            shed_delta=max(0, shed_total - self._autoscale_shed_seen),
            ingest_latency_s=(ctrl.latency_ewma()
                              if ctrl is not None else 0.0),
            live_nodes=live_nodes, flagged_nodes=flagged,
            replicas=len(self._ring))
        self._autoscale_shed_seen = shed_total
        decision = policy.observe(sig)
        with self._results_lock:
            self._autoscale_last = decision
            self._autoscale_decisions[decision.direction] = \
                self._autoscale_decisions.get(decision.direction, 0) + 1
        if decision.direction == "hold":
            return
        log.warning("autoscale recommendation: scale %s to %d "
                    "replica(s) — %s", decision.direction,
                    decision.replicas, decision.reason)
        if not self._membership_auto_apply:
            return
        lease = self._lease
        if lease is None or lease.holder != self._self_peer:
            return  # only the lease holder enacts membership
        try:
            self._enact_scale(decision)
        except ValueError as err:
            log.error("autoscale enactment failed: %s", err)

    def _enact_scale(self, decision: AutoscaleDecision) -> None:
        """Turn one non-hold autoscale decision into a membership:
        scale-up promotes the first unused
        ``aggregator.membership.standbyPeers`` entry; scale-down
        retires the highest-sorting non-holder peer (deterministic,
        and never the lease holder — that would orphan the lease
        mid-change)."""
        ring = self._ring
        current = set(ring.peers)
        extra: list[str] = []
        if decision.direction == "up":
            pool = [p for p in self._standby_peers if p not in current]
            if not pool:
                log.warning(
                    "autoscale wants %d replicas but "
                    "aggregator.membership.standbyPeers has no unused "
                    "entry — recommendation stands, nothing enacted",
                    decision.replicas)
                return
            peers = sorted(current | {pool[0]})
        else:
            victims = [p for p in sorted(current, reverse=True)
                       if p != self._self_peer]
            if not victims:
                return
            peers = sorted(current - {victims[0]})
            extra = [victims[0]]
        epoch = ring.epoch + 1
        self.apply_membership(peers, epoch, source="autoscale",
                              issuer=self._self_peer)
        changed = sorted(set(peers) ^ current)
        self._journal.emit("autoscale.enact",
                           direction=decision.direction, epoch=epoch,
                           peer=changed[0] if changed else "",
                           replicas=len(peers), reason=decision.reason)
        self._broadcast_membership(peers, epoch, extra=extra)

    def ring_health(self) -> dict:
        """``fleet-ring`` probe for /healthz: degraded while a hand-off
        is actively settling — a redirect answered or a membership
        change applied within ``degradedTtl``. That is the operator's
        "rebalance in progress" signal; it recovers on its own once
        displaced agents stop arriving here."""
        ring = self._ring
        now = self._clock()
        with self._lock:
            last_redirect = self._last_redirect_at
            last_membership = self._last_membership_at
            redirected = self._stats["reports_redirected_total"]
        settling = any(
            t is not None and now - t <= self._degraded_ttl
            for t in (last_redirect, last_membership))
        with self._results_lock:
            awaiting = self._awaiting_membership
        lease = self._lease
        out = {
            "ok": not settling and not awaiting,
            "epoch": ring.epoch if ring is not None else 0,
            "peers": len(ring) if ring is not None else 0,
            "self": self._self_peer,
            "redirected_total": redirected,
            "lease_holder": lease.holder if lease is not None else "",
            "lease_epoch": lease.epoch if lease is not None else 0,
        }
        if awaiting:
            out["awaiting_membership"] = True
            out["detail"] = ("degraded, awaiting membership: a peer "
                             "died and this replica is not the "
                             "succession issuer (or takeover is off) — "
                             "recovers on the issuer's broadcast or an "
                             "operator apply_membership")
        if last_redirect is not None:
            out["last_redirect_age_s"] = round(now - last_redirect, 3)
        if last_membership is not None:
            out["last_membership_age_s"] = round(now - last_membership, 3)
        return out

    # keplint: requires-lock=_lock
    def _observe_delivery_locked(self, node: str, header: Mapping,
                                 received: float) -> None:
        """Close the window's delivery trace: observe emit→ingest latency
        into ``kepler_fleet_delivery_latency_seconds``.

        Runs only for ACCEPTED reports (duplicates were already measured
        when their first copy arrived; quarantined reports never merged).
        Fresh sends measure from the agent's ``emitted_at``; spool
        replays from the ORIGINAL ``appended_at``, under ``path=replay``.
        All header fields are untrusted: non-numeric stamps mean no
        observation, and the path label is clamped to the two known
        values so hostile input can't mint series."""
        def _num(v: object) -> float | None:
            return (float(v) if isinstance(v, (int, float))
                    and not isinstance(v, bool) else None)

        emitted = _num(header.get("emitted_at"))
        if emitted is None:
            return  # pre-telemetry agent: no trace to close
        path = ("replay" if header.get("delivery_path") == "replay"
                else "fresh")
        basis = emitted
        if path == "replay":
            appended = _num(header.get("appended_at"))
            if appended is not None:
                basis = appended
        latency = max(0.0, received - basis)
        self._delivery_hist[path].observe(latency)
        if path == "fresh":
            # the scoreboard's per-node EWMA tracks network health, so
            # replay latency (outage age, not delivery speed) stays out
            self._scoreboard.observe_delivery(node, latency)
        trace = header.get("trace")
        if trace:
            log.debug("delivery trace %s closed: node=%s path=%s "
                      "latency=%.3fs", trace, node, path, latency)

    def _push_history(self, report: NodeReport) -> None:
        """Advance the node's feature-history window (temporal mode).
        Caller holds the store lock; the buffer's own lock (ordered
        store→buffer, matching _history_windows' buffer-only usage) still
        guards against a concurrent window assembly reading the node."""
        from kepler_tpu.resource.informer import FeatureBatch

        entry = self._history.get(report.node_name)
        if entry is None:
            entry = (threading.Lock(),
                     HistoryBuffer(window=self._history_window))
            self._history[report.node_name] = entry
        lock, buf = entry
        kinds = (report.workload_kinds if report.workload_kinds is not None
                 else np.zeros(len(report.workload_ids), np.int8))
        batch = FeatureBatch(
            kinds=kinds,
            ids=list(report.workload_ids),
            cpu_deltas=np.asarray(report.cpu_deltas, np.float32),
            node_cpu_delta=float(report.node_cpu_delta),
            usage_ratio=float(report.usage_ratio),
        )
        with lock:
            buf.push(batch, dt_s=float(report.dt_s))

    # -- degradation accounting --------------------------------------------

    def _record_degraded_locked(self, node: str, reason: str,
                                detail: str) -> None:
        """Charge one quarantined report to ``node``. Caller holds _lock."""
        node = node[:self._degraded_name_cap]
        entry = self._degraded.get(node)
        if entry is None:
            # black box: ONSET only — the node ENTERING the degraded
            # set is the event; per-report charges are counters
            self._journal.emit("quarantine.onset", node=node,
                               reason=reason)
            if len(self._degraded) >= self._degraded_cap:
                oldest = min(self._degraded,
                             key=lambda n: self._degraded[n]["last_at"])
                del self._degraded[oldest]
            entry = {"malformed": 0, "clock_skew": 0,
                     "last_error": "", "last_at": 0.0}
            self._degraded[node] = entry
        entry[reason] += 1
        entry["last_error"] = detail
        entry["last_at"] = self._clock()
        self._scoreboard.observe_quarantine(node, entry["last_at"], reason)
        log.warning("quarantined %s report from node %s: %s",
                    reason, node, detail)

    def degraded_nodes(self) -> dict[str, dict]:
        """Nodes with quarantined reports inside the decay window."""
        now = self._clock()
        with self._lock:
            return {n: dict(e) for n, e in self._degraded.items()
                    if now - e["last_at"] <= self._degraded_ttl}

    def health(self) -> dict:
        """Probe for /healthz: degraded while any node's reports are being
        quarantined (decays after degraded_ttl of clean ingest)."""
        degraded = self.degraded_nodes()
        with self._results_lock:
            last = self._last_window_at
        out = {
            "ok": not degraded,
            "degraded_nodes": sorted(degraded),
            "quarantined_total": self._stats["quarantined_total"],
            "windows_lost_total": self._stats["windows_lost_total"],
            "duplicates_total": self._stats["duplicates_total"],
        }
        if last is not None:
            out["last_window_age_s"] = round(self._clock() - last, 3)
        return out

    def _rung_display(self, rung: int) -> str:
        """Operator-facing rung name: rung 0 reads as its multi-host or
        sharded form on a multi-device node mesh (only rung 0 has
        one), and as the "mesh minus one host" tier after a mesh
        demotion."""
        if rung == RUNG_PIPELINED:
            if self._multihost_active():
                return (RUNG_NAME_MESH_DEGRADED if self._mesh_degraded
                        else RUNG_NAME_MULTIHOST)
            if self._fused_tier_active():
                return RUNG_NAME_FUSED
            if self._shard_count > 1:
                return RUNG_NAME_SHARDED
        return RUNG_NAMES[rung]

    def _fused_tier_active(self) -> bool:
        """Whether rung 0 currently runs the fused device-resident
        window loop (aggregator.fusedWindowK > 1, packed path, single
        host, not demoted within rung 0)."""
        return (self._fused_window_k > 1 and not self._fused_degraded
                and not self._multihost_enabled and self._use_packed())

    def window_health(self) -> dict:
        """``fleet-window`` probe for /healthz: degraded while the device
        window leg runs below the full packed-pipelined rung. Names the
        rung, so operators see WHAT degraded service they are getting
        (einsum-serial = slower but exact; numpy-host = device fully
        dead, ratio attribution still correct)."""
        with self._results_lock:
            out = {
                "ok": self._rung == RUNG_PIPELINED,
                "rung": self._rung,
                "rung_name": self._rung_display(self._rung),
                "shards": (self._shard_count
                           if self._rung == RUNG_PIPELINED else 1),
                "demotions_total": self._stats["window_demotions_total"],
                "repromotions_total":
                    self._stats["window_repromotions_total"],
                "windows_since_last_failure": self._windows_since_failure,
                "fallback_enabled": self._fallback_enabled,
                "probe_backoff": self._probe_penalty,
                "windows_at_rung": self._windows_at_rung,
                "timeline_len": len(self._rung_timeline),
                # the last few transitions inline (full ring on
                # /debug/window) — enough for "what just happened"
                "timeline": list(self._rung_timeline)[-5:],
            }
            if self._last_window_failure:
                out["last_failure"] = self._last_window_failure
            if self._fused_window_k > 1:
                eng = self._engine_fused
                out["fused"] = {
                    "k": self._fused_window_k,
                    "active": (self._rung == RUNG_PIPELINED
                               and self._fused_tier_active()),
                    "degraded": self._fused_degraded,
                    # host-ring occupancy: intervals staged, not yet
                    # flushed (the next flush publishes this many + 1)
                    "pending_windows": len(self._fused_pending),
                    "sync_per_window_ms":
                        self._stats["last_sync_per_window_ms"],
                }
                if eng is not None:
                    out["fused"]["ring_occupancy"] = \
                        eng.pending_occupancy()
                if self._fused_degraded:
                    # fused is rung 0's healthy tier when configured —
                    # running packed-pipelined instead IS degraded
                    # service, mirrored on the probe like _mesh_degraded
                    out["ok"] = False
            if self._multihost_enabled:
                from kepler_tpu.parallel.mesh import multihost_status

                init = multihost_status()
                # a degraded mesh is NOT ok — the probe names the tier
                # so a half-joined or half-dead mesh is diagnosable
                lease = self._lease
                out["multihost"] = {
                    "active": self._multihost_active(),
                    "mesh_degraded": self._mesh_degraded,
                    "init_joined": bool(init.joined),
                    # the DISTINCT init failure reason (joined |
                    # unconfigured | coordinator_unreachable |
                    # init_error) — never a generic decline
                    "init_reason": init.reason,
                    "awaiting_membership": self._awaiting_membership,
                    "lease_holder": (lease.holder
                                     if lease is not None else ""),
                    "lease_epoch": (lease.epoch
                                    if lease is not None else 0),
                }
                if init.detail:
                    out["multihost"]["init_detail"] = init.detail
                if self._awaiting_membership:
                    # a peer died and this replica is NOT the succession
                    # issuer (or takeover is disabled): engines rebuilt
                    # over a stale ring would misattribute, so the probe
                    # flags it until the issuer's broadcast (or an
                    # operator apply_membership) lands
                    out["ok"] = False
                    out["multihost"]["detail"] = \
                        "degraded, awaiting membership"
                if self._mesh_degraded:
                    out["ok"] = False
        return out

    # -- degradation ladder ------------------------------------------------

    # keplint: requires-lock=_results_lock
    def _record_rung_transition_locked(self, prev: int, rung: int,
                                       reason: str,
                                       from_name: str = "") -> None:
        """Append one ladder transition to the bounded rung timeline
        (the flight recorder's demote/re-promote history). Monotonic
        time orders transitions across wall-clock steps; wall time
        anchors them for humans. ``from_name`` overrides the from-rung
        display for the mesh demotion, whose from/to share rung 0."""
        rung_name = self._rung_display(rung)
        from_rung_name = from_name or self._rung_display(prev)
        stamp = self._journal.emit(
            "rung.transition", rung=rung, rung_name=rung_name,
            from_rung=prev, from_rung_name=from_rung_name,
            reason=reason)
        entry: dict[str, Any] = {
            "rung": rung,
            "rung_name": rung_name,
            "from_rung": prev,
            "from_rung_name": from_rung_name,
            "reason": reason,
            "wall_time": self._clock(),
            "monotonic_s": _time.monotonic(),
            "windows_at_prev_rung": self._windows_at_rung,
        }
        if stamp is not None:
            # the journal's HLC stamp, when enabled — lets /debug/window
            # rows line up against the merged fleet timeline (wall +
            # monotonic stay: humans and single-process ordering)
            entry["hlc"] = stamp.to_dict()
        self._rung_timeline.append(entry)
        self._windows_at_rung = 0

    def _handle_device_failure(self, err: Exception) -> None:
        """One device-leg failure: abandon every in-flight window (their
        handles may be poisoned — a donated buffer consumed by a failed
        dispatch can never be read or rebound), re-seed the resident ring
        and host staging from scratch, and demote one rung. The caller
        recomputes the CURRENT window at the new rung, so the interval
        still publishes."""
        reason = (err.reason if isinstance(err, DeviceWindowError)
                  else "runtime_error")
        with self._pipeline_lock:
            abandoned = len(self._inflight)
            self._inflight.clear()
        # both packed engines re-seed: the failed rung's ring is poisoned
        # and the OTHER engine's buffers may alias handles a drained
        # window read — re-entering either rung starts from a full re-pack
        if self._engine is not None:
            self._engine.reset()
        if self._engine_serial is not None:
            self._engine_serial.reset()
        if self._engine_fused is not None:
            # the fused ring is poisoned like any other: reset drops its
            # device block AND the host pending ring — the orphaned
            # windows republish from _fused_pending snapshots at the
            # demoted tier (zero gaps)
            self._engine_fused.reset()
        self._program = None  # a failed serial program recompiles fresh
        # a failure at the MULTI-HOST rung demotes to "mesh minus one
        # host" first: rung 0 is kept, but its engine becomes the
        # surviving single-host sharded engine — the next failure (a
        # genuinely dead local device) walks the ordinary ladder
        mesh_demotion = (self._multihost_active()
                         and not self._mesh_degraded
                         and self._rung == RUNG_PIPELINED)
        # likewise a failure at the FUSED tier demotes WITHIN rung 0
        # first — the fused flag flips and rung 0's engine becomes the
        # ordinary packed-pipelined one; the next failure walks the
        # ladder. Checked under _results_lock below via the same
        # rung-0 gate the dispatch path used.
        fused_demotion = (not mesh_demotion
                          and self._rung == RUNG_PIPELINED
                          and self._fused_tier_active())
        with self._results_lock:
            prev = self._rung
            prev_name = self._rung_display(prev)  # before any flag flip
            from_name = ""
            if mesh_demotion:
                from_name = prev_name
                self._mesh_degraded = True
                rung = prev  # rung 0 stays; its engine changes tier
            elif fused_demotion:
                from_name = RUNG_NAME_FUSED
                self._fused_degraded = True
                rung = prev  # rung 0 stays; its engine changes tier
            else:
                self._rung = min(prev + 1, RUNG_NUMPY)
                rung = self._rung
            self._clean_windows = 0
            self._windows_since_failure = 0
            if self._just_promoted:
                # a failed PROBE (the promoted rung died before proving
                # itself): back off the next probe exponentially
                self._probe_penalty = min(self._probe_penalty * 2,
                                          self._probe_penalty_cap)
                self._just_promoted = False
            self._demotions_by_reason[reason] = \
                self._demotions_by_reason.get(reason, 0) + 1
            self._stats["window_demotions_total"] += 1
            self._stats["window_rung"] = rung
            self._last_window_failure = f"{reason}: {err}"[:240]
            self._record_rung_transition_locked(prev, rung, reason,
                                                from_name=from_name)
        if mesh_demotion:
            self._demote_mesh(reason)
        log.error("fleet window device leg failed (%s) at rung %s; "
                  "demoting to %s, %d in-flight window(s) abandoned, "
                  "resident ring re-seeded: %s", reason,
                  from_name or prev_name, self._rung_display(rung),
                  abandoned, err)

    def _ladder_window_ok(self) -> None:
        """One window published without a device failure. At a demoted
        rung, ``repromote_after`` consecutive clean windows retry the
        rung above (one step at a time — the breaker's half-open probe,
        ladder-shaped). A failure during the retried rung demotes right
        back and restarts the count."""
        promoted = None
        with self._results_lock:
            self._windows_since_failure += 1
            self._windows_at_rung += 1
            if self._just_promoted:
                self._just_promoted = False  # the rung proved itself
                if self._rung == RUNG_PIPELINED:
                    # reset only AFTER the healthy rung publishes a clean
                    # window — resetting at promotion time would let a
                    # rung-0-specific failure probe at a constant ~2×
                    # cadence forever instead of decaying to the cap
                    self._probe_penalty = 1
            if self._rung != RUNG_PIPELINED:
                self._clean_windows += 1
                needed = self._repromote_after * self._probe_penalty
                if self._clean_windows >= needed:
                    self._rung -= 1
                    self._clean_windows = 0
                    self._just_promoted = True
                    self._stats["window_repromotions_total"] += 1
                    self._stats["window_rung"] = self._rung
                    promoted = self._rung
                    self._record_rung_transition_locked(
                        self._rung + 1, self._rung, "repromoted")
            elif self._fused_degraded and self._fused_window_k > 1:
                # within-rung-0 probe back to the fused tier: same
                # clean-window hysteresis as the ladder proper. The
                # fused engine re-seeds its ring from scratch on the
                # next interval (its reset survived with program caches
                # intact), so the probe costs one full re-pack.
                self._clean_windows += 1
                needed = self._repromote_after * self._probe_penalty
                if self._clean_windows >= needed:
                    from_name = self._rung_display(RUNG_PIPELINED)
                    self._fused_degraded = False
                    self._clean_windows = 0
                    self._just_promoted = True
                    self._stats["window_repromotions_total"] += 1
                    promoted = RUNG_PIPELINED
                    self._record_rung_transition_locked(
                        RUNG_PIPELINED, RUNG_PIPELINED, "repromoted",
                        from_name=from_name)
        if promoted is not None:
            log.info("fleet window ladder: clean-window threshold met — "
                     "re-promoted to rung %d (%s)", promoted,
                     self._rung_display(promoted))

    def _fetch_device(self, fn: "Callable[[], object]") -> object:
        """Blocking device fetch with MonitorWatchdog-style stall
        detection: the fetch runs on the persistent ``_FetchWorker``
        thread bounded by ``dispatch_timeout`` — a hung dispatch (wedged
        tunnel, dead device runtime) DEMOTES instead of wedging the
        aggregation loop forever. On a stall the worker is abandoned
        (parked in native code on a handle the ring re-seed guarantees
        nothing else reads) and replaced lazily. ``device.stall``
        injects a deterministic hang of ``arg`` seconds ahead of the
        real fetch."""
        spec = fault.fire("device.stall")

        def work() -> object:
            if spec is not None and spec.arg:
                _time.sleep(float(spec.arg))
            return fn()

        timeout = self._dispatch_timeout
        if timeout <= 0:
            return work()
        worker = self._fetch_worker
        if worker is None or not worker.alive():
            worker = self._fetch_worker = _FetchWorker()
        outcome = worker.run(work, timeout)
        if outcome is None:
            # abandon the occupied worker, but queue its stop sentinel:
            # a TRANSIENTLY stuck fetch that eventually completes lets
            # the thread exit instead of parking forever; a truly wedged
            # one is no worse off
            self._fetch_worker = None
            worker.stop()
            raise DeviceWindowError(
                "stall", f"window fetch exceeded aggregator."
                f"dispatchTimeout {timeout:g}s")
        kind, value = outcome
        if kind == "error":
            raise value
        return value

    # -- aggregation -------------------------------------------------------

    def aggregate_once(self) -> "FleetResults | None":
        """One pipeline step: dispatch this interval's window, publish the
        oldest in-flight one.

        At ``pipeline_depth`` 1 (the constructor default) the two halves
        run back-to-back — classic serial semantics, every call publishes
        the window it assembled. At depth D ≥ 2 the dispatched window
        stays in flight while the PREVIOUS window is fetched, scattered,
        and published: the device computes window N while the host
        assembles N+1, and the blocking fetch (``window.pipeline_wait``)
        only pays whatever the device hasn't already finished. Returns
        the published :class:`FleetResults` (None when nothing published
        yet — the pipeline is still filling).

        An empty fleet drains the pipeline instead of dispatching, so
        results never rot in flight when reports stop.
        """
        t_win = _time.perf_counter()
        now = self._clock()
        with self._lock:
            live = {name: s for name, s in self._reports.items()
                    if now - s.received <= self._stale_after}
            self._reports = dict(live)
            for name in [n for n in self._history if n not in live]:
                del self._history[name]
            for name in [n for n in self._superseded_runs if n not in live]:
                del self._superseded_runs[name]
            # _seq_trackers are NOT pruned here: they must survive
            # partitions longer than stale_after (see __init__ comment)
            for name in [n for n, e in self._degraded.items()
                         if now - e["last_at"] > self._degraded_ttl]:
                del self._degraded[name]
        # one autoscale observation per aggregation interval — BEFORE
        # the empty-fleet early return, so an idle fleet still feeds
        # the scale-down streak
        self._autoscale_tick()
        if not live:
            return self._drain_pipeline()
        # one telemetry cycle per non-empty fleet window, with the
        # assembly/h2d/compile/wait legs as stages (the same legs the
        # last_*_ms stats report — the histograms add distribution)
        with telemetry.span("aggregator.window"):
            stored_sorted = sorted(live.values(),
                                   key=lambda s: s.report.node_name)
            zone_names = sorted(
                {z for s in stored_sorted for z in s.zone_names})
            # degradation-ladder retry loop: a device-leg failure demotes
            # one rung and RECOMPUTES this interval's window there, so a
            # dead device costs latency, never a publish. Bounded: the
            # rung strictly increases per retry and the bottom rung's
            # failures re-raise (a NumPy bug is a bug, not degradation).
            while True:
                try:
                    # republish windows a fused-tier failure orphaned
                    # (no-op while the fused ring is intact or empty);
                    # a failure HERE re-enters the same demote+retry
                    # loop with the un-replayed snapshots preserved
                    self._replay_fused_pending()
                    return self._window_step(stored_sorted, zone_names,
                                             now, t_win)
                except Exception as err:
                    if (not self._fallback_enabled
                            or self._rung >= RUNG_NUMPY):
                        raise
                    self._handle_device_failure(err)

    def _window_step(self, stored_sorted: list, zone_names: list[str],
                     now: float, t_win: float) -> "FleetResults | None":
        """One dispatch+publish pass at the CURRENT ladder rung."""
        rung = self._rung
        if rung >= RUNG_NUMPY:
            pending = self._dispatch_numpy(stored_sorted, zone_names,
                                           now, t_win)
        elif rung >= RUNG_EINSUM or not self._use_packed():
            pending = self._dispatch_legacy(stored_sorted, zone_names,
                                            now, t_win)
        elif rung == RUNG_PIPELINED and self._fused_tier_active():
            # the fused tier publishes on its own cadence (K windows
            # per flush, all inside the flush call) — it never enters
            # the per-window pipeline deque below
            return self._window_step_fused(stored_sorted, zone_names,
                                           now, t_win)
        else:
            pending = self._dispatch_packed(stored_sorted, zone_names,
                                            now, t_win, rung)
        # every demoted rung drains each window (no in-flight handle
        # outlives its own interval); only the healthy rung pipelines —
        # the legacy path included (temporal/accuracy modes pipeline at
        # rung 0 exactly as before the ladder existed)
        depth = self._pipeline_depth if rung == RUNG_PIPELINED else 1
        with self._pipeline_lock:
            self._inflight.append(pending)
            # prune cumulative totals while the device computes —
            # host work needing no outputs overlaps the window
            for name, seen in list(self._cum_last_seen.items()):
                if now - seen > self._cum_retention:
                    del self._cum_last_seen[name]
                    self._cum.pop(name)
            published = None
            while len(self._inflight) >= depth:
                published = self._publish(self._inflight.popleft())
        if published is not None:
            self._ladder_window_ok()
        return published

    def _use_packed(self) -> bool:
        """Packed-f16 resident path is the default; the serial einsum-f32
        path serves accuracy mode (the 0.5%-budget validation config),
        temporal mode (no packed layout for [N, W, T, F] histories), and
        training-dump capture (which needs the assembled host batch)."""
        return (not self._accuracy_mode and self._model_mode != "temporal"
                and not self._dump_dir)

    def _drain_pipeline(self) -> "FleetResults | None":
        published = None
        failure: Exception | None = None
        eng = self._engine_fused
        if eng is not None and eng.pending_occupancy():
            # reports stopped arriving (or shutdown): force-flush the
            # fused ring so its staged windows publish instead of
            # rotting host-side — results never rot in flight, fused
            # tier included
            try:
                zones = self._fused_pending[-1][1]
                params = self._params_for_zones(len(zones))
                if params is None:
                    params = np.zeros((), np.float32)
                flush = eng.flush(params)
                if flush is not None:
                    published = self._dispatch_fused_flush(eng, flush,
                                                           0.0)
            except Exception as err:
                failure = err
        with self._pipeline_lock:
            while self._inflight:
                try:
                    published = self._publish(self._inflight.popleft())
                except Exception as err:
                    # a drain has no current window to recompute (empty
                    # fleet or shutdown) — abandon what's left, demote,
                    # and let the next live window run at the lower rung
                    failure = err
                    break
        if failure is not None:
            if not self._fallback_enabled:
                raise failure
            self._handle_device_failure(failure)
            # windows a failed fused flush orphaned republish at the
            # demoted tier right away (a drain has no next interval to
            # carry them); repeated failures walk the ladder like the
            # aggregate_once retry loop, and the bottom rung re-raises
            while True:
                try:
                    published = self._replay_fused_pending() or published
                    break
                except Exception as err:
                    if (not self._fallback_enabled
                            or self._rung >= RUNG_NUMPY):
                        raise
                    self._handle_device_failure(err)
        return published

    # -- dispatch half ------------------------------------------------------

    def _fused_engine(self) -> FusedWindowEngine:
        """Rung 0's fused-tier engine (lazy, like the packed engines).
        Runs on the FULL configured mesh — the resident block and scan
        operands are global arrays with node-axis shardings, so XLA
        shards the scan body exactly like the unfused packed program."""
        if self._engine_fused is None:
            self._engine_mesh = self._mesh
            self._engine_fused = FusedWindowEngine(
                self._mesh, backend=self._backend,
                model_mode=self._model_mode,
                node_bucket=self._node_bucket,
                workload_bucket=self._workload_bucket,
                shrink_after=self._bucket_shrink_after,
                fused_k=self._fused_window_k)
        return self._engine_fused

    def _window_step_fused(self, stored_sorted: list,
                           zone_names: list[str], now: float,
                           t_win: float) -> "FleetResults | None":
        """One interval at the fused tier: HOST-ONLY staging, and — on
        every K-th interval (or a forced shape-change flush) — one
        device dispatch + one batched fetch publishing all pending
        windows. Non-flush intervals return None (the ring is filling,
        same contract as a filling pipeline) and cost no device sync at
        all: that is the amortization this tier exists for."""
        engine = self._fused_engine()
        rows = [
            RowInput(name=s.report.node_name, report=s.report,
                     zone_names=s.zone_names,
                     # content identity, as on the packed path: a v2
                     # FLAG_SAME delta stages zero rows end to end
                     ident=((s.run, s.content_seq or s.seq)
                            if s.run and s.seq > 0 else None))
            for s in stored_sorted]
        params = self._params_for_zones(len(zone_names))
        if params is None:
            params = np.zeros((), np.float32)  # ratio-only: unused leaf
        # snapshot BEFORE staging: if anything below fails, the ladder
        # retry recomputes THIS interval itself, so only the snapshot is
        # popped back off; EARLIER snapshots stay until their windows
        # actually publish (the zero-gaps invariant)
        self._fused_pending.append((stored_sorted, zone_names, now,
                                    t_win))
        try:
            with telemetry.span("window.h2d_delta"):
                _meta, flush = engine.stage(rows, zone_names, params)
            t_staged = _time.perf_counter()
            # consulted AFTER the host staging, covering both flush and
            # accumulate intervals — a mid-scan fault abandons the ring
            # and the pending windows republish at the demoted tier
            if fault.fire("device.dispatch_error") is not None:
                raise DeviceWindowError(
                    "dispatch_error",
                    "injected dispatch failure (fused window scan)")
        except BaseException:
            self._fused_pending.pop()
            raise
        stage_ms = (t_staged - t_win) * 1e3
        if flush is None:
            # ring filling: no device leg this interval. The per-call
            # leg stats say so honestly (the previous flush's batch
            # cost must not read as THIS interval's device time).
            with self._results_lock:
                self._stats["last_assembly_ms"] = stage_ms
                self._stats["last_dispatch_ms"] = 0.0
                self._stats["last_wait_ms"] = 0.0
                self._stats["last_fetch_ms"] = 0.0
                self._stats["last_device_ms"] = 0.0
                self._stats["last_h2d_rows"] = 0
            return None
        published = self._dispatch_fused_flush(engine, flush, stage_ms)
        if published is not None:
            self._ladder_window_ok()
        return published

    def _dispatch_fused_flush(self, engine: FusedWindowEngine,
                              flush: FusedFlush,
                              stage_ms: float) -> "FleetResults | None":
        """Dispatch one fused batch, fetch ALL its outputs in one
        transfer, publish every live window oldest-first. The batch's
        whole device cost lands on its LAST window's stats sample
        (earlier windows ride free — that is the measured amortization);
        ``sync_per_window_ms`` carries the averaged per-window figure."""
        t0 = _time.perf_counter()
        with telemetry.span("window.fused_scan"):
            if flush.cold:
                # first dispatch of this (buckets, zones, mode, K, DB)
                # key blocks on trace + XLA compile
                with telemetry.span("window.compile"):
                    outs = engine.dispatch(flush)
            else:
                outs = engine.dispatch(flush)
        t_disp = _time.perf_counter()
        fetch_box = [0.0]

        def _materialize() -> np.ndarray:
            with telemetry.span("window.publish_fetch"):
                t_f = _time.perf_counter()
                plane = np.asarray(outs)
                fetch_box[0] = (_time.perf_counter() - t_f) * 1e3
            return plane

        with telemetry.span("window.pipeline_wait"):
            plane = self._fetch_device(_materialize)
        t_done = _time.perf_counter()
        batch_ms = (t_done - t0) * 1e3
        spw = batch_ms / max(1, flush.k_live)
        published = None
        with self._pipeline_lock:
            for j, meta in enumerate(flush.metas):
                # each published window keeps ITS OWN interval's clock
                # (snapshotted at stage time) — staleness is visible in
                # the timestamps, exactly like pipeline-depth staleness
                _, _, w_now, _ = self._fused_pending[0]
                last = j == len(flush.metas) - 1
                published = self._publish(_Pending(
                    kind="fused", out=plane[j], meta=meta, now=w_now,
                    assembly_ms=stage_ms if last else 0.0,
                    dispatch_ms=batch_ms if last else 0.0,
                    h2d_rows=flush.h2d_rows if last else 0,
                    compiled=flush.cold and last,
                    sync_per_window_ms=spw,
                    fused_fetch_ms=fetch_box[0] if last else 0.0))
                self._fused_pending.pop(0)
        return published

    def _replay_fused_pending(self) -> "FleetResults | None":
        """Republish windows ORPHANED by a fused-tier failure: the
        engine reset dropped its ring, so every remaining snapshot in
        ``_fused_pending`` is a staged-but-never-published window.
        Peek-publish-pop, oldest first — a snapshot is only popped
        after its window published, so a failure mid-replay (this
        raises; the caller demotes and retries) loses nothing. No-op
        while the fused ring is intact (its snapshots are live, not
        orphaned) or when there is nothing pending."""
        if not self._fused_pending:
            return None
        eng = self._engine_fused
        if eng is not None and eng.pending_occupancy():
            return None
        published = None
        while self._fused_pending:
            snap = self._fused_pending[0]
            published = self._window_step(*snap) or published
            self._fused_pending.pop(0)
        return published

    def _packed_engine(self, rung: int) -> PackedWindowEngine:
        """The packed engine for ``rung``: the sharded engine owns rung 0
        on a multi-device node mesh; the packed-serial rung then demotes
        to a SINGLE-device engine pinned to the mesh's first device, so
        a demoted window no longer touches the other shards' devices.
        (Which shard failed is unknowable from a mesh-wide SPMD error —
        if the pinned device is itself the dead one, this rung fails too
        and the ladder walks on to einsum and then the device-free NumPy
        rung; every interval still publishes.)"""
        if self._engine is None:
            kwargs = dict(
                backend=self._backend, model_mode=self._model_mode,
                node_bucket=self._node_bucket,
                workload_bucket=self._workload_bucket,
                shrink_after=self._bucket_shrink_after,
                staging_slots=self._pipeline_depth + 1)
            if self._multihost_active() and not self._mesh_degraded:
                # the multi-host tier: host-local rings over the LIVE
                # mesh (the elastic submesh after a membership change,
                # else the full configured mesh), one SPMD dispatch,
                # owned-rows publish fetch
                mh_mesh = self._live_mesh()
                self._engine_mesh = mh_mesh
                self._shard_count = mh_mesh.devices.size
                self._engine = MultiHostWindowEngine(
                    mh_mesh,
                    process_index=self._mh_process_index,
                    device_process=self._mh_device_process,
                    fabric=self._mh_fabric, **kwargs)
            else:
                mesh = self._mesh
                if self._multihost_enabled and self._mesh_degraded:
                    # "mesh minus one host": the survivors' own devices
                    mesh = self._local_mesh()
                self._engine_mesh = mesh
                self._shard_count = self._mesh_shard_count(mesh)
                cls = (ShardedWindowEngine if self._shard_count > 1
                       else PackedWindowEngine)
                self._engine = cls(mesh, **kwargs)
        if rung == RUNG_PIPELINED or self._shard_count == 1:
            return self._engine
        if self._engine_serial is None:
            base = self._engine_mesh or self._mesh
            self._engine_serial = PackedWindowEngine(
                make_mesh([1], devices=[base.devices.flat[0]]),
                backend=self._backend, model_mode=self._model_mode,
                node_bucket=self._node_bucket,
                workload_bucket=self._workload_bucket,
                shrink_after=self._bucket_shrink_after,
                staging_slots=self._pipeline_depth + 1)
        return self._engine_serial

    def _dispatch_packed(self, stored_sorted: list, zone_names: list[str],
                         now: float, t_win: float,
                         rung: int = RUNG_PIPELINED) -> _Pending:
        """Sync the device-resident packed batch (delta H2D) and dispatch
        the packed-f16 program asynchronously."""
        engine = self._packed_engine(rung)
        rows = [
            RowInput(name=s.report.node_name, report=s.report,
                     zone_names=s.zone_names,
                     # CONTENT identity, not delivery identity: a v2
                     # FLAG_SAME delta bumps seq but not content_seq,
                     # so an unchanged node stages zero rows end to end
                     ident=((s.run, s.content_seq or s.seq)
                            if s.run and s.seq > 0 else None))
            for s in stored_sorted]
        params = self._params_for_zones(len(zone_names))
        if params is None:
            params = np.zeros((), np.float32)  # ratio-only: unused leaf
        with telemetry.span("window.h2d_delta"):
            plan = engine.plan_window(rows, zone_names, params)
        t_planned = _time.perf_counter()
        # consulted AFTER the donated ring update ran: a dispatch that
        # dies here leaves a consumed donated buffer behind — exactly the
        # poisoned-ring state the ladder's reset() re-seed exists for
        if fault.fire("device.dispatch_error") is not None:
            raise DeviceWindowError(
                "dispatch_error",
                "injected dispatch failure (packed window program)")
        if plan.cold:
            # first dispatch of this (buckets, zones, mode) key: the call
            # blocks on trace+XLA-compile; execution itself stays async
            with telemetry.span("window.compile"):
                out = plan.program(*plan.args)
        else:
            out = plan.program(*plan.args)
        copy_async = getattr(out, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()  # D2H queues behind the compute, off the host
        t_dispatched = _time.perf_counter()
        return _Pending(
            kind="packed", out=out, meta=plan.meta, now=now,
            assembly_ms=(t_planned - t_win) * 1e3,
            dispatch_ms=(t_dispatched - t_planned) * 1e3,
            h2d_rows=plan.h2d_rows, compiled=plan.cold,
            h2d_shards=plan.h2d_shards, shards=plan.n_shards,
            fetch=plan.fetch)

    def _dispatch_legacy(self, stored_sorted: list, zone_names: list[str],
                         now: float, t_win: float) -> _Pending:
        """Serial-path dispatch: full assemble, one big H2D, the sharded
        einsum/temporal program, async output copies."""
        aligned = [s.report for s in stored_sorted]
        n_zones = len(zone_names)
        zd_mat, zv_mat = align_zone_matrices(
            aligned, [s.zone_names for s in stored_sorted], zone_names)
        batch = assemble_fleet_batch(
            aligned, n_zones=n_zones, node_bucket=self._node_bucket,
            workload_bucket=self._workload_bucket,
            zone_deltas_mat=zd_mat, zone_valid_mat=zv_mat)
        cold = self._program is None
        if cold:
            if fault.fire("device.compile_error") is not None:
                raise DeviceWindowError(
                    "compile_error",
                    "injected compile failure (serial fleet program)")
            if self._model_mode == "temporal":
                self._program = make_temporal_fleet_program(
                    self._mesh, backend=self._backend,
                    accuracy_mode=self._accuracy_mode)
            else:
                self._program = make_fleet_program(
                    self._mesh, model_mode=self._model_mode,
                    backend=self._backend,
                    accuracy_mode=self._accuracy_mode)
        program = self._program
        params = self._params_for_zones(n_zones)
        feat_hist = t_valid = None
        if self._model_mode == "temporal":
            feat_hist, t_valid = self._history_windows(batch)
        t_assembled = _time.perf_counter()
        if fault.fire("device.dispatch_error") is not None:
            raise DeviceWindowError(
                "dispatch_error",
                "injected dispatch failure (serial fleet program)")
        # ASYNC dispatch: jax returns device futures immediately; the D2H
        # copies start NOW (they queue behind the compute on the device
        # stream) instead of at the np.asarray fetch in _publish. The
        # FIRST dispatch blocks on trace + XLA compile — time it as the
        # window.compile stage (later per-shape recompiles hide inside
        # jax's own cache and are not individually attributable here;
        # the packed path's keyed program cache counts those exactly)
        if cold:
            with telemetry.span("window.compile"):
                result = run_fleet_attribution(program, batch, params,
                                               feat_hist, t_valid)
        else:
            result = run_fleet_attribution(program, batch, params,
                                           feat_hist, t_valid)
        for arr in (result.node_power_uw, result.node_energy_uj,
                    result.workload_power_uw, result.workload_energy_uj):
            copy_async = getattr(arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        t_dispatched = _time.perf_counter()
        return _Pending(
            kind="legacy", out=result, meta=None, now=now,
            assembly_ms=(t_assembled - t_win) * 1e3,
            dispatch_ms=(t_dispatched - t_assembled) * 1e3,
            h2d_rows=batch.n_nodes, compiled=cold,
            batch=batch, aligned=aligned, zone_names=zone_names,
            feat_hist=feat_hist, t_valid=t_valid)

    def _dispatch_numpy(self, stored_sorted: list, zone_names: list[str],
                        now: float, t_win: float) -> _Pending:
        """Bottom ladder rung: the whole window in host NumPy — no jax,
        no device, no compile. Ratio attribution is exact; model rows are
        served for the NumPy-mirrored estimators (linear, mlp) when the
        trained params fit this window's zone axis, and publish zero
        watts otherwise (``parallel.packed.numpy_fleet_window``). Output
        reuses the packed scatter path, so publication is identical to
        the device rungs' minus the f16 wire quantization."""
        from kepler_tpu.parallel.packed import (numpy_fleet_window,
                                                pack_fleet_inputs)

        aligned = [s.report for s in stored_sorted]
        n_zones = len(zone_names)
        zd_mat, zv_mat = align_zone_matrices(
            aligned, [s.zone_names for s in stored_sorted], zone_names)
        batch = assemble_fleet_batch(
            aligned, n_zones=n_zones, node_bucket=self._node_bucket,
            workload_bucket=self._workload_bucket,
            zone_deltas_mat=zd_mat, zone_valid_mat=zv_mat)
        packed = pack_fleet_inputs(batch)
        t_assembled = _time.perf_counter()
        params = None
        if (self._model_mode in ("linear", "mlp")
                and self._params is not None
                and self._model_out_dim() == n_zones):
            params = self._params
        watts = numpy_fleet_window(packed, batch.cpu_deltas.shape[1],
                                   n_zones, params, self._model_mode)
        t_done = _time.perf_counter()
        n_real = batch.n_nodes
        names = list(batch.node_names[:n_real])
        meta = WindowMeta(
            zones=list(zone_names),
            names=names,
            rows={name: i for i, name in enumerate(names)},
            mode=np.asarray(batch.mode, np.int32),
            dt=np.asarray(batch.dt_s, np.float32),
            counts=list(batch.workload_counts),
            ids=list(batch.workload_ids),
            kinds=([a.workload_kinds for a in aligned]
                   + [None] * (watts.shape[0] - n_real)),
            n_live=n_real,
            n_rows=watts.shape[0],
        )
        return _Pending(
            kind="numpy", out=watts, meta=meta, now=now,
            assembly_ms=(t_assembled - t_win) * 1e3,
            dispatch_ms=(t_done - t_assembled) * 1e3,
            h2d_rows=0, compiled=False)

    # -- publish half -------------------------------------------------------

    # keplint: requires-lock=_pipeline_lock
    def _publish(self, p: _Pending) -> "FleetResults":
        """Fetch one in-flight window (the pipeline's only blocking point),
        scatter it into a :class:`FleetResults`, publish, account legs.
        Holding the pipeline lock keeps a lifecycle-thread drain from
        interleaving publishes (out-of-order ``_results``) with the
        aggregation loop's own."""
        t0 = _time.perf_counter()
        fetch_ms = 0.0
        if p.kind == "packed":
            # the engine's plan may override the fetch (per-shard
            # addressable materialization; owned shards only on the
            # multi-host engine — publish cost scales with owned rows)
            fetch_fn = p.fetch or np.asarray

            def _materialize() -> np.ndarray:
                with telemetry.span("window.publish_fetch"):
                    t_f = _time.perf_counter()
                    plane = fetch_fn(p.out)
                    nonlocal_box[0] = (_time.perf_counter() - t_f) * 1e3
                return plane

            nonlocal_box = [0.0]
            with telemetry.span("window.pipeline_wait"):
                packed = self._fetch_device(_materialize)
            fetch_ms = nonlocal_box[0]
            t_fetched = _time.perf_counter()
            results = self._scatter_packed(p, packed)
        elif p.kind in ("numpy", "fused"):
            # host rung: the "fetch" is a no-op — p.out is already a host
            # array (and consulting the stall site would be a lie: there
            # is no device leg to hang). Fused windows look the same by
            # the time they publish: the flush materialized the whole
            # K-batch in one transfer and sliced this window's plane out
            # host-side (the batched fetch cost rides in fused_fetch_ms).
            t_fetched = _time.perf_counter()
            fetch_ms = p.fused_fetch_ms
            results = self._scatter_packed(p, p.out)
        else:
            result = p.out
            with telemetry.span("window.pipeline_wait"):
                fetched = self._fetch_device(lambda: (
                    np.asarray(result.node_power_uw),
                    np.asarray(result.node_energy_uj),
                    np.asarray(result.workload_power_uw),
                    np.asarray(result.workload_energy_uj)))
            node_power, node_energy, wl_power, wl_energy = fetched
            t_fetched = _time.perf_counter()
            results = self._scatter_legacy(p, node_power, node_energy,
                                           wl_power, wl_energy)
        t_done = _time.perf_counter()
        wait_ms = (t_fetched - t0) * 1e3
        scatter_ms = (t_done - t_fetched) * 1e3
        n_workloads = sum(results.counts)
        with self._results_lock:
            self._results = results
            self._last_window_at = p.now
            self._stats["attributions_total"] += 1
            self._stats["last_batch_nodes"] = len(results.names)
            self._stats["last_batch_workloads"] = int(n_workloads)
            self._stats["last_assembly_ms"] = p.assembly_ms
            self._stats["last_dispatch_ms"] = p.dispatch_ms
            self._stats["last_wait_ms"] = wait_ms
            self._stats["last_fetch_ms"] = fetch_ms
            self._stats["last_device_ms"] = p.dispatch_ms + wait_ms
            self._stats["last_scatter_ms"] = scatter_ms
            self._stats["last_attribution_ms"] = (
                p.assembly_ms + p.dispatch_ms + wait_ms + scatter_ms)
            self._stats["last_h2d_rows"] = p.h2d_rows
            self._stats["window_shards"] = p.shards
            self._stats["last_h2d_shards"] = list(p.h2d_shards)
            if p.sync_per_window_ms >= 0.0:
                self._stats["last_sync_per_window_ms"] = (
                    p.sync_per_window_ms)
            engines_all = (self._engine, self._engine_serial,
                           self._engine_fused)
            if any(e is not None for e in engines_all):
                self._stats["window_compiles_total"] = sum(
                    e.compile_count for e in engines_all
                    if e is not None)
            # per-window engine introspection snapshot: computed HERE
            # (the only thread that owns engine state) so /debug/window
            # and collect() read a coherent copy off-thread without
            # touching live engine internals
            engines: dict[str, dict] = {}
            for label, eng in (("pipelined", self._engine),
                               ("serial", self._engine_serial),
                               ("fused", self._engine_fused)):
                if eng is not None:
                    engines[label] = eng.introspect()
            primary = _primary_introspect(engines)
            skew = 0.0
            if primary is not None:
                occupied = [s["rows"] for s in primary["shards"]]
                if any(occupied):
                    skew = max(occupied) / (sum(occupied) / len(occupied))
            self._stats["shard_skew"] = round(skew, 4)
            self._introspect_cache = engines
        log.debug("fleet attribution: %d nodes, %d workloads, %.2f ms "
                  "(h2d rows %d)", len(results.names), n_workloads,
                  self._stats["last_attribution_ms"], p.h2d_rows)
        if p.kind == "legacy" and self._dump_dir:
            # AFTER results publication — file I/O must not delay /v1/results
            try:
                self._dump_training_window(p.batch, wl_power, p.zone_names,
                                           p.now, p.feat_hist, p.t_valid)
            except OSError as err:
                log.warning("training dump failed: %s", err)
        return results

    def _scatter_packed(self, p: _Pending,
                        packed: np.ndarray) -> "FleetResults":
        """One f16 D2H array → the published column-oriented results.

        All arrays are indexed by RESIDENT ROW (``results.rows`` maps
        names to rows — free rows simply hold zeros); node energy is
        reconstituted as power × dt, which is exact for ratio nodes
        (their power was measured energy / dt) and definitional for
        model nodes, modulo the f16 watt quantization the accuracy bench
        budgets at ≤ 0.5%.
        """
        from kepler_tpu.parallel.packed import unpack_fleet_window

        m = p.meta
        wl_watts, _active_w, total_w = unpack_fleet_window(packed)
        node_power = np.multiply(total_w, 1e6, dtype=np.float32)  # W → µW
        node_energy = node_power * m.dt[:, None]  # µW·s = µJ
        row_idx = np.asarray([m.rows[name] for name in m.names],
                             np.intp)
        joules = np.zeros_like(node_power)
        if row_idx.size:
            joules[row_idx] = self._accumulate_node_energy(
                m.names, m.zones, node_energy[row_idx], p.now)
        return FleetResults(
            timestamp=p.now,
            zones=m.zones,
            names=m.names,
            rows=m.rows,
            mode=m.mode,
            node_power_uw=node_power,
            node_energy_uj=node_energy,
            node_joules_total=joules,
            workload_ids=m.ids,
            workload_kinds=m.kinds,
            counts=m.counts,
            wl_watts_f16=wl_watts,
            dt=m.dt,
        )

    def _scatter_legacy(self, p: _Pending, node_power: np.ndarray,
                        node_energy: np.ndarray, wl_power: np.ndarray,
                        wl_energy: np.ndarray) -> "FleetResults":
        """Dense-layout scatter: per-node array views published as-is;
        JSON materializes lazily in ``/v1/results`` (VERDICT r3 weak #3:
        the old per-workload dict scatter was O(nodes × workloads)
        Python per window)."""
        batch = p.batch
        n_real = batch.n_nodes
        names = batch.node_names[:n_real]
        joules = self._accumulate_node_energy(names, p.zone_names,
                                              node_energy[:n_real], p.now)
        return FleetResults(
            timestamp=p.now,
            zones=p.zone_names,  # shared ref; treated immutable
            names=names,
            rows={name: i for i, name in enumerate(names)},
            mode=batch.mode,
            node_power_uw=node_power,
            node_energy_uj=node_energy,
            node_joules_total=joules,
            workload_ids=batch.workload_ids,
            workload_kinds=[a.workload_kinds for a in p.aligned],
            counts=batch.workload_counts,
            wl_power_uw=wl_power,
            wl_energy_uj=wl_energy,
        )

    def _accumulate_node_energy(self, names: list[str],
                                zone_names: list[str],
                                node_energy: np.ndarray,
                                now: float) -> np.ndarray:
        """store[names] += node_energy → cumulative joules [n, Z].

        Steady state (same fleet, same zone axis) is one cached gather,
        one add, one scatter (RowStore). A zone-axis change remaps the
        store's columns by name; new nodes allocate (or reuse) rows."""
        if self._cum_zones != zone_names:
            self._cum.remap_columns(self._cum_zones, zone_names)
            self._cum_zones = list(zone_names)
        vals = self._cum.accumulate(tuple(names), node_energy)
        last_seen = self._cum_last_seen
        for name in names:
            last_seen[name] = now
        return vals / 1e6

    def _params_for_zones(self, n_zones: int) -> Any:
        """Trained params when their output dim matches the canonical zone
        axis this window; otherwise a cached untrained fallback — the
        trained params are kept, so a transient zone-set change (one node
        reporting an extra zone) doesn't destroy them."""
        if not self._model_mode:
            return None
        if self._params is not None and self._model_out_dim() == n_zones:
            return self._params
        fallback = self._fallback_params.get(n_zones)
        if fallback is None:
            import jax

            from kepler_tpu.models.estimator import initializer
            log.warning("model output dim %s != fleet zones %d; using "
                        "untrained %s fallback for this window",
                        self._model_out_dim(), n_zones, self._model_mode)
            kwargs = {}
            if self._model_mode == "temporal":
                # the fallback's positional table must cover the window
                kwargs["t_max"] = max(128, self._history_window)
            fallback = initializer(self._model_mode)(
                jax.random.PRNGKey(0), n_zones=n_zones, **kwargs)
            self._fallback_params[n_zones] = fallback
        return fallback

    def _dump_training_window(self, batch: Any, wl_power_uw: np.ndarray,
                              zone_names: list[str], now: float,
                              feat_hist: np.ndarray | None = None,
                              t_valid: np.ndarray | None = None) -> None:
        """Write one training file: RAPL rows' inputs + their ratio watts.

        Only MODE_RATIO rows carry trustworthy labels (the estimator's own
        output would be circular); rows keep the padded [n, W] layout with
        ``workload_valid`` masking. The file records its OWN zone axis
        (``zone_names``) and per-row ``zone_valid`` — the zone union varies
        across rounds as fleet membership changes, so cmd/train aligns
        columns by name and masks zones a node didn't report (their 0-watt
        rows are absence, not labels). In temporal mode the ratio rows'
        feature-HISTORY windows ([n, W, T, F] + t_valid) are saved too, so
        ``cmd/train --model temporal`` can fit from the same dumps —
        closing the train→serve loop for all five families. Oldest files
        beyond the cap are pruned so a long-running aggregator bounds its
        disk."""
        import os

        ratio_rows = np.flatnonzero(
            (np.asarray(batch.mode[:batch.n_nodes]) != MODE_MODEL))
        if ratio_rows.size == 0:
            return
        os.makedirs(self._dump_dir, exist_ok=True)
        self._dump_seq += 1
        path = os.path.join(
            self._dump_dir, f"window-{int(now * 1e3):014d}-"
            f"{self._dump_seq:06d}.npz")
        r = ratio_rows
        arrays = dict(
            zone_names=np.asarray(zone_names),
            zone_valid=batch.zone_valid[r],
            cpu_deltas=batch.cpu_deltas[r],
            workload_valid=batch.workload_valid[r],
            node_cpu_delta=batch.node_cpu_delta[r],
            usage_ratio=batch.usage_ratio[r],
            dt_s=batch.dt_s[r],
            target_watts=wl_power_uw[r] / 1e6,  # labels in watts
        )
        if feat_hist is not None:
            arrays["feat_hist"] = feat_hist[r]
            arrays["t_valid"] = t_valid[r]
        np.savez_compressed(path, **arrays)
        # prune via an in-process ledger (seeded from disk once) — no
        # per-dump directory scan
        if self._dump_files is None:
            self._dump_files = sorted(
                os.path.join(self._dump_dir, f)
                for f in os.listdir(self._dump_dir)
                if f.startswith("window-") and f.endswith(".npz"))
        else:
            self._dump_files.append(path)
        while len(self._dump_files) > self._dump_max_files:
            try:
                os.unlink(self._dump_files.pop(0))
            except OSError:
                pass

    def _history_windows(self, batch: Any) -> tuple[np.ndarray,
                                                    np.ndarray]:
        """→ (feat_hist [N, W, T, F], t_valid [N, W, T]) aligned with the
        padded fleet batch's (node, workload) layout.

        Holds only ONE node's buffer lock at a time (never the report-
        store lock), so ingest POSTs stall at most for one node's
        ``window_arrays`` — not the whole [N, W, T, F] assembly."""
        from kepler_tpu.models.features import NUM_FEATURES

        n, w = batch.cpu_deltas.shape
        t = self._history_window
        hist = np.zeros((n, w, t, NUM_FEATURES), np.float32)
        tv = np.zeros((n, w, t), bool)
        with self._lock:
            entries = [self._history.get(batch.node_names[i])
                       for i in range(batch.n_nodes)]
        for i, entry in enumerate(entries):
            ids = batch.workload_ids[i]
            if entry is None or not ids:
                continue
            lock, buf = entry
            with lock:
                f, v = buf.window_arrays(ids)
            hist[i, :len(ids)] = f
            tv[i, :len(ids)] = v
        return hist, tv

    def _check_params_shape(self) -> None:
        """Fail at startup (not first window) on params/model mismatch."""
        if self._model_mode not in _REQUIRED_PARAM_KEYS:
            raise ValueError(
                f"unknown aggregator model {self._model_mode!r}; valid: "
                f"{', '.join(_REQUIRED_PARAM_KEYS)}")
        if self._params is None:
            return
        required = _REQUIRED_PARAM_KEYS[self._model_mode]
        missing = [k for k in required if k not in self._params]
        if missing:
            raise ValueError(
                f"params are missing {missing} for model "
                f"{self._model_mode!r} — were they saved from a different "
                "model kind?")
        # the input projection's feature axis must match THIS build's
        # feature vector — a checkpoint trained before a feature-set change
        # (e.g. F 6→7, node_cpu_log) must fail HERE, not as an XLA shape
        # error inside the first window's jit
        from kepler_tpu.models.features import NUM_FEATURES

        in_key, f_axis = {"mlp": ("w0", 0), "linear": ("weight", 0),
                          "moe": ("w0", 1), "deep": ("in_proj", 0),
                          "temporal": ("in_proj", 0)}[self._model_mode]
        got_f = int(np.asarray(self._params[in_key]).shape[f_axis])
        if got_f != NUM_FEATURES:
            raise ValueError(
                f"params' {in_key} has feature dim {got_f} but this build's "
                f"feature vector is F={NUM_FEATURES} — the checkpoint "
                "predates a feature-set change; retrain it "
                "(models.features.build_features documents the vector)")
        if self._model_mode == "temporal":
            t_max = int(np.asarray(self._params["pos_emb"]).shape[0])
            if t_max < self._history_window:
                raise ValueError(
                    f"temporal params were trained with t_max={t_max} < "
                    f"aggregator.historyWindow={self._history_window} — "
                    "shrink the window or retrain with a longer t_max")

    def _model_out_dim(self) -> int | None:
        if self._params is None:
            return None
        # the mode's output bias — its LAST axis length is Z (moe's b1 is
        # [E, Z], so probing by key alone would confuse it with mlp's b1)
        key = _OUTPUT_BIAS_KEY.get(self._model_mode)
        if key is None or key not in self._params:
            return None
        return int(np.asarray(self._params[key]).shape[-1])

    # -- read endpoints ----------------------------------------------------

    def _handle_results(
            self, request: Any) -> tuple[int, dict[str, str], bytes]:
        from urllib.parse import unquote_plus

        query = ""
        if "?" in request.path:
            query = request.path.split("?", 1)[1]
        node = None
        for part in query.split("&"):
            if part.startswith("node="):
                node = unquote_plus(part[len("node="):])
        with self._results_lock:
            results = self._results  # swapped wholesale; safe to read out
            stats = dict(self._stats)
        if node is not None:
            if results is None or node not in results:
                return (404, {"Content-Type": "text/plain"},
                        f"no results for node {node!r}\n".encode())
            payload = results.render_node(node)
        else:
            nodes = ({} if results is None
                     else {name: results.render_node(name)
                           for name in results.names})
            payload = {"nodes": nodes, "stats": stats}
        return (200, {"Content-Type": "application/json"},
                json.dumps(payload).encode())

    def _handle_window_debug(
            self, request: Any) -> tuple[int, dict[str, str], bytes]:
        """``GET /debug/window``: the device plane's flight-recorder
        dump — rung + transition timeline, shard layout, bucket
        ladders, compile-cache keys with their cost stats, last H2D per
        shard, sticky-map skew. Engine state comes from the per-window
        introspection snapshot (coherent, no live engine access)."""
        with self._results_lock:
            payload: dict = {
                "rung": self._rung,
                "rung_name": self._rung_display(self._rung),
                "shards": (self._shard_count
                           if self._rung == RUNG_PIPELINED else 1),
                "windows_at_rung": self._windows_at_rung,
                "windows_since_last_failure": self._windows_since_failure,
                "fallback_enabled": self._fallback_enabled,
                "probe_backoff": self._probe_penalty,
                "timeline": list(self._rung_timeline),
                "demotions_by_reason": dict(self._demotions_by_reason),
                "engines": self._introspect_cache,
                "stats": {k: self._stats[k] for k in (
                    "last_assembly_ms", "last_dispatch_ms",
                    "last_wait_ms", "last_fetch_ms",
                    "last_sync_per_window_ms", "last_scatter_ms",
                    "last_attribution_ms", "last_h2d_rows",
                    "last_h2d_shards", "window_shards", "shard_skew",
                    "window_compiles_total", "window_rung",
                    "window_demotions_total",
                    "window_repromotions_total", "last_batch_nodes",
                    "last_batch_workloads")},
            }
            if self._fused_window_k > 1:
                eng = self._engine_fused
                payload["fused"] = {
                    "k": self._fused_window_k,
                    "active": self._fused_tier_active(),
                    "degraded": self._fused_degraded,
                    "pending_windows": len(self._fused_pending),
                    "ring_occupancy": (eng.pending_occupancy()
                                       if eng is not None else 0),
                }
            if self._last_window_failure:
                payload["last_failure"] = self._last_window_failure
        return (200, {"Content-Type": "application/json"},
                json.dumps(payload).encode())

    def _handle_ring_debug(
            self, request: Any) -> tuple[int, dict[str, str], bytes]:
        """``GET /debug/ring``: the ingest ring's membership +
        ownership view from THIS replica — epoch, peers, hash-space
        share, owned node count, redirect accounting. ``enabled: false``
        (epoch 0) when the tier runs single-replica."""
        ring = self._ring
        now = self._clock()
        with self._lock:
            redirected = self._stats["reports_redirected_total"]
            last_redirect = self._last_redirect_at
            owned = len(self._reports)
        payload: dict[str, Any] = {
            "enabled": ring is not None,
            "epoch": ring.epoch if ring is not None else 0,
            "self": self._self_peer,
            "peers": list(ring.peers) if ring is not None else [],
            "vnodes": ring.vnodes if ring is not None else 0,
            "ownership_ratio": (
                round(ring.ownership_ratio(self._self_peer), 6)
                if ring is not None else 1.0),
            "owned_nodes": owned,
            "redirected_total": redirected,
            "last_redirect_age_s": (
                round(now - last_redirect, 3)
                if last_redirect is not None else None),
        }
        if ring is not None:
            payload["digest"] = ring.membership_digest
            lease = self._lease
            with self._results_lock:
                awaiting = self._awaiting_membership
                decision = self._autoscale_last
            with self._lock:
                rejected = dict(self._membership_rejected)
                applied = dict(self._membership_applied)
            payload["membership"] = {
                "lease": lease.describe() if lease is not None else None,
                "awaiting_membership": awaiting,
                "auto_apply": self._membership_auto_apply,
                "rejected_total": rejected,
                "applied_total": applied,
                "standby_peers": list(self._standby_peers),
            }
            if decision is not None:
                payload["membership"]["autoscale"] = {
                    "direction": decision.direction,
                    "replicas": decision.replicas,
                    "reason": decision.reason,
                }
        return (200, {"Content-Type": "application/json"},
                json.dumps(payload).encode())

    def _handle_fleet_debug(self, request: Any) -> tuple[int,
                                                         dict[str, str],
                                                    bytes]:
        """``GET /debug/fleet``: the per-node scoreboard table."""
        now = self._clock()
        with self._lock:
            snap = self._scoreboard.snapshot(now, self._stale_after)
        return (200, {"Content-Type": "application/json"},
                json.dumps(snap).encode())

    def _handle_bundle_debug(self, request: Any) -> tuple[int,
                                                          dict[str, str],
                                                          bytes]:
        """``GET /debug/bundle``: the one-shot incident snapshot —
        journal + rung timeline + scoreboard + ring view + config
        fingerprint, as CANONICAL JSON (sorted keys, no whitespace) so
        two captures of the same state are byte-identical. Feed the
        file straight to ``python -m kepler_tpu.blackbox``."""
        return (200, {"Content-Type": "application/json"},
                canonical_json(self.bundle()) + b"\n")

    def bundle(self) -> dict[str, Any]:
        """The incident-bundle document (kepler-bundle/v1). Pure state
        capture — safe to call from tests and the chaos conductor."""
        now = self._clock()
        ring = self._ring
        lease = self._lease
        with self._lock:
            scoreboard = self._scoreboard.snapshot(now, self._stale_after)
            stats = dict(self._stats)
        with self._results_lock:
            timeline = list(self._rung_timeline)
            rung = self._rung
        ring_view: dict[str, Any] = {
            "enabled": ring is not None,
            "epoch": ring.epoch if ring is not None else 0,
            "peers": list(ring.peers) if ring is not None else [],
            "holder": lease.holder if lease is not None else "",
        }
        if ring is not None:
            ring_view["digest"] = ring.membership_digest
        return {
            "schema": "kepler-bundle/v1",
            "node": self._journal.node or self._self_peer,
            "captured_hlc": (self._journal.hlc.now().to_dict()
                             if self._journal.enabled else None),
            "journal": self._journal.snapshot(),
            "journal_stats": self._journal.stats(),
            "rung": rung,
            "rung_timeline": timeline,
            "scoreboard": scoreboard,
            "ring": ring_view,
            "stats": {k: stats[k] for k in sorted(stats)
                      if isinstance(stats[k], (int, float, str))},
            "config_fingerprint": self._config_fingerprint,
        }

    # -- prometheus (cluster-level families) -------------------------------

    def collect(self) -> "Iterator[Any]":
        """prometheus_client custom-collector hook (kepler_fleet_*)."""
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )
        # black-box families ride the aggregator's registration (the
        # binary registers ONE collector; the journal's events/HLC
        # families must not need a second)
        yield from self._journal.collect()
        with self._results_lock:
            results = self._results
            stats = dict(self._stats)
            demotions_snap = sorted(self._demotions_by_reason.items())
            # replaced wholesale per published window; nested dicts are
            # never mutated after construction, so reading out is safe
            introspect_snap = self._introspect_cache
        nodes = GaugeMetricFamily(
            "kepler_fleet_nodes", "Nodes in the last fleet batch")
        nodes.add_metric([], stats["last_batch_nodes"])
        yield nodes
        workloads = GaugeMetricFamily(
            "kepler_fleet_workloads", "Workloads in the last fleet batch")
        workloads.add_metric([], stats["last_batch_workloads"])
        yield workloads
        lat = GaugeMetricFamily(
            "kepler_fleet_attribution_latency_ms",
            "Whole-window latency of the last fleet attribution "
            "(assembly + device + scatter)")
        lat.add_metric([], stats["last_attribution_ms"])
        yield lat
        legs = GaugeMetricFamily(
            "kepler_fleet_window_leg_ms",
            "Last fleet window's latency by leg (device = dispatch + "
            "pipeline wait; assembly includes the delta-H2D staging)",
            labels=["leg"])
        legs.add_metric(["assembly"], stats["last_assembly_ms"])
        legs.add_metric(["device"], stats["last_device_ms"])
        legs.add_metric(["dispatch"], stats["last_dispatch_ms"])
        legs.add_metric(["wait"], stats["last_wait_ms"])
        legs.add_metric(["scatter"], stats["last_scatter_ms"])
        yield legs
        h2d_rows = GaugeMetricFamily(
            "kepler_fleet_window_h2d_rows",
            "Node rows re-uploaded (delta H2D) for the last fleet window "
            "— 0 when the resident device batch was already current")
        h2d_rows.add_metric([], stats["last_h2d_rows"])
        yield h2d_rows
        fetch_ms = GaugeMetricFamily(
            "kepler_fleet_window_fetch_ms",
            "Publish-fetch leg of the last fleet window: per-shard "
            "addressable D2H materialization of the result plane "
            "(owned shards only on the multi-host engine, so the cost "
            "scales with owned rows, not fleet size)")
        fetch_ms.add_metric([], stats["last_fetch_ms"])
        yield fetch_ms
        sync_pw = GaugeMetricFamily(
            "kepler_fleet_window_sync_per_window_ms",
            "Amortized host↔device sync cost per published window at "
            "the fused tier: the last fused flush's whole device leg "
            "(dispatch + scan + batched K-window fetch) divided by the "
            "windows it published; 0.0 until a fused flush has run "
            "(fusedWindowK=1 or unfused rungs never set it)")
        sync_pw.add_metric([], stats["last_sync_per_window_ms"])
        yield sync_pw
        shards = GaugeMetricFamily(
            "kepler_fleet_window_shards",
            "Device shards the last fleet window ran over (node-axis "
            "mesh size on the sharded packed path; 1 = unsharded engine "
            "or a demoted single-device ladder rung)")
        shards.add_metric([], stats["window_shards"])
        yield shards
        primary = _primary_introspect(introspect_snap)
        skew = GaugeMetricFamily(
            "kepler_fleet_window_shard_skew_ratio",
            "Sticky-map load skew: max/mean per-shard resident-row "
            "occupancy (1.0 = balanced; the sparse model bucket — and "
            "so the whole mesh's estimator FLOPs — is sized by the "
            "fullest shard)")
        skew.add_metric([], stats["shard_skew"])
        yield skew
        shard_rows = GaugeMetricFamily(
            "kepler_fleet_window_shard_rows",
            "Resident-row occupancy per device shard, split by row "
            "mode (shard-count-bounded cardinality)",
            labels=["shard", "mode"])
        if primary is not None:
            for k, occ in enumerate(primary["shards"]):
                shard_rows.add_metric([str(k), "model"],
                                      occ["model_rows"])
                shard_rows.add_metric([str(k), "ratio"],
                                      occ["rows"] - occ["model_rows"])
        yield shard_rows
        h2d_by_shard = GaugeMetricFamily(
            "kepler_fleet_window_shard_h2d_rows",
            "Rows staged + uploaded per device shard for the last "
            "fleet window (delta H2D; a hot shard here means churn is "
            "landing unevenly)",
            labels=["shard"])
        for k, n in enumerate(stats["last_h2d_shards"]):
            h2d_by_shard.add_metric([str(k)], n)
        yield h2d_by_shard
        staleness = GaugeMetricFamily(
            "kepler_fleet_window_buffer_staleness_windows",
            "Windows since each ping-pong ring slot last served (0 = "
            "served the latest window; a slot stuck high means the "
            "donation rotation is wedged)",
            labels=["slot"])
        if primary is not None:
            for slot, age in enumerate(
                    primary["resident"]["staleness_windows"]):
                staleness.add_metric([str(slot)], age)
        yield staleness
        prog_flops = GaugeMetricFamily(
            "kepler_fleet_window_program_flops",
            "XLA cost_analysis FLOPs of each cached fleet-window "
            "program (captured at cold compile; label cardinality "
            "bounded by the compile-cache cap)",
            labels=["program"])
        prog_bytes = GaugeMetricFamily(
            "kepler_fleet_window_program_bytes",
            "XLA cost_analysis bytes accessed per execution of each "
            "cached fleet-window program",
            labels=["program"])
        prog_mem = GaugeMetricFamily(
            "kepler_fleet_window_program_device_memory_bytes",
            "XLA memory_analysis device footprint (arguments + outputs "
            "+ temps + generated code) of each cached fleet-window "
            "program",
            labels=["program"])
        if introspect_snap:
            seen_programs: set[str] = set()
            for eng in introspect_snap.values():
                prog_lists = [eng.get(kind, ())
                              for kind in ("programs", "updates")]
                fused_sub = eng.get("fused")
                if fused_sub:
                    prog_lists.append(fused_sub.get("programs", ()))
                for progs in prog_lists:
                    for prog in progs:
                        cost = prog.get("cost")
                        if not cost or "flops" not in cost:
                            continue
                        label = cost["label"]
                        if label in seen_programs:
                            continue  # serial engine mirrors a key
                        seen_programs.add(label)
                        prog_flops.add_metric([label], cost["flops"])
                        prog_bytes.add_metric([label],
                                              cost["bytes_accessed"])
                        if "device_memory_bytes" in cost:
                            prog_mem.add_metric(
                                [label], cost["device_memory_bytes"])
        yield prog_flops
        yield prog_bytes
        yield prog_mem
        compiles = CounterMetricFamily(
            "kepler_fleet_window_compiles_total",
            "Fleet-window program-cache misses — attribution programs "
            "AND delta scatter-updates (bucket-ladder shape changes; "
            "growth is geometric, shrink is hysteretic)")
        compiles.add_metric([], stats["window_compiles_total"])
        yield compiles
        rung = GaugeMetricFamily(
            "kepler_fleet_window_degraded",
            "Degradation-ladder rung of the window's device leg "
            "(0 = packed-f16 pipelined [healthy], 1 = packed serial, "
            "2 = einsum-f32 serial, 3 = pure-NumPy host fallback)")
        rung.add_metric([], stats["window_rung"])
        yield rung
        demotions = CounterMetricFamily(
            "kepler_fleet_window_demotions_total",
            "Window device-leg ladder demotions, by failure reason",
            labels=["reason"])
        for reason, count in demotions_snap:
            demotions.add_metric([reason], count)
        yield demotions
        repromotions = CounterMetricFamily(
            "kepler_fleet_window_repromotions_total",
            "Window ladder re-promotions (repromoteAfter consecutive "
            "clean windows at a demoted rung retried the rung above)")
        repromotions.add_metric([], stats["window_repromotions_total"])
        yield repromotions
        total = CounterMetricFamily(
            "kepler_fleet_attributions_total", "Completed fleet attributions")
        total.add_metric([], stats["attributions_total"])
        yield total
        reports = CounterMetricFamily(
            "kepler_fleet_reports_total", "Node reports received")
        reports.add_metric([], stats["reports_total"])
        yield reports
        rejected = CounterMetricFamily(
            "kepler_fleet_reports_rejected_total", "Malformed reports rejected")
        rejected.add_metric([], stats["rejected_total"])
        yield rejected
        quarantined = CounterMetricFamily(
            "kepler_fleet_reports_quarantined_total",
            "Reports quarantined before ingest, by reason",
            labels=["reason"])
        quarantined.add_metric(["malformed"], stats["malformed_total"])
        quarantined.add_metric(["clock_skew"], stats["clock_skew_total"])
        yield quarantined
        duplicates = CounterMetricFamily(
            "kepler_fleet_reports_duplicate_total",
            "Redelivered (run, seq) reports absorbed by the dedup window")
        duplicates.add_metric([], stats["duplicates_total"])
        yield duplicates
        redirected = CounterMetricFamily(
            "kepler_fleet_reports_redirected_total",
            "Reports answered with a 421 owner redirect (node owned by "
            "another ring replica; the agent follows to the owner)")
        redirected.add_metric([], stats["reports_redirected_total"])
        yield redirected
        keyframes = CounterMetricFamily(
            "kepler_fleet_reports_keyframe_requests_total",
            "Wire-v2 delta frames answered with a structured 409 "
            "needs-keyframe (base missing after hand-off/eviction or "
            "run/seq mismatch) — the agent resends full, never a loss")
        keyframes.add_metric([], stats["keyframe_requests_total"])
        yield keyframes
        with self._lock:
            ingest_bytes_snap = sorted(self._ingest_bytes.items())
            version_rollup: dict[int, int] = {1: 0, 2: 0}
            for s in self._reports.values():
                version_rollup[s.wire_version] = \
                    version_rollup.get(s.wire_version, 0) + 1
        ingest_bytes = CounterMetricFamily(
            "kepler_fleet_ingest_bytes_total",
            "Report payload bytes ingested, by wire version (v2 delta "
            "steady state runs far below v1's JSON-framed bytes)",
            labels=["version"])
        for version, count in ingest_bytes_snap:
            ingest_bytes.add_metric([str(version)], count)
        yield ingest_bytes
        wire_version = GaugeMetricFamily(
            "kepler_fleet_wire_version",
            "Live nodes by the wire version of their last stored "
            "report (the v1→v2 fleet-rollout progress rollup)",
            labels=["version"])
        for version, count in sorted(version_rollup.items()):
            wire_version.add_metric([str(version)], count)
        yield wire_version
        ctrl = self._admission
        shed = CounterMetricFamily(
            "kepler_fleet_reports_shed_total",
            "Reports shed by ingest admission control (429 + "
            "Retry-After before decode), by budget signal — loss-free: "
            "shed records stay spooled on the agent and replay later",
            labels=["reason"])
        for reason, count in sorted((ctrl.shed_by_reason() if ctrl
                                     else {}).items()):
            shed.add_metric([reason], count)
        yield shed
        inflight = GaugeMetricFamily(
            "kepler_fleet_ingest_inflight",
            "Admitted ingest requests currently being decoded/merged "
            "(admission sheds at a load-derived multiple of "
            "aggregator.admissionMaxInflight; 0 with admission off)")
        inflight.add_metric([], ctrl.inflight() if ctrl else 0)
        yield inflight
        ingest_lat = GaugeMetricFamily(
            "kepler_fleet_ingest_latency_seconds",
            "EWMA of per-record ingest service time — the admission "
            "controller's latency-budget signal (decays while shedding "
            "so recovery probes always resume; 0 with admission off)")
        ingest_lat.add_metric([], ctrl.latency_ewma() if ctrl else 0.0)
        yield ingest_lat
        ring = self._ring
        ring_epoch = GaugeMetricFamily(
            "kepler_fleet_ring_epoch",
            "Ingest ring membership epoch (monotonic, bumped per "
            "membership change; 0 = ring disabled / single-replica)")
        ring_epoch.add_metric([], ring.epoch if ring is not None else 0)
        yield ring_epoch
        ownership = GaugeMetricFamily(
            "kepler_fleet_ring_ownership_ratio",
            "Share of the consistent-hash space this replica owns "
            "(1.0 = single replica or ring disabled)")
        ownership.add_metric(
            [], ring.ownership_ratio(self._self_peer)
            if ring is not None else 1.0)
        yield ownership
        ring_peers = GaugeMetricFamily(
            "kepler_fleet_ring_peers",
            "Replicas in the current ingest-ring membership (0 = ring "
            "disabled) — the elastic fleet's replica count")
        ring_peers.add_metric([], len(ring) if ring is not None else 0)
        yield ring_peers
        with self._lock:
            rejected_snap = sorted(self._membership_rejected.items())
            applied_snap = sorted(self._membership_applied.items())
        mem_rejected = CounterMetricFamily(
            "kepler_fleet_membership_rejected_total",
            "Membership operations rejected, by structured reason "
            "(equal_epoch_conflict is the split-brain detector firing)",
            labels=["reason"])
        for reason, count in rejected_snap:
            mem_rejected.add_metric([reason], count)
        yield mem_rejected
        mem_applied = CounterMetricFamily(
            "kepler_fleet_membership_applied_total",
            "Membership changes applied, by source (operator | "
            "succession | wire | join | leave | autoscale)",
            labels=["source"])
        for source, count in applied_snap:
            mem_applied.add_metric([source], count)
        yield mem_applied
        with self._results_lock:
            awaiting_now = self._awaiting_membership
            decision_now = self._autoscale_last
            scale_snap = sorted(self._autoscale_decisions.items())
        mem_awaiting = GaugeMetricFamily(
            "kepler_fleet_membership_awaiting_state",
            "1 while this replica is degraded awaiting a membership "
            "(a peer died and it is not the succession issuer, or "
            "takeover is disabled)")
        mem_awaiting.add_metric([], 1 if awaiting_now else 0)
        yield mem_awaiting
        if self._autoscale is not None:
            rec = GaugeMetricFamily(
                "kepler_fleet_autoscale_recommended_replicas",
                "The autoscale policy's current replica recommendation "
                "(enacted only with aggregator.membership.autoApply)")
            rec.add_metric([], decision_now.replicas
                           if decision_now is not None
                           else (len(ring) if ring is not None else 0))
            yield rec
            scale_dec = CounterMetricFamily(
                "kepler_fleet_autoscale_decisions_total",
                "Autoscale observations by decision direction",
                labels=["direction"])
            for direction, count in scale_snap:
                scale_dec.add_metric([direction], count)
            yield scale_dec
        now = self._clock()
        with self._lock:
            lost_by_node = dict(self._lost_by_node)
            delivery_snap = [
                (path, h.cumulative(), h.sum)
                for path, h in sorted(self._delivery_hist.items())]
            node_states = self._scoreboard.states(now, self._stale_after)
        from prometheus_client.core import HistogramMetricFamily
        delivery = HistogramMetricFamily(
            "kepler_fleet_delivery_latency_seconds",
            "End-to-end window delivery latency, agent emit → aggregator "
            "merge (fresh sends from emitted_at; spool replays from the "
            "original appended_at)",
            labels=["path"])
        for path, buckets, total_sum in delivery_snap:
            delivery.add_metric([path], buckets=buckets,
                                sum_value=total_sum)
        yield delivery
        lost = CounterMetricFamily(
            "kepler_fleet_windows_lost_total",
            "Windows that never arrived (seq gaps), by reporting node",
            labels=["node_name"])
        for node, count in lost_by_node.items():
            lost.add_metric([node], count)
        yield lost
        degraded = GaugeMetricFamily(
            "kepler_fleet_degraded_nodes",
            "Nodes whose reports were quarantined within the decay window")
        degraded.add_metric([], len(self.degraded_nodes()))
        yield degraded
        node_state = GaugeMetricFamily(
            "kepler_fleet_node_state",
            "Scoreboard state per node (0 healthy, 1 stale, 2 lossy, "
            "3 anomalous, 4 quarantined); cardinality bounded by the "
            "scoreboard LRU cap",
            labels=["node_name"])
        state_rollup = {name: 0 for name in STATE_NAMES}
        for node, code in node_states.items():
            node_state.add_metric([node], code)
            state_rollup[STATE_NAMES[code]] += 1
        yield node_state
        scoreboard_nodes = GaugeMetricFamily(
            "kepler_fleet_scoreboard_nodes",
            "Scoreboard rollup: nodes currently in each health state",
            labels=["state"])
        for name in STATE_NAMES:
            scoreboard_nodes.add_metric([name], state_rollup[name])
        yield scoreboard_nodes
        node_watts = GaugeMetricFamily(
            "kepler_fleet_node_cpu_watts",
            "Per-node power attributed by the fleet aggregator",
            labels=["node_name", "zone", "mode"])
        node_joules = CounterMetricFamily(
            "kepler_fleet_node_cpu_joules_total",
            "Per-node cumulative energy seen by the fleet aggregator",
            labels=["node_name", "zone", "mode"])
        if results is not None:
            zones = results.zones
            for name in results.names:
                # rows map, not enumerate: the packed-resident layout
                # keeps nodes at stable row indices with holes
                i = results.rows[name]
                mode = "model" if results.mode[i] else "ratio"
                power = results.node_power_uw[i]
                joules = results.node_joules_total[i]
                for j, zone in enumerate(zones):
                    node_watts.add_metric([name, zone, mode],
                                          float(power[j]) / 1e6)
                    node_joules.add_metric([name, zone, mode],
                                           float(joules[j]))
        yield node_watts
        yield node_joules
