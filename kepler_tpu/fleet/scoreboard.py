"""Per-node fleet scoreboard: one glanceable health row per node.

Before this module, answering "which of my 1024 agents is unhealthy?"
meant grepping four counters across families (quarantine, seq gaps,
duplicates, staleness) and eyeballing the power gauges for outliers.
The scoreboard synthesizes them into one bounded table the aggregator
updates at ingest time and serves three ways:

- ``GET /debug/fleet`` — the full table as JSON (operator drill-down);
- ``kepler_fleet_node_state{node_name}`` — per-node enum gauge (the
  state code below), cardinality bounded by the LRU cap;
- ``kepler_fleet_scoreboard_nodes{state}`` — the rollup (how many nodes
  in each state), cardinality fixed at ``len(STATE_NAMES)``.

State machine (priority order — a node is its WORST current state):

``quarantined`` (a report was quarantined within ``flag_ttl``) >
``stale`` (no accepted report within ``stale_after``) >
``anomalous`` (reported node power z-scored past ``anomaly_z`` within
``flag_ttl``) > ``lossy`` (a seq gap charged lost windows within
``flag_ttl``) > ``healthy``.

The anomaly flag is a ROLLING z-score over an EWMA mean/variance of the
node's reported power (sum of valid zone deltas / dt): cheap (O(1) per
report, no history buffer) and self-tuning per node, but it flags
CHANGES, not absolutes — a node that boots hot and stays hot reads
healthy, and the first ``min_samples`` reports never flag while the
baseline forms (docs/developer/observability.md "Fleet scoreboard").

This is the read side ROADMAP items 3 (online calibration: which nodes'
ratio labels to trust) and 4 (power-aware actuation: which node to act
on) consume.

Concurrency: NOT internally locked. The owning :class:`Aggregator`
mutates and snapshots the table under its report-store lock, one call
per ingest — the same discipline as its other per-node tables.
"""

from __future__ import annotations

import math

__all__ = ["FleetScoreboard", "STATE_NAMES", "STATE_HEALTHY",
           "STATE_STALE", "STATE_LOSSY", "STATE_ANOMALOUS",
           "STATE_QUARANTINED"]

# enum-gauge codes: 0 is healthy so dashboards can alert on `> 0`, and
# the ordering matches escalation severity
STATE_HEALTHY = 0
STATE_STALE = 1
STATE_LOSSY = 2
STATE_ANOMALOUS = 3
STATE_QUARANTINED = 4
STATE_NAMES = ("healthy", "stale", "lossy", "anomalous", "quarantined")


class _NodeEntry:
    __slots__ = ("last_seen", "reports", "duplicates", "windows_lost",
                 "last_lost_at", "quarantined", "last_quarantine_at",
                 "last_quarantine_reason", "delivery_ewma_s",
                 "delivery_n", "power_w", "power_mean_w", "power_var",
                 "power_n", "last_z", "last_anomaly_at")

    def __init__(self) -> None:
        self.last_seen = 0.0
        self.reports = 0
        self.duplicates = 0
        self.windows_lost = 0
        self.last_lost_at = 0.0
        self.quarantined = 0
        self.last_quarantine_at = 0.0
        self.last_quarantine_reason = ""
        self.delivery_ewma_s = 0.0
        self.delivery_n = 0
        self.power_w = 0.0
        self.power_mean_w = 0.0
        self.power_var = 0.0
        self.power_n = 0
        self.last_z = 0.0
        self.last_anomaly_at = 0.0


class FleetScoreboard:
    """Count-capped LRU table of per-node health state.

    ``cap`` bounds BOTH memory and metric cardinality: the
    least-recently-updated node is evicted beyond it (an evicted node
    that reports again simply restarts its baselines), junk rows that
    never had an accepted report first. Node names come off the wire,
    so they are length-capped too."""

    def __init__(self, cap: int = 1024, anomaly_z: float = 4.0,
                 flag_ttl: float = 60.0, ewma_alpha: float = 0.2,
                 min_samples: int = 8, name_cap: int = 128,
                 junk_cap: int = 64) -> None:
        self._cap = max(1, int(cap))
        self._anomaly_z = max(0.0, float(anomaly_z))
        self._flag_ttl = max(0.0, float(flag_ttl))
        self._alpha = min(1.0, max(1e-3, float(ewma_alpha)))
        self._min_samples = max(2, int(min_samples))
        self._name_cap = max(1, int(name_cap))
        # rows that never had an accepted report are second-class: their
        # count is sub-capped (the same 64 discipline as the
        # aggregator's degraded table) and they expire once their
        # quarantine flag decays — spoofed names from malformed reports
        # must neither evict real rows nor linger as permanent series
        self._junk_cap = max(1, int(junk_cap))
        self._junk = 0  # rows with reports == 0 (kept exact so the
        # eviction scan is skipped entirely in the common no-junk case)
        self._nodes: dict[str, _NodeEntry] = {}

    def __len__(self) -> int:
        return len(self._nodes)

    # -- update side (caller holds the aggregator's store lock) ------------

    def _touch(self, node: str, weak: bool = False) -> _NodeEntry | None:
        """LRU access: pop-and-reinsert keeps dict order = update
        recency, so cap eviction drops the longest-silent node.

        Node names on the quarantine/duplicate paths come off the wire
        UNVALIDATED (``peek_node_name`` of a report that failed
        decoding), so a malformed-report burst can mint unbounded
        distinct junk names. Eviction therefore prefers rows that never
        had an accepted report (junk churns junk), and a ``weak`` insert
        — used by those paths — is DROPPED rather than evict a real
        node's row when the table is full of accepted reporters."""
        node = node[:self._name_cap]
        entry = self._nodes.pop(node, None)
        if entry is None:
            if weak and self._junk >= self._junk_cap:
                # a flood inside the decay window churns the junk
                # sub-table, never growing it past its cap
                self._evict_junk()
            while len(self._nodes) >= self._cap:
                if self._junk and self._evict_junk():
                    continue
                if weak:
                    return None
                del self._nodes[next(iter(self._nodes))]
            entry = _NodeEntry()
            self._junk += 1  # no accepted report yet
        self._nodes[node] = entry
        return entry

    def _evict_junk(self) -> bool:
        """Evict the oldest never-accepted row. O(position of the first
        junk row); callers skip the scan via ``_junk`` when none exist."""
        victim = next((k for k, v in self._nodes.items()
                       if v.reports == 0), None)
        if victim is None:  # counter drift safety net
            self._junk = 0
            return False
        del self._nodes[victim]
        self._junk -= 1
        return True

    def observe_report(self, node: str, now: float, power_w: float,
                       lost: int = 0) -> None:
        """One ACCEPTED report: liveness, loss accounting, and the
        rolling power z-score."""
        e = self._touch(node)
        if e.reports == 0:
            self._junk -= 1  # first accepted report promotes the row
        e.last_seen = now
        e.reports += 1
        if lost:
            e.windows_lost += int(lost)
            e.last_lost_at = now
        if not math.isfinite(power_w) or power_w < 0.0:
            return  # a hostile/garbage magnitude never poisons the stats
        e.power_w = power_w
        if e.power_n == 0:
            # seed the baseline from the first sample: an EWMA walking
            # up from zero would inject a large cold-start variance
            # transient that takes tens of windows to decay
            e.power_mean_w = power_w
            e.power_n = 1
            return
        if e.power_n >= self._min_samples and self._anomaly_z > 0.0:
            spread = math.sqrt(e.power_var) if e.power_var > 0.0 else 0.0
            # variance floor: a perfectly flat baseline (fake meters,
            # quantized readings) must not turn a 1e-6 W wiggle into an
            # "anomaly" — require real relative + absolute movement
            floor = max(0.05 * max(e.power_mean_w, 0.0), 0.5)
            z = (power_w - e.power_mean_w) / max(spread, floor)
            e.last_z = z
            if abs(z) > self._anomaly_z:
                e.last_anomaly_at = now
        delta = power_w - e.power_mean_w
        e.power_mean_w += self._alpha * delta
        e.power_var = ((1.0 - self._alpha)
                       * (e.power_var + self._alpha * delta * delta))
        e.power_n += 1

    def drop(self, node: str) -> bool:
        """Remove a node's row outright (ingest hand-off: the node now
        belongs to another replica — keeping the row here would decay
        into a permanent false 'stale' signal on the OLD owner)."""
        entry = self._nodes.pop(node[:self._name_cap], None)
        if entry is None:
            return False
        if entry.reports == 0:
            self._junk -= 1
        return True

    def observe_duplicate(self, node: str, now: float) -> None:
        e = self._touch(node, weak=True)
        if e is None:
            return
        e.duplicates += 1
        e.last_seen = now  # a duplicate still proves the sender is alive

    # keplint: taint-sink=bounded-store-key — the name becomes an LRU key
    # and a metric label; callers sanitize wire-peeked names first
    def observe_quarantine(self, node: str, now: float,
                           reason: str) -> None:
        """Weak insert: the name may be hostile garbage (it is peeked
        from a report that FAILED validation) — it never evicts a real
        node's row (the aggregator's separate 64-capped ``_degraded``
        table still records it)."""
        e = self._touch(node, weak=True)
        if e is None:
            return
        e.quarantined += 1
        e.last_quarantine_at = now
        e.last_quarantine_reason = reason

    def observe_delivery(self, node: str, latency_s: float) -> None:
        """EWMA of the end-to-end delivery latency the trace closure
        measured (fresh path only is fed by the aggregator — replay
        latency is outage age, not network health)."""
        e = self._touch(node)
        if e.delivery_n == 0:
            e.delivery_ewma_s = latency_s
        else:
            e.delivery_ewma_s += self._alpha * (latency_s
                                                - e.delivery_ewma_s)
        e.delivery_n += 1

    # -- read side ---------------------------------------------------------
    # (still under the aggregator's store lock — the read paths prune
    # expired junk rows, so they mutate too)

    def _expire_junk(self, now: float) -> None:
        """Drop never-accepted rows whose quarantine flag has decayed:
        a spoofed name must not linger as a permanent 'stale' series
        once its evidence expires (rows with accepted reports live for
        the LRU lifetime — silence about a REAL node is signal)."""
        if not self._junk:
            return
        dead = [k for k, e in self._nodes.items()
                if e.reports == 0
                and not (self._flag_ttl and e.quarantined
                         and now - e.last_quarantine_at <= self._flag_ttl)]
        for k in dead:
            del self._nodes[k]
            self._junk -= 1

    def _state_of(self, e: _NodeEntry, now: float,
                  stale_after: float) -> int:
        if self._flag_ttl and now - e.last_quarantine_at <= self._flag_ttl \
                and e.quarantined:
            return STATE_QUARANTINED
        if stale_after > 0 and now - e.last_seen > stale_after:
            return STATE_STALE
        if self._flag_ttl and e.last_anomaly_at \
                and now - e.last_anomaly_at <= self._flag_ttl:
            return STATE_ANOMALOUS
        if self._flag_ttl and e.last_lost_at \
                and now - e.last_lost_at <= self._flag_ttl:
            return STATE_LOSSY
        return STATE_HEALTHY

    def flagged(self, node: str, now: float) -> bool:
        """Cheap read (caller holds the aggregator's store lock): does
        the node currently carry a live quarantine/anomaly/loss flag?
        The admission controller's priority input — flagged reporters'
        fresh windows queue behind clean ground truth under overload.
        Staleness is deliberately NOT a flag here: "hasn't reported
        lately" describes every node at the front of a recovery burst,
        not a quality problem. Unknown nodes are unflagged."""
        e = self._nodes.get(node[:self._name_cap])
        if e is None:
            return False
        return self._state_of(e, now, float("inf")) != STATE_HEALTHY

    def states(self, now: float, stale_after: float) -> dict[str, int]:
        """node → state code (the enum gauge's samples)."""
        self._expire_junk(now)
        return {node: self._state_of(e, now, stale_after)
                for node, e in self._nodes.items()}

    def snapshot(self, now: float, stale_after: float) -> dict:
        """The ``/debug/fleet`` payload: per-node rows + state rollup."""
        self._expire_junk(now)
        nodes: dict[str, dict] = {}
        rollup = {name: 0 for name in STATE_NAMES}
        for node, e in self._nodes.items():
            state = self._state_of(e, now, stale_after)
            rollup[STATE_NAMES[state]] += 1
            nodes[node] = {
                "state": STATE_NAMES[state],
                "state_code": state,
                "last_seen_age_s": round(max(0.0, now - e.last_seen), 3),
                "reports": e.reports,
                "duplicates": e.duplicates,
                "windows_lost": e.windows_lost,
                "quarantined": e.quarantined,
                "last_quarantine_reason": e.last_quarantine_reason,
                "delivery_ewma_s": round(e.delivery_ewma_s, 6),
                "power_w": round(e.power_w, 3),
                "power_mean_w": round(e.power_mean_w, 3),
                "power_z": round(e.last_z, 3),
                "anomalous": bool(
                    self._flag_ttl and e.last_anomaly_at
                    and now - e.last_anomaly_at <= self._flag_ttl),
            }
        return {"cap": self._cap, "anomaly_z": self._anomaly_z,
                "flag_ttl_s": self._flag_ttl,
                "stale_after_s": stale_after,
                "states": rollup, "nodes": nodes}
