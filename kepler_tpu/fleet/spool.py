"""Crash-safe on-disk report spool: the durable leg of the delivery plane.

PR 1 made the agent→aggregator path *retry*-safe (backoff, breaker); this
module makes it *crash*-safe. Every window report is appended to an
append-only, segment-rotated spool before any send attempt, and the ack
cursor only advances on a 2xx — so an agent crash, a node reboot, or an
aggregator outage longer than the in-memory ring replays the backlog
instead of silently losing it (at-least-once delivery; the aggregator's
``(run, seq)`` dedup window makes replays idempotent).

Layout (one directory per agent):

- ``spool-<n>.seg`` — segments of length-prefixed CRC32-framed records::

      frame = <u32 payload_len> <u32 crc32(payload)> <f64 appended_at> payload

  The payload is the existing ``wire.encode_report`` bytes — no wire
  format fork. ``appended_at`` (agent wall clock, via the injected seam)
  exists only for the health probe's oldest-record age.
- ``cursor.json`` — the persisted ack cursor ``{segment, offset}``,
  written via atomic rename. Records before it were 2xx-acknowledged.

Durability contract:

- **Torn tails recover.** A ``kill -9`` mid-append leaves a partial or
  CRC-broken final frame; :meth:`Spool.open` scans the last segment and
  truncates at the first bad frame, so the spool reopens clean and loses
  at most the one record that was being written.
- **fsync policy is configurable.** ``"none"`` (page cache only),
  ``"batch"`` (default: at most one fsync per ``fsync_interval``, issued
  from the agent's DRAIN thread via :meth:`Spool.sync` — the append path,
  which runs inside the monitor's refresh lock, never fsyncs), or
  ``"always"`` (every append pays its fsync inline; the subprocess crash
  tests use this).
- **Bounded.** ``max_bytes``/``max_records`` caps evict the *oldest*
  segment wholesale; every unacked record so evicted is counted
  (``evicted_total`` → ``kepler_fleet_spool_evicted_total``) — overflow
  is loss, and loss must be visible, never silent.

Fault injection sites (``kepler_tpu.fault``): ``disk.write_error``
(append fails cleanly), ``disk.fsync_error`` (fsync fails; the record
stays appended), ``disk.torn_tail`` (a partial frame is written and the
append raises — the deterministic stand-in for kill -9 mid-write).
"""

from __future__ import annotations

# keplint: monotonic-only — cursor/oldest-age math must survive NTP steps;
# wall time only via the injected clock seam (record appended_at stamps).

import json
import logging
import os
import struct
import threading
import time as _time
import zlib
from dataclasses import dataclass
from typing import BinaryIO, Callable

from kepler_tpu import fault
from kepler_tpu.fleet.delivery import plan_ack_cursor, plan_rewind_tail
from kepler_tpu.utils.atomicio import atomic_write_json

log = logging.getLogger("kepler.fleet.spool")

_FRAME = struct.Struct("<IId")  # payload_len, crc32, appended_at
_SEG_PREFIX = "spool-"
_SEG_SUFFIX = ".seg"
_CURSOR_FILE = "cursor.json"
# a single report is a few KiB; anything near the segment cap is corrupt
MAX_RECORD_BYTES = 16 << 20

FSYNC_POLICIES = ("none", "batch", "always")


class SpoolError(OSError):
    """Spool I/O failed; the caller degrades to in-memory-only delivery."""


@dataclass(frozen=True)
class SpoolRecord:
    """One unacked record, as handed to the drain loop."""

    payload: bytes
    appended_at: float  # agent wall clock at append (clock seam)
    segment: int
    offset: int  # frame start within the segment
    # appended by a PREVIOUS process (crash backlog found at open): the
    # structural "this send is a replay" signal for the delivery-latency
    # path label — wall-clock comparisons can't distinguish a crash
    # backlog from a fresh window under a frozen test clock
    recovered: bool = False


def _seg_name(index: int) -> str:
    return f"{_SEG_PREFIX}{index:010d}{_SEG_SUFFIX}"


def _seg_index(name: str) -> int | None:
    if not (name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX)):
        return None
    try:
        return int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
    except ValueError:
        return None


class Spool:
    """Append-only segmented spool with a persisted ack cursor.

    Thread-safe: appends arrive on the monitor's refresh thread (the
    agent's window listener), peek/ack on the agent's drain thread; all
    state lives behind one lock. Disk work per append is one buffered
    write (+ a batched fsync at most once per ``fsync_interval``).
    """

    # keplint: protocol-transition — cursor birth state
    def __init__(
        self,
        directory: str,
        max_bytes: int = 64 << 20,
        max_records: int = 4096,
        segment_bytes: int = 1 << 20,
        fsync: str = "batch",
        fsync_interval: float = 1.0,
        clock: Callable[[], float] | None = None,
        monotonic: Callable[[], float] | None = None,
    ) -> None:
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"unknown fsync policy {fsync!r}; valid: "
                f"{', '.join(FSYNC_POLICIES)}")
        self._dir = directory
        self._max_bytes = max(segment_bytes, max_bytes)
        self._max_records = max(1, max_records)
        self._segment_bytes = max(4096, segment_bytes)
        # rotate every quarter of the record cap too, so the record-cap
        # eviction (whole oldest segments) has useful granularity
        self._segment_records = max(1, self._max_records // 4)
        self._fsync = fsync
        self._fsync_interval = max(0.0, fsync_interval)
        self._clock = clock or _time.time
        self._monotonic = monotonic or _time.monotonic
        self._lock = threading.Lock()
        # segment index → (record_count, byte_size) for sealed segments;
        # the active (highest-index) segment is tracked live
        self._segments: dict[int, tuple[int, int]] = {}  # keplint: guarded-by=_lock
        self._active: int = 0
        self._active_bytes = 0
        self._active_records = 0
        self._write_fh: BinaryIO | None = None
        self._read_fh: BinaryIO | None = None
        self._read_seg = 0
        self._cursor_seg = 0  # keplint: guarded-by=_lock
        self._cursor_off = 0  # keplint: guarded-by=_lock
        self._last_fsync = float("-inf")  # monotonic
        self._dirty = False  # keplint: guarded-by=_lock
        self._peeked: SpoolRecord | None = None  # keplint: guarded-by=_lock
        self._pending_records = 0  # keplint: guarded-by=_lock
        self._stats = {"appended_total": 0, "acked_total": 0,
                       "evicted_total": 0, "truncated_tail_records": 0,
                       "rewound_total": 0,
                       "write_errors_total": 0, "fsync_errors_total": 0}
        self._open()

    # -- open / recovery ---------------------------------------------------

    # keplint: requires-lock=_lock
    # keplint: protocol-transition — recovery clamps the persisted cursor
    def _open(self) -> None:
        os.makedirs(self._dir, exist_ok=True)
        cursor = self._load_cursor()
        indices = sorted(
            i for i in (_seg_index(n) for n in os.listdir(self._dir))
            if i is not None)
        if not indices:
            indices = [1]
            with open(self._seg_path(1), "ab"):
                pass
        # torn-tail recovery on the LAST segment only: earlier segments
        # were sealed by rotation, so a partial frame can only be at the
        # end of the newest one (a kill -9 mid-append)
        for idx in indices[:-1]:
            count, size = self._scan_segment(idx, truncate=False)
            self._segments[idx] = (count, size)
        last = indices[-1]
        count, size = self._scan_segment(last, truncate=True)
        self._active = last
        self._active_records = count
        self._active_bytes = size
        # records below this (segment, offset) watermark were appended by
        # a previous process → their delivery is a replay by construction
        self._open_tail = (last, size)
        self._write_fh = open(self._seg_path(last), "ab")
        # clamp a cursor pointing at an evicted/older segment or past a
        # truncated tail back onto real data
        self._cursor_seg, self._cursor_off = cursor
        if self._cursor_seg not in self._segments \
                and self._cursor_seg != self._active:
            later = [i for i in indices if i >= self._cursor_seg]
            self._cursor_seg = later[0] if later else self._active
            self._cursor_off = 0
        if self._cursor_seg == self._active:
            self._cursor_off = min(self._cursor_off, self._active_bytes)
        # pending backlog from the counts the scan above already produced;
        # only a mid-segment cursor needs one partial re-read
        counts = {**{i: c for i, (c, _s) in self._segments.items()},
                  self._active: self._active_records}
        pending = sum(c for i, c in counts.items() if i > self._cursor_seg)
        if self._cursor_off == 0:
            pending += counts.get(self._cursor_seg, 0)
        else:
            pending += self._records_from(self._cursor_seg,
                                          self._cursor_off)
        self._pending_records = pending
        if self._pending_records:
            log.info("spool %s: replaying %d unacked record(s) from a "
                     "previous run", self._dir, self._pending_records)

    def _scan_segment(self, index: int, truncate: bool) -> tuple[int, int]:
        """→ (records, valid_bytes); optionally truncate a torn tail."""
        path = self._seg_path(index)
        records = 0
        good = 0
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as fh:
                while True:
                    header = fh.read(_FRAME.size)
                    if len(header) < _FRAME.size:
                        break
                    length, crc, _ts = _FRAME.unpack(header)
                    if length > MAX_RECORD_BYTES or \
                            good + _FRAME.size + length > size:
                        break
                    payload = fh.read(length)
                    if len(payload) < length or \
                            zlib.crc32(payload) != crc:
                        break
                    good += _FRAME.size + length
                    records += 1
        except OSError as err:
            raise SpoolError(f"cannot scan spool segment {path}: {err}") \
                from err
        if truncate and good < size:
            self._stats["truncated_tail_records"] += 1
            log.warning("spool %s: truncating torn tail (%d bytes) — "
                        "recovered from an interrupted append", path,
                        size - good)
            with open(path, "ab") as fh:
                fh.truncate(good)
        return records, good

    # keplint: requires-lock=_lock
    def _count_pending(self) -> int:
        """Records at/after the cursor (startup only; kept incrementally
        afterwards)."""
        pending = 0
        for idx in sorted([*self._segments, self._active]):
            if idx < self._cursor_seg:
                continue
            start = self._cursor_off if idx == self._cursor_seg else 0
            pending += self._records_from(idx, start)
        return pending

    def _records_from(self, index: int, offset: int) -> int:
        count = 0
        try:
            fh = open(self._seg_path(index), "rb")
        except OSError:
            return 0  # unreadable segment: counted as loss by the caller
        with fh:
            fh.seek(offset)
            while True:
                header = fh.read(_FRAME.size)
                if len(header) < _FRAME.size:
                    return count
                length, _crc, _ts = _FRAME.unpack(header)
                payload = fh.read(length)
                if len(payload) < length:
                    return count
                count += 1

    # -- append ------------------------------------------------------------

    def append(self, payload: bytes) -> bool:
        """Durably append one encoded report. Returns False (and counts a
        write error) when the disk rejects it — the caller's in-memory
        path still runs, so a sick disk degrades to PR-1 semantics
        instead of blocking the monitor."""
        frame = _FRAME.pack(len(payload), zlib.crc32(payload),
                            self._clock())
        with self._lock:
            fh = None
            try:
                if (self._active_bytes >= self._segment_bytes
                        or self._active_records >= self._segment_records):
                    self._rotate_locked()
                self._evict_for_locked(len(frame) + len(payload))
                fh = self._write_fh
                assert fh is not None  # opened in _open()
                spec = fault.fire("disk.torn_tail")
                if spec is not None:
                    # the deterministic kill -9 stand-in: part of the
                    # frame lands on disk, then the "process dies"
                    torn = (frame + payload)[:max(1, int(spec.arg or
                                                         _FRAME.size + 3))]
                    fh.write(torn)
                    fh.flush()
                    raise SpoolError("fault-injected torn write")
                if fault.fire("disk.write_error") is not None:
                    raise SpoolError("fault-injected write error")
                fh.write(frame)
                fh.write(payload)
                fh.flush()
            except (OSError, ValueError) as err:
                # ValueError covers writes on a handle something closed
                # underneath us — any of these must degrade, never raise
                # into the monitor's refresh thread
                self._stats["write_errors_total"] += 1
                log.warning("spool append failed: %s", err)
                # a SURVIVED write error must leave the stream framed: any
                # partial bytes are cut back to the last good frame (a real
                # kill -9 never gets here — open() truncates its torn tail)
                if fh is not None:
                    try:
                        fh.truncate(self._active_bytes)
                        fh.seek(self._active_bytes)
                    except (OSError, ValueError):
                        pass
                return False
            self._active_bytes += len(frame) + len(payload)
            self._active_records += 1
            self._pending_records += 1
            self._stats["appended_total"] += 1
            self._dirty = True
            if self._fsync == "always":
                # the caller opted into paying the fsync per append
                self._fsync_locked()
        return True

    def sync(self) -> None:
        """Batched-durability tick — called from the agent's DRAIN
        thread (every wake cycle) and on close, never from the append
        path: ``append()`` runs inside the monitor's refresh lock, where
        a slow disk's fsync would stall attribution and every concurrent
        scrape. Worst case the batch policy leaves ``fsync_interval`` +
        one wake period of appends in the page cache — that is the
        documented trade against a zero-cost hot path."""
        if self._fsync != "batch":
            return
        with self._lock:
            now = self._monotonic()
            if (not self._dirty
                    or now - self._last_fsync < self._fsync_interval):
                return
            self._last_fsync = now
            self._fsync_locked()

    # keplint: requires-lock=_lock
    def _fsync_locked(self) -> None:
        try:
            if fault.fire("disk.fsync_error") is not None:
                raise SpoolError("fault-injected fsync error")
            assert self._write_fh is not None
            os.fsync(self._write_fh.fileno())
            self._dirty = False
        except OSError as err:
            # the record is written (page cache); only the durability
            # guarantee weakened — count it, keep serving
            self._stats["fsync_errors_total"] += 1
            log.warning("spool fsync failed: %s", err)

    # keplint: requires-lock=_lock
    def _rotate_locked(self) -> None:
        # open the NEW segment first: if the disk refuses (full, r/o),
        # the raise leaves every field untouched and the old handle open,
        # so the spool keeps limping on the current segment instead of
        # wedging on a closed file
        new_fh = open(self._seg_path(self._active + 1), "ab")
        old_fh = self._write_fh
        self._segments[self._active] = (self._active_records,
                                        self._active_bytes)
        self._active += 1
        self._active_records = 0
        self._active_bytes = 0
        self._write_fh = new_fh
        if old_fh is not None:
            try:
                if self._fsync != "none":
                    # seal durably: sync() only ever reaches the ACTIVE
                    # fd, so an unsynced tail closed here would sit in
                    # page cache until kernel writeback — outliving the
                    # documented batch-durability window. Rotation is
                    # rare (once per segment), so the cost stays off the
                    # per-window path.
                    os.fsync(old_fh.fileno())
                old_fh.close()
            except OSError as err:
                self._stats["fsync_errors_total"] += 1
                log.warning("spool segment seal fsync failed: %s", err)
                try:
                    old_fh.close()
                except OSError:
                    pass

    # -- eviction (byte/record caps) ----------------------------------------

    # keplint: requires-lock=_lock
    # keplint: protocol-transition — eviction hops the cursor off dead segments
    def _evict_for_locked(self, incoming: int) -> None:
        """Evict oldest sealed segments until the incoming frame fits the
        caps. Unacked records in an evicted segment are LOST — counted in
        ``evicted_total`` so prolonged overflow is alertable."""
        while self._segments and (
                self._total_bytes_locked() + incoming > self._max_bytes
                or self._total_records_locked() + 1 > self._max_records):
            oldest = min(self._segments)
            count, _size = self._segments.pop(oldest)
            lost = count
            if oldest < self._cursor_seg:
                lost = 0  # fully acked segment: nothing unacked lost
            elif oldest == self._cursor_seg:
                lost = self._records_from(oldest, self._cursor_off)
            if lost:
                self._stats["evicted_total"] += lost
                self._pending_records -= lost
                log.warning("spool cap reached: evicted segment %d with "
                            "%d unacked record(s)", oldest, lost)
            try:
                os.unlink(self._seg_path(oldest))
            except OSError:
                pass
            if self._cursor_seg <= oldest:
                self._cursor_seg = oldest + 1
                self._cursor_off = 0
                self._persist_cursor_locked()
            if self._read_seg <= oldest:
                self._close_read_locked()
            self._peeked = None

    def _total_bytes_locked(self) -> int:
        return sum(s for _, s in self._segments.values()) \
            + self._active_bytes

    def _total_records_locked(self) -> int:
        return sum(c for c, _ in self._segments.values()) \
            + self._active_records

    # -- drain (peek / ack) --------------------------------------------------

    # keplint: protocol-transition — the exhausted-segment cursor hop
    def peek(self) -> SpoolRecord | None:
        """Next unacked record, or None when fully drained. Repeated
        peeks without an ack return the same record."""
        with self._lock:
            if self._peeked is not None:
                return self._peeked
            while True:
                rec = self._read_at_locked(self._cursor_seg,
                                           self._cursor_off)
                if rec is not None:
                    self._peeked = rec
                    return rec
                # cursor segment exhausted: hop to the next segment, or
                # report drained when already on the active one
                if self._cursor_seg >= self._active:
                    return None
                nxt = [i for i in [*self._segments, self._active]
                       if i > self._cursor_seg]
                self._cursor_seg = min(nxt)
                self._cursor_off = 0
                self._close_read_locked()

    def peek_batch(self, max_records: int) -> "list[SpoolRecord]":
        """Up to ``max_records`` consecutive unacked records starting at
        the cursor, WITHOUT advancing it — the batched-drain read
        (``/v1/reports``): recovery replay ships K records per request
        instead of one. The first element always equals :meth:`peek`'s
        record, and acking the returned records in order walks the
        cursor past exactly this batch.

        Deliberately side-effect-free (unlike :meth:`peek`, it never
        hops the cursor or recounts the backlog): the scan simply STOPS
        at the first unreadable/corrupt point and the single-record
        path deals with it when the cursor arrives there — a read-ahead
        must never mutate durability state."""
        if max_records <= 0:
            return []
        with self._lock:
            return self._scan_ahead_locked(self._cursor_seg,
                                           self._cursor_off, max_records)

    # keplint: requires-lock=_lock
    def _scan_ahead_locked(self, seg: int, offset: int,
                           max_records: int) -> "list[SpoolRecord]":
        out: list[SpoolRecord] = []
        while len(out) < max_records:
            end = (self._active_bytes if seg == self._active
                   else self._segments.get(seg, (0, 0))[1])
            try:
                with open(self._seg_path(seg), "rb") as fh:
                    while len(out) < max_records \
                            and offset + _FRAME.size <= end:
                        fh.seek(offset)
                        header = fh.read(_FRAME.size)
                        if len(header) < _FRAME.size:
                            return out
                        length, crc, ts = _FRAME.unpack(header)
                        if offset + _FRAME.size + length > end:
                            return out
                        payload = fh.read(length)
                        if len(payload) < length \
                                or zlib.crc32(payload) != crc:
                            return out  # corrupt: stop the read-ahead
                        out.append(SpoolRecord(
                            payload=payload, appended_at=ts,
                            segment=seg, offset=offset,
                            recovered=(seg, offset) < self._open_tail))
                        offset += _FRAME.size + length
            except OSError:
                return out  # unreadable: the drain head will report it
            if len(out) >= max_records or seg >= self._active:
                return out
            nxt = [i for i in [*self._segments, self._active] if i > seg]
            if not nxt:
                return out
            seg, offset = min(nxt), 0
        return out

    # keplint: requires-lock=_lock
    # keplint: protocol-transition — corrupt-region skip moves the cursor
    def _read_at_locked(self, seg: int, offset: int) -> SpoolRecord | None:
        if self._read_fh is None or self._read_seg != seg:
            self._close_read_locked()
            try:
                self._read_fh = open(self._seg_path(seg), "rb")
            except OSError as err:
                if seg == self._active:
                    # transient (fd exhaustion?): do NOT hop the cursor —
                    # the drain stalls and retries on the next wake
                    log.warning("spool: cannot open active segment %d "
                                "(%s); will retry", seg, err)
                    return None
                # a SEALED segment we cannot read is unrecoverable loss:
                # make it visible (the contract: loss is never silent),
                # drop it from the plan, and recount the backlog gauge
                count, _size = self._segments.pop(seg, (0, 0))
                lost = count if offset == 0 else 0  # acked part unknowable
                self._stats["evicted_total"] += lost
                log.warning("spool: sealed segment %d unreadable (%s); "
                            "skipping it — %s unacked record(s) lost",
                            seg, err, lost if offset == 0 else "an unknown "
                            "number of")
                self._pending_records = self._count_pending()
                return None
            self._read_seg = seg
        fh = self._read_fh
        assert fh is not None
        end = (self._active_bytes if seg == self._active
               else self._segments.get(seg, (0, 0))[1])
        if offset + _FRAME.size > end:
            return None
        fh.seek(offset)
        header = fh.read(_FRAME.size)
        if len(header) < _FRAME.size:
            return None
        length, crc, ts = _FRAME.unpack(header)
        if offset + _FRAME.size + length > end:
            return None
        payload = fh.read(length)
        if len(payload) < length or zlib.crc32(payload) != crc:
            # CRC break mid-segment (disk corruption): skip the rest of
            # this segment rather than replaying garbage forever, and
            # recount the pending backlog — the skipped region's record
            # count is unknowable, so the gauge must not drift
            log.warning("spool %s: corrupt record at segment %d offset "
                        "%d; skipping rest of segment",
                        self._dir, seg, offset)
            self._cursor_off = end
            self._pending_records = self._count_pending()
            return None
        return SpoolRecord(payload=payload, appended_at=ts,
                           segment=seg, offset=offset,
                           recovered=(seg, offset) < self._open_tail)

    # keplint: protocol-transition
    def ack(self, rec: SpoolRecord | None = None) -> None:
        """Advance the cursor past ``rec`` (the record whose delivery
        concluded — 2xx or permanent 4xx) and persist it.

        The ack is validated against the CURRENT cursor: if eviction (or
        anything else) moved the cursor since the record was peeked, the
        ack is a no-op — advancing past a record that was never sent
        would silently drop it. ``rec=None`` acks the currently peeked
        record (single-threaded callers/tests)."""
        with self._lock:
            if rec is None:
                rec = self._peeked
            if rec is None:
                return
            # validation against the CURRENT cursor — including the ONE
            # segment hop batched acks (peek_batch) legitimately cross —
            # is the PURE cursor rule (fleet/delivery.py, model-checked
            # by kepmc); anything it rejects means the cursor moved
            # underneath us (cap eviction, a concurrent re-peek) and
            # acking would skip a record that was never sent
            end = (self._active_bytes
                   if self._cursor_seg == self._active
                   else self._segments.get(self._cursor_seg,
                                           (0, 0))[1])
            nxt = [i for i in [*self._segments, self._active]
                   if i > self._cursor_seg]
            new_cursor = plan_ack_cursor(
                (self._cursor_seg, self._cursor_off),
                (rec.segment, rec.offset),
                rec.offset + _FRAME.size + len(rec.payload),
                end, min(nxt) if nxt else None)
            if new_cursor is None:
                return
            self._peeked = None
            self._cursor_seg, self._cursor_off = new_cursor
            self._pending_records = max(0, self._pending_records - 1)
            self._stats["acked_total"] += 1
            self._persist_cursor_locked()
            # fully-acked sealed segments are dead weight: drop them
            for idx in [i for i in self._segments
                        if i < self._cursor_seg]:
                del self._segments[idx]
                try:
                    os.unlink(self._seg_path(idx))
                except OSError:
                    pass

    # keplint: protocol-transition
    def rewind(self, max_records: int) -> int:
        """Move the ack cursor BACK over up to ``max_records`` already-
        acknowledged records so they re-deliver.

        The ingest hand-off's spool-tail replay: when an agent's owner
        moves (membership change, replica loss), the NEW owner has
        never seen the node — re-sending the recent tail rebuilds its
        scoreboard/seq state from real records, and any replica that
        already ingested them absorbs the overlap through the
        ``(run, seq)`` dedup window. Bounded by segment retention:
        fully-acked sealed segments are deleted at ack time, so the
        rewind reaches at most the start of the cursor's current
        segment. Returns how many records the cursor moved back over.
        """
        if max_records <= 0:
            return 0
        with self._lock:
            if self._cursor_off == 0:
                return 0
            end = (self._active_bytes
                   if self._cursor_seg == self._active
                   else self._segments.get(self._cursor_seg, (0, 0))[1])
            end = min(end, self._cursor_off)
            starts: list[int] = []
            try:
                with open(self._seg_path(self._cursor_seg), "rb") as fh:
                    off = 0
                    while off + _FRAME.size <= end:
                        fh.seek(off)
                        header = fh.read(_FRAME.size)
                        if len(header) < _FRAME.size:
                            break
                        length, _crc, _ts = _FRAME.unpack(header)
                        if length > MAX_RECORD_BYTES \
                                or off + _FRAME.size + length > end:
                            break
                        starts.append(off)
                        off += _FRAME.size + length
            except OSError as err:
                log.warning("spool rewind failed (%s); tail not "
                            "re-delivered", err)
                return 0
            # which acked frames re-deliver is the PURE rewind rule
            # (fleet/delivery.py, model-checked by kepmc)
            tail = plan_rewind_tail(starts, self._cursor_off,
                                    max_records)
            if not tail:
                return 0
            self._cursor_off = tail[0]
            self._peeked = None
            self._pending_records += len(tail)
            self._stats["rewound_total"] = (
                self._stats.get("rewound_total", 0) + len(tail))
            self._persist_cursor_locked()
            return len(tail)

    # -- cursor persistence --------------------------------------------------

    def _cursor_path(self) -> str:
        return os.path.join(self._dir, _CURSOR_FILE)

    def _persist_cursor_locked(self) -> None:
        try:
            atomic_write_json(self._cursor_path(),
                              {"v": 1, "segment": self._cursor_seg,
                               "offset": self._cursor_off})
        except OSError as err:
            # a stale cursor only means re-delivery (at-least-once); the
            # aggregator's dedup window absorbs it
            log.warning("spool cursor persist failed: %s", err)

    def _load_cursor(self) -> tuple[int, int]:
        try:
            with open(self._cursor_path(), encoding="utf-8") as fh:
                data = json.load(fh)
            seg, off = int(data["segment"]), int(data["offset"])
            if seg < 1 or off < 0:
                raise ValueError("negative cursor")
            return seg, off
        except FileNotFoundError:
            return 1, 0
        except (OSError, ValueError, TypeError, KeyError) as err:
            # a corrupt cursor re-delivers from the oldest record —
            # at-least-once holds, dedup absorbs it; never crash startup
            log.warning("spool cursor unreadable (%s); replaying from "
                        "oldest record", err)
            return 1, 0

    # -- introspection -------------------------------------------------------

    def pending_records(self) -> int:
        with self._lock:
            return self._pending_records

    def utilization(self) -> float:
        """Fraction of the binding cap in use (0..1): the MAX of byte and
        record utilization — a record-cap-bound spool (small maxRecords,
        roomy maxBytes) must still trip the health probe's early warning
        before eviction starts discarding windows."""
        with self._lock:
            by_bytes = self._total_bytes_locked() / max(1, self._max_bytes)
            by_records = (self._total_records_locked()
                          / max(1, self._max_records))
            return min(1.0, max(by_bytes, by_records))

    def oldest_age(self) -> float | None:
        """Agent-clock seconds since the oldest UNACKED record was
        appended (None when drained) — the backlog-depth probe signal."""
        rec = self.peek()
        if rec is None:
            return None
        return max(0.0, self._clock() - rec.appended_at)

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)

    def health(self) -> dict:
        """Probe for the HealthRegistry: degraded when the spool is close
        to evicting (utilization ≥ 0.9) — the operator's early warning
        before overflow starts discarding windows."""
        util = self.utilization()
        age = self.oldest_age()
        out = {
            "ok": util < 0.9,
            "utilization": round(util, 4),
            "pending_records": self.pending_records(),
            **self.stats(),
        }
        if age is not None:
            out["oldest_record_age_s"] = round(age, 3)
        return out

    def close(self) -> None:
        with self._lock:
            if (self._fsync == "batch" and self._dirty
                    and self._write_fh is not None):
                self._fsync_locked()  # final durability flush
            if self._write_fh is not None:
                try:
                    self._write_fh.close()
                except OSError:
                    pass
                self._write_fh = None
            self._close_read_locked()

    def _close_read_locked(self) -> None:
        if self._read_fh is not None:
            try:
                self._read_fh.close()
            except OSError:
                pass
            self._read_fh = None
            self._read_seg = 0

    def _seg_path(self, index: int) -> str:
        return os.path.join(self._dir, _seg_name(index))
