"""Device-resident pipelined fleet windows: the aggregator's hot-path engine.

The serial window cycle (assemble → one big H2D → dispatch → fetch) pays
three costs every interval that this module removes:

* **Re-allocation + full H2D per window.** The padded packed batch is kept
  RESIDENT on device. Each window, only the rows of nodes whose report
  actually changed since the last window are re-packed on host and
  scatter-updated into the resident array through a ``donate_argnums``
  program — the update writes in place (no per-window batch allocation),
  and a churn burst or partial window uploads only its slice
  (``window.h2d_delta``). The donated handle is dead after the call; the
  engine rebinds (``resident = update(resident, …)``) — keplint KTL110
  enforces that discipline lexically.

* **Recompile thrash on fleet growth.** Padded shapes come from
  :class:`BucketLadder`\\ s: buckets grow geometrically (so a growing
  fleet crosses O(log N) shapes, ever) and only SHRINK after
  ``shrink_after`` consecutive windows at under half occupancy — a fleet
  oscillating around a bucket edge never flip-flops compilations.
  Programs are cached per (node-bucket, workload-bucket, zones, mode)
  key and compile events are counted and surfaced
  (``window.compile``, ``kepler_fleet_window_compiles_total``).

* **Dense mixed-fleet evaluation.** With a model mode set, the packed
  program runs the estimator sparsely: only MODE_MODEL rows are gathered
  through a bucketed ``model_rows`` index vector (bit-identical results —
  see ``parallel.packed``), halving the device leg on a 50/50 fleet.

The engine owns no locks and no HTTP: :class:`Aggregator` snapshots the
report store, hands the engine plain :class:`RowInput`\\ s, and overlaps
the returned dispatch handle with the next window's host work (the
depth-2 pipeline lives in ``fleet.aggregator``).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Any, Callable, NamedTuple, Sequence

import numpy as np

from kepler_tpu import fault
from kepler_tpu.parallel.fleet import (MODE_MODEL, NodeReport,
                                       assemble_fleet_batch)

log = logging.getLogger("kepler.fleet.window")

__all__ = [
    "BucketLadder",
    "DeviceWindowError",
    "FusedFlush",
    "FusedWindowEngine",
    "HostLocalFabric",
    "MultiHostWindowEngine",
    "PackedWindowEngine",
    "RowInput",
    "ShardedWindowEngine",
    "WindowMeta",
    "WindowPlan",
    "align_zone_matrices",
]


class DeviceWindowError(RuntimeError):
    """A device-leg failure inside the fleet window (dispatch, compile,
    bucket-growth recompile, stall). ``reason`` is the bounded label the
    degradation ladder counts demotions under
    (``kepler_fleet_window_demotions_total{reason}``)."""

    def __init__(self, reason: str, detail: str = "") -> None:
        super().__init__(detail or reason)
        self.reason = reason

# per-buffer row-content sentinels: _EMPTY = the device row is the packed
# empty row (cleared / never filled); _DIRTY = unknown content, must be
# re-staged before the buffer serves again (set on cross-buffer row
# reassignment). Compared by identity — they never equal a (run, seq).
_EMPTY = object()
_DIRTY = object()


class BucketLadder:
    """Geometric bucket sizing with shrink hysteresis.

    ``fit(need)`` returns the current bucket, growing it by doubling
    whenever ``need`` exceeds it (growth is immediate: a window must
    never be truncated) and shrinking it — one halving step at a time —
    only after ``shrink_after`` CONSECUTIVE fits at ≤ half occupancy.
    The bucket never drops below ``base``, and ``base`` is rounded up to
    a multiple of ``align`` (the mesh's node-axis size for the node
    ladder) so every rung stays evenly shardable.
    """

    __slots__ = ("base", "align", "shrink_after", "bucket", "_under")

    def __init__(self, base: int, shrink_after: int, align: int = 1) -> None:
        align = max(1, int(align))
        base = max(1, int(base))
        if base % align:
            base = (base // align + 1) * align
        self.base = base
        self.align = align
        self.shrink_after = max(1, int(shrink_after))
        self.bucket = base
        self._under = 0

    def fit(self, need: int) -> int:
        need = max(1, int(need))
        if need > self.bucket:
            while self.bucket < need:
                self.bucket *= 2
            self._under = 0
        elif self.bucket > self.base and need <= self.bucket // 2:
            self._under += 1
            if self._under >= self.shrink_after:
                self.bucket = max(self.base, self.bucket // 2)
                self._under = 0
        else:
            self._under = 0
        return self.bucket


class RowInput(NamedTuple):
    """One live node's contribution to a window, as the engine sees it.

    A NamedTuple, not a dataclass: the aggregator builds one per node
    per window and frozen-dataclass construction alone costs real
    milliseconds at 1k nodes.
    """

    name: str
    report: NodeReport
    zone_names: tuple[str, ...]
    # data identity: (run, seq) for nonce-carrying agents. None = no
    # identity (pre-nonce agent) → the row is re-uploaded every window.
    ident: tuple[str, int] | None


@dataclass
class WindowMeta:
    """Per-window snapshot of the resident row layout (immutable once
    captured — the next window's sync mutates the engine, not this)."""

    zones: list[str]
    names: list[str]  # live node names (publication order)
    rows: dict[str, int]  # name → resident row index
    mode: np.ndarray  # int32 [N]
    dt: np.ndarray  # f32 [N] per-row report interval
    counts: list[int]  # per-ROW real workload count
    ids: list[list[str]]  # per-ROW workload ids
    kinds: list[np.ndarray | None]  # per-ROW workload kinds
    n_live: int
    n_rows: int


@dataclass
class WindowPlan:
    """Everything the caller needs to dispatch one window."""

    program: Callable
    args: tuple  # (params, resident_batch[, model_rows])
    cold: bool  # True → dispatching compiles (time it as window.compile)
    meta: WindowMeta
    h2d_rows: int  # rows staged + uploaded this window (delta or full)
    # sharded engine only: rows uploaded per shard (index = shard), and
    # the shard count — (h2d_rows,) / 1 on the single-device engine
    h2d_shards: tuple[int, ...] = ()
    n_shards: int = 1
    # publish-fetch override: fetches the dispatched output as a host
    # plane whose row layout matches ``meta`` (per-shard addressable
    # fetch on the sharded engines; owned shards only on the multi-host
    # engine, so publish cost scales with owned rows). None = plain
    # ``np.asarray`` of the whole output.
    fetch: Callable[[Any], np.ndarray] | None = None


def align_zone_matrices(reports: Sequence[NodeReport],
                        zone_tuples: Sequence[tuple[str, ...]],
                        zone_names: Sequence[str]) -> tuple[np.ndarray,
                                                            np.ndarray]:
    """Ragged per-node zone arrays → canonical [n, Z] matrices.

    Alignment is GROUPED: nodes sharing a zone tuple (in practice the
    whole fleet) scatter into the canonical matrix with one stacked
    fancy-index per group — no per-node zone arrays. The homogeneous
    case is one stacked fill + a column permutation.
    """
    z_index = {z: i for i, z in enumerate(zone_names)}
    n_zones = len(zone_names)
    n = len(reports)
    zd_mat = np.empty((n, n_zones), np.float32)
    zv_mat = np.empty((n, n_zones), bool)
    if n == 0:
        return zd_mat, zv_mat
    first = zone_tuples[0]
    if all(zt is first or zt == first for zt in zone_tuples):
        # homogeneous batch (the normal case): one stacked fill scattered
        # through the shared column permutation. The batch may cover only
        # PART of the canonical axis (a delta slice while some other node
        # reports an extra zone), so absent columns stay zero/invalid.
        stacked_zd = np.stack([r.zone_deltas_uj for r in reports]).astype(
            np.float32, copy=False)
        stacked_zv = np.stack([r.zone_valid for r in reports]).astype(
            bool, copy=False)
        perm = np.asarray([z_index[z] for z in first])
        zd_mat[:] = 0.0
        zv_mat[:] = False
        zd_mat[:, perm] = stacked_zd
        zv_mat[:, perm] = stacked_zv
        return zd_mat, zv_mat
    zd_mat[:] = 0.0
    zv_mat[:] = False
    groups: dict[tuple[str, ...], list[int]] = {}
    for i, zt in enumerate(zone_tuples):
        groups.setdefault(zt, []).append(i)
    for ztuple, idxs in groups.items():
        perm = np.asarray([z_index[z] for z in ztuple])
        rows = np.asarray(idxs)
        zd_mat[rows[:, None], perm] = np.stack(
            [np.asarray(reports[i].zone_deltas_uj, np.float32)
             for i in idxs])
        zv_mat[rows[:, None], perm] = np.stack(
            [np.asarray(reports[i].zone_valid, bool) for i in idxs])
    return zd_mat, zv_mat


# keplint: forbid-role=http-handler — live engine state (device buffers,
# compile cache, cost ledgers) is mutated by the pipelined window thread;
# HTTP handlers read the PUBLISHED introspection snapshot the aggregator
# caches under _results_lock at _publish time (PR 8 invariant, KTL113)
class PackedWindowEngine:
    """Resident packed batch + program/update cache for the default
    (packed-f16) fleet path. Single-threaded by contract: only the
    aggregation loop calls :meth:`plan_window`."""

    # program-cache bound: ladder moves retire old shapes; keep a few
    # around for oscillation, evict the oldest beyond this
    _CACHE_CAP = 32

    # sparse model-row indices are GLOBAL and replicated on this engine;
    # the sharded subclass flips this to compile the shard-local variant
    _LOCAL_SPARSE = False

    # device shards the resident batch spans (the sharded subclass sets
    # its instance attribute from the mesh)
    n_shards = 1

    def __init__(self, mesh: Any, backend: str = "einsum",
                 model_mode: str | None = None,
                 node_bucket: int = 8, workload_bucket: int = 256,
                 shrink_after: int = 16, staging_slots: int = 2) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kepler_tpu.parallel.mesh import NODE_AXIS

        self._jax = jax
        self._mesh = mesh
        self._backend = backend
        self._model_mode = model_mode
        n_dev = mesh.devices.size
        self._ladder_n = BucketLadder(node_bucket, shrink_after, align=n_dev)
        self._ladder_w = BucketLadder(workload_bucket, shrink_after)
        self._ladder_m = BucketLadder(max(8, n_dev), shrink_after)
        self._ladder_d = BucketLadder(8, shrink_after)
        # sparse model evaluation needs the einsum gather path
        self._sparse = bool(model_mode) and backend == "einsum"
        self._sh_batch = NamedSharding(mesh, P(NODE_AXIS, None))
        self._sh_repl = NamedSharding(mesh, P())
        # cache entries are [program, cold, cost_stats | None, label]:
        # cost stats (XLA cost_analysis / memory_analysis, keyed by the
        # bounded label minted with the cache key) are captured once per
        # entry at the first dispatch-ready plan
        self._programs: dict[tuple, list] = {}
        self._updates: dict[tuple, list] = {}  # (n, width, db) key
        self.compile_count = 0  # program-cache misses (attribution + update)

        # resident state (invalid until the first plan_window). The
        # resident batch is PING-PONGED across `staging_slots` device
        # buffers: the donated in-place update must never target a buffer
        # an in-flight window still reads (donation with outstanding
        # readers blocks the host on CPU PJRT — measured at the full
        # device-leg cost — and would alias on a stream-ordered backend
        # only by luck). Each buffer tracks its own per-row content
        # identity, so the delta staged into buffer B covers everything
        # that changed since B last served.
        self._key: tuple | None = None  # (n_bucket, w_bucket, zones)
        self._buffers: list = []  # device f32 [N, width] ring
        self._content: list[list] = []  # per-buffer per-row ident/_EMPTY/_DIRTY
        self._buf_i = 0
        self._names: list[str | None] = []
        self._row_of: dict[str, int] = {}
        # python lists, not np arrays: the per-row bookkeeping loop does
        # thousands of scalar writes per window and np scalar assignment
        # is ~10× a list store; meta snapshots convert once in C
        self._mode: list[int] = []
        self._dt: list[float] = []
        self._counts: list[int] = []
        self._ids: list[list[str]] = []
        self._kinds: list[np.ndarray | None] = []
        self._free: list[int] = []
        self._empty_row = np.zeros(0, np.float32)
        # reusable HOST staging arrays, rotated per window: a slot is
        # only rewritten after the window that uploaded from it has been
        # fetched (the H2D provably completed), so an async transfer can
        # never observe a half-rewritten source. One slot per pipeline
        # stage plus one covers any depth ≤ staging_slots. The slot count
        # also sizes the device buffer ring.
        self._stages: list[np.ndarray] = [
            np.zeros((0, 0), np.float32)
            for _ in range(max(2, staging_slots))]
        self._stage_i = 0
        # introspection: monotone window counter + per-ring-slot "last
        # window this buffer served" (staleness = how many windows a
        # ping-pong buffer has sat out — a slot that stops serving is a
        # rotation bug, surfaced instead of silently shipping stale rows)
        self._window_seq = 0
        self._buf_served: list[int] = []

    # -- program/update caches ---------------------------------------------

    def _program_for(self, nb: int, wb: int, z: int,
                     mb: int | None) -> list:
        key = (nb, wb, z, self._model_mode or "", mb)
        entry = self._programs.get(key)
        if entry is None:
            # fired BEFORE the entry caches: a failed compile leaves no
            # poisoned cache entry behind, so the retry (at a lower rung,
            # or after the fault window closes) compiles for real
            if fault.fire("device.compile_error") is not None:
                raise DeviceWindowError(
                    "compile_error",
                    f"injected compile failure for program key {key}")
            from kepler_tpu.parallel.packed import make_packed_fleet_program

            program = make_packed_fleet_program(
                self._mesh, n_workloads=wb, n_zones=z,
                model_mode=self._model_mode, backend=self._backend,
                model_bucket=mb, local_model_rows=self._LOCAL_SPARSE)
            entry = [program, True, None, self._program_label(key)]
            self._programs[key] = entry
            self.compile_count += 1
            while len(self._programs) > self._CACHE_CAP:
                self._programs.pop(next(iter(self._programs)))
        return entry

    def _jit_scatter(self, scatter_rows: Callable[..., Any]) -> Any:
        """jit the donated scatter-update with the mesh shardings (the
        sharded engine overrides this — its per-shard operands carry
        placement themselves)."""
        return self._jax.jit(
            scatter_rows, donate_argnums=(0,),
            in_shardings=(self._sh_batch, self._sh_repl, self._sh_repl),
            out_shardings=self._sh_batch)

    def _update_for(self, n: int, width: int, db: int) -> list:
        key = (n, width, db)
        entry = self._updates.get(key)
        if entry is None:
            if fault.fire("device.compile_error") is not None:
                raise DeviceWindowError(
                    "compile_error",
                    f"injected compile failure for update key {key}")

            def scatter_rows(resident: Any, rows: Any, idx: Any) -> Any:
                # index n (the pad value) is out of bounds → dropped
                return resident.at[idx].set(rows, mode="drop")

            entry = [self._jit_scatter(scatter_rows), True, None,
                     self._update_label(key)]
            self._updates[key] = entry
            self.compile_count += 1
            while len(self._updates) > self._CACHE_CAP:
                self._updates.pop(next(iter(self._updates)))
        return entry

    # -- cost introspection ------------------------------------------------

    def _program_label(self, key: tuple) -> str:
        """Bounded metric label for an attribution-program cache key
        (cardinality ≤ the cache cap by construction). The shard suffix
        keeps the sharded rung-0 engine's SPMD programs distinct from
        the serial demotion engine's: after a demotion both engines hold
        cost stats, and on a multi-device mesh the two can reach the
        same bucket key for genuinely different executables."""
        nb, wb, z, mode, mb = key
        label = f"prog_n{nb}_w{wb}_z{z}_{mode or 'ratio'}"
        if mb is not None:
            label += f"_m{mb}"
        return label + self._label_suffix()

    def _update_label(self, key: tuple) -> str:
        n, width, db = key
        return f"upd_n{n}_x{width}_d{db}" + self._label_suffix()

    def _label_suffix(self) -> str:
        return f"_s{self.n_shards}" if self.n_shards > 1 else ""

    def _capture_cost(self, entry: list, fn: Any, args: tuple) -> None:
        """Best-effort XLA ``cost_analysis()``/``memory_analysis()`` for a
        freshly compiled cache entry, stored as ``entry[2]``.

        Runs once per entry, at its first cold plan: an AOT
        ``lower(...).compile()`` of the same program (jax's jit cache and
        the AOT path don't share executables, so this is a second
        compile — bounded by the cache cap, paid only on cold windows).
        On CPU hosts the numbers describe the HOST program XLA built
        (useful for relative comparison, not TPU absolutes —
        docs/developer/observability.md "Device introspection").
        Introspection must never break a window: any failure records the
        error string and the window proceeds."""
        if entry[2] is not None:
            return
        label = entry[3]  # minted with the cache key — never diverges
        stats: dict = {"label": label}
        try:
            from kepler_tpu import telemetry

            # surfaced as window.compile: the call sites sit inside the
            # caller's window.h2d_delta span, and hundreds of ms of XLA
            # compile must not read as staging/upload time
            with telemetry.span("window.compile"):
                compiled = fn.lower(*args).compile()
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            stats["flops"] = float(cost.get("flops", 0.0))
            stats["bytes_accessed"] = float(
                cost.get("bytes accessed", 0.0))
            mem = compiled.memory_analysis()
            if mem is not None:
                arg_b = float(getattr(mem, "argument_size_in_bytes", 0))
                out_b = float(getattr(mem, "output_size_in_bytes", 0))
                tmp_b = float(getattr(mem, "temp_size_in_bytes", 0))
                gen_b = float(getattr(
                    mem, "generated_code_size_in_bytes", 0))
                stats["argument_bytes"] = arg_b
                stats["output_bytes"] = out_b
                stats["temp_bytes"] = tmp_b
                stats["generated_code_bytes"] = gen_b
                stats["device_memory_bytes"] = (arg_b + out_b + tmp_b
                                                + gen_b)
        except Exception as err:
            stats["error"] = f"{type(err).__name__}: {err}"[:160]
            log.debug("cost analysis unavailable for %s: %s", label, err)
        entry[2] = stats

    def cost_stats(self) -> dict[str, dict]:
        """label → captured cost stats for every cached program/update
        that has them (the compile-cache entries' third slot)."""
        out: dict[str, dict] = {}
        for entry in self._programs.values():
            if entry[2] is not None:
                out[entry[2]["label"]] = entry[2]
        for entry in self._updates.values():
            if entry[2] is not None:
                out[entry[2]["label"]] = entry[2]
        return out

    def buffer_staleness(self) -> list[int]:
        """Windows since each ping-pong ring slot last served (0 = the
        slot that served the latest window)."""
        return [self._window_seq - s for s in self._buf_served]

    def shard_occupancy(self) -> list[dict]:
        """Per-shard resident-row occupancy, split by row mode — the
        load the sticky assignment exists to balance (one shard's model
        rows size the whole mesh's sparse estimator bucket)."""
        out = [{"rows": 0, "model_rows": 0} for _ in range(self.n_shards)]
        if self._key is None:
            return out
        per = self._key[0]  # rows per shard (the whole bucket unsharded)
        for i in self._row_of.values():
            k = min(i // per, self.n_shards - 1)
            out[k]["rows"] += 1
            if self._mode[i] == MODE_MODEL:
                out[k]["model_rows"] += 1
        return out

    def introspect(self) -> dict:
        """Engine state dump for ``/debug/window`` — everything bounded:
        ladders are scalars, caches are capped, shards follow the mesh."""
        programs = [{"key": entry[3],
                     "cold": bool(entry[1]), "cost": entry[2]}
                    for entry in self._programs.values()]
        updates = [{"key": entry[3],
                    "cold": bool(entry[1]), "cost": entry[2]}
                   for entry in self._updates.values()]
        return {
            "engine": type(self).__name__,
            "n_shards": self.n_shards,
            "window_seq": self._window_seq,
            "buckets": {
                "node": self._ladder_n.bucket,
                "node_base": self._ladder_n.base,
                "workload": self._ladder_w.bucket,
                "model": self._ladder_m.bucket,
                "delta": self._ladder_d.bucket,
            },
            "resident": {
                "slots": max(len(self._buffers), len(self._stages)),
                "current_slot": self._buf_i,
                "rows": len(self._row_of),
                "staleness_windows": self.buffer_staleness(),
            },
            "shards": self.shard_occupancy(),
            "programs": programs,
            "updates": updates,
            "compile_count": self.compile_count,
        }

    # -- window planning ---------------------------------------------------

    def plan_window(self, rows: Sequence[RowInput],
                    zone_names: Sequence[str], params: Any) -> WindowPlan:
        """Sync the resident batch to ``rows`` and return the dispatchable
        plan. The donated update (if any) runs HERE; the caller dispatches
        ``plan.program(*plan.args)`` (timing the compile when ``cold``)."""
        self._window_seq += 1
        zones_t = tuple(zone_names)
        z = len(zones_t)
        need_w = max((len(r.report.cpu_deltas) for r in rows), default=1)
        prev_nb, prev_wb = self._ladder_n.bucket, self._ladder_w.bucket
        wb = self._ladder_w.fit(need_w)
        nb = self._ladder_n.fit(len(rows))
        if self._buffers and (nb > prev_nb or wb > prev_wb):
            # a bucket GREW mid-run: the next dispatch allocates a larger
            # resident batch + compiles a new rung — the realistic OOM
            # point on a memory-tight device
            if fault.fire("device.oom_on_grow") is not None:
                raise DeviceWindowError(
                    "oom_on_grow",
                    f"injected OOM growing buckets ({prev_nb}, {prev_wb})"
                    f" → ({nb}, {wb})")
        key = (nb, wb, zones_t)
        if key != self._key or not self._buffers:
            h2d_rows = self._rebuild(rows, nb, wb, zones_t)
        else:
            # rotate to the least-recently-read buffer BEFORE updating:
            # its in-flight readers (if any) are ≥ staging_slots windows
            # old and therefore already fetched, so the donated in-place
            # scatter neither blocks nor aliases live reads
            self._buf_i = (self._buf_i + 1) % len(self._buffers)
            h2d_rows = self._delta_sync(rows, zones_t)
        self._buf_served[self._buf_i] = self._window_seq
        meta = WindowMeta(
            zones=list(zones_t),
            names=[r.name for r in rows],
            rows=dict(self._row_of),
            mode=np.asarray(self._mode, np.int32),
            dt=np.asarray(self._dt, np.float32),
            counts=list(self._counts),
            ids=list(self._ids),
            kinds=list(self._kinds),
            n_live=len(rows),
            n_rows=nb,
        )
        resident = self._buffers[self._buf_i]
        args: tuple
        mb: int | None = None
        if self._sparse:
            model_idx = np.flatnonzero(
                np.asarray(self._mode, np.int32) == MODE_MODEL)
            mb = self._ladder_m.fit(max(1, len(model_idx)))
            idx = np.full(mb, nb, np.int32)  # pad → gather-clamped, scatter-dropped
            idx[:len(model_idx)] = model_idx
            args = (params, resident,
                    self._jax.device_put(idx, self._sh_repl))
        else:
            args = (params, resident)
        entry = self._program_for(nb, wb, z, mb)
        program, cold = entry[0], entry[1]
        if cold:
            self._capture_cost(entry, program, args)
        entry[1] = False
        return WindowPlan(program=program, args=args, cold=cold, meta=meta,
                          h2d_rows=h2d_rows, h2d_shards=(h2d_rows,),
                          n_shards=1)

    # -- failure recovery --------------------------------------------------

    def reset(self) -> None:
        """Abandon the resident ring and host staging wholesale.

        Called by the aggregator's degradation ladder after ANY device-leg
        failure: a donated buffer consumed by a failed dispatch can never
        be read or rebound, and a buffer whose update raised mid-scatter
        holds unknown bytes — so per-buffer ``(run, seq)`` identity is
        invalidated across the board and the next :meth:`plan_window`
        performs a full re-pack (``_rebuild``) from the report store.
        Program/update caches survive (a compiled executable is not
        poisoned by a failed dispatch); the bucket ladders keep their
        sizes so recovery doesn't recompile every rung from base.
        """
        self._key = None
        self._buffers = []
        self._content = []
        self._buf_i = 0
        self._names = []
        self._row_of = {}
        self._mode = []
        self._dt = []
        self._counts = []
        self._ids = []
        self._kinds = []
        self._free = []
        self._stage_i = 0
        self._stages = [np.zeros((0, 0), np.float32) for _ in self._stages]
        self._buf_served = []  # _window_seq survives: staleness restarts
        # at zero when the next plan's rebuild re-seeds the ring

    # -- resident maintenance ----------------------------------------------

    def _rebuild(self, rows: Sequence[RowInput], nb: int, wb: int,
                 zones_t: tuple[str, ...]) -> int:
        """Full re-pack: shape key or zone axis changed (or first window)."""
        from kepler_tpu.parallel.packed import (PackedLayout,
                                                pack_fleet_inputs,
                                                packed_width)

        ordered = sorted(rows, key=lambda r: r.name)
        reports = [r.report for r in ordered]
        zd, zv = align_zone_matrices(reports,
                                     [r.zone_names for r in ordered],
                                     zones_t)
        batch = assemble_fleet_batch(reports, n_zones=len(zones_t),
                                     node_bucket=nb, workload_bucket=wb,
                                     zone_deltas_mat=zd, zone_valid_mat=zv)
        packed = pack_fleet_inputs(batch)
        if packed.shape != (nb, packed_width(wb, len(zones_t))):
            raise AssertionError(  # ladder/assembly contract violation
                f"packed shape {packed.shape} != resident bucket "
                f"({nb}, {packed_width(wb, len(zones_t))})")
        n_real = len(ordered)
        # every ring buffer starts from this full pack (each device_put
        # is its own device allocation), all content-current
        self._buffers = [self._jax.device_put(packed, self._sh_batch)
                         for _ in self._stages]
        idents = ([r.ident for r in ordered]
                  + [_EMPTY] * (nb - n_real))
        self._content = [list(idents) for _ in self._buffers]
        self._buf_i = 0
        self._buf_served = [self._window_seq] * len(self._buffers)
        self._key = (nb, wb, zones_t)
        self._names = [r.name for r in ordered] + [None] * (nb - n_real)
        self._row_of = {r.name: i for i, r in enumerate(ordered)}
        self._mode = batch.mode.tolist()
        self._dt = batch.dt_s.tolist()
        self._counts = list(batch.workload_counts)
        self._ids = list(batch.workload_ids)
        self._kinds = ([r.workload_kinds for r in reports]
                       + [None] * (nb - n_real))
        self._free = list(range(nb - 1, n_real - 1, -1))
        width = packed.shape[1]
        self._empty_row = PackedLayout(wb, len(zones_t)).empty_row()
        self._stages = [np.zeros((0, width), np.float32)
                        for _ in self._stages]
        return n_real

    def _delta_sync(self, rows: Sequence[RowInput],
                    zones_t: tuple[str, ...]) -> int:
        """Bring the CURRENT ring buffer up to date: stage every row whose
        content identity differs from what this buffer last held (changed
        reports, joins, clears), upload the slice through the donated
        scatter-update. → rows staged (0 = the buffer is already true).

        The layout (row assignment, mode/dt/count mirrors) is shared
        across buffers and updated once; content identity is PER BUFFER —
        a buffer that sat out K windows stages the union of those
        windows' changes when its turn comes."""
        nb, wb, _ = self._key  # type: ignore[misc]
        live = {r.name for r in rows}
        content = self._content[self._buf_i]
        for name, i in list(self._row_of.items()):
            if name not in live:
                del self._row_of[name]
                self._names[i] = None
                self._mode[i] = 0
                self._dt[i] = 0.0
                self._counts[i] = 0
                self._ids[i] = []
                self._kinds[i] = None
                self._free.append(i)
        changed: list[tuple[int, RowInput]] = []
        for r in rows:
            i = self._row_of.get(r.name)
            if i is None:
                i = self._free.pop()
                self._row_of[r.name] = i
                self._names[i] = r.name
                # the row may still hold another node's data in the OTHER
                # ring buffers — mark their content unknown so they
                # restage it on their next turn (a (run, seq) collision
                # across nodes must never be mistaken for "current")
                for other in self._content:
                    if other is not content:
                        other[i] = _DIRTY
            elif (r.ident is not None and content[i] is not _EMPTY
                    and content[i] is not _DIRTY and content[i] == r.ident):
                continue  # this buffer's row is current
            self._mode[i] = r.report.mode
            self._dt[i] = r.report.dt_s
            self._counts[i] = len(r.report.cpu_deltas)
            self._ids[i] = r.report.workload_ids
            self._kinds[i] = r.report.workload_kinds
            content[i] = r.ident
            changed.append((i, r))
        # clear every freed row THIS buffer still carries data for (rows
        # freed this window or while the buffer sat out), except rows a
        # join just reclaimed — those are in `changed` and a duplicate
        # scatter index would race the two writes nondeterministically
        changed_rows = {i for i, _ in changed}
        cleared = [i for i in range(nb)
                   if self._names[i] is None and content[i] is not _EMPTY
                   and i not in changed_rows]
        for i in cleared:
            content[i] = _EMPTY
        n_stage = len(changed) + len(cleared)
        if n_stage == 0:
            return 0
        # changed and cleared rows are disjoint subsets of the nb resident
        # rows, so n_stage ≤ nb and the cap below can never truncate
        db = min(self._ladder_d.fit(n_stage), nb)
        width = self._empty_row.shape[0]
        self._stage_i = (self._stage_i + 1) % len(self._stages)
        if self._stages[self._stage_i].shape != (db, width):
            self._stages[self._stage_i] = np.zeros((db, width), np.float32)
        stage, idx = self._stages[self._stage_i], np.full(db, nb, np.int32)
        if changed:
            from kepler_tpu.parallel.packed import pack_reports_into

            reports = [r.report for _, r in changed]
            zd, zv = align_zone_matrices(
                reports, [r.zone_names for _, r in changed], zones_t)
            pack_reports_into(stage, reports, zd, zv, wb)
            idx[:len(changed)] = [i for i, _ in changed]
        for k, i in enumerate(cleared):
            stage[len(changed) + k] = self._empty_row
            idx[len(changed) + k] = i
        jax = self._jax
        entry = self._update_for(nb, width, db)
        update = entry[0]  # keplint: donates=0
        update_cold, entry[1] = entry[1], False
        # the donated handle dies inside the call; rebind immediately
        # (KTL110 tracks `resident` through the donating call)
        resident = self._buffers[self._buf_i]
        rows_dev = jax.device_put(stage, self._sh_repl)
        idx_dev = jax.device_put(idx, self._sh_repl)
        if update_cold:
            self._capture_cost(entry, update,
                               (resident, rows_dev, idx_dev))
            # a new (n, width, delta-bucket) scatter-update key: the call
            # blocks on trace+compile — surface it as window.compile
            # (nested inside the caller's window.h2d_delta span)
            from kepler_tpu import telemetry

            with telemetry.span("window.compile"):
                resident = update(resident, rows_dev, idx_dev)
        else:
            resident = update(resident, rows_dev, idx_dev)
        self._buffers[self._buf_i] = resident
        return n_stage


@dataclass
class FusedFlush:
    """One dispatchable fused batch: program + args for a single donated
    ``lax.scan`` call that replays every pending interval's delta rows
    against the resident block and returns all their packed outputs in
    one ``[K, N, W+2, Z]`` f16 array (one device sync per K windows)."""

    program: Callable
    args: tuple  # (params, resident, rows_dev, idx_dev[, model_rows_dev])
    cold: bool  # True → dispatching compiles (time it as window.compile)
    metas: list[WindowMeta]  # pending windows, oldest first (len = k_live)
    k: int  # compiled scan depth (k_live padded with no-op intervals)
    k_live: int  # real windows in this batch
    h2d_rows: int  # delta rows staged across the whole batch
    # False when the ring was rebuilt AFTER this flush was cut (shape
    # change): the donated scan still runs — its carry is the retired
    # old-shape block and is dropped instead of rebound
    rebind: bool = True


class FusedWindowEngine(PackedWindowEngine):
    """Device-resident window LOOP — one host↔device sync per K windows.

    The packed engines above dispatch one program (plus one donated
    scatter-update) per window; at fleet scale the fixed per-dispatch
    host sync dwarfs the ~0.1 ms of attribution math (ROADMAP item 2's
    sync floor). This engine severs that: :meth:`stage` is HOST-ONLY —
    it runs the same delta-sync bookkeeping as the base engine but
    accretes the interval's packed delta rows into a host-side pending
    ring instead of uploading them. Every K-th interval
    (``aggregator.fusedWindowK``) it cuts a :class:`FusedFlush`: one
    donated ``lax.scan`` program (:func:`make_fused_window_program`)
    replays the K delta sets against the device-resident block and
    returns all K packed watts planes in one array, so dispatch, sync,
    and publish fetch each happen once per K windows.

    Staleness: windows 1..K−1 of a batch publish when window K flushes —
    at most K−1 intervals late, the ladder's existing ≤ depth−1
    staleness contract with K as the depth.

    Single resident buffer, no ping-pong: the flush is synchronous (the
    publish fetch drains the scan before the next stage), so a donated
    update never targets a buffer with outstanding readers. Failure
    story: a failed flush abandons the ring wholesale — :meth:`reset`
    drops the pending host ring too, the aggregator demotes one rung and
    republishes the pending windows from its own report snapshots (zero
    gaps), and re-seeds this ring on re-promotion.
    """

    def __init__(self, mesh: Any, backend: str = "einsum",
                 model_mode: str | None = None,
                 node_bucket: int = 8, workload_bucket: int = 256,
                 shrink_after: int = 16, fused_k: int = 4) -> None:
        super().__init__(mesh, backend=backend, model_mode=model_mode,
                         node_bucket=node_bucket,
                         workload_bucket=workload_bucket,
                         shrink_after=shrink_after)
        self.fused_k = max(1, int(fused_k))
        # ONE resident buffer and ONE (vestigial) staging slot: the
        # synchronous flush means donation never races an in-flight
        # reader, so the ping-pong ring collapses — _rebuild sizes the
        # device ring from the slot count
        self._stages = [np.zeros((0, 0), np.float32)]
        self._fused_programs: dict[tuple, list] = {}
        # host-side pending ring, oldest first: (rows [n, width] f32,
        # idx [n] i32, model_idx i32 | None, meta)
        self._pending: list[tuple] = []

    # -- interval staging --------------------------------------------------

    def stage(self, rows: Sequence[RowInput], zone_names: Sequence[str],
              params: Any) -> tuple[WindowMeta, FusedFlush | None]:
        """Account one interval host-side and return ``(meta, flush)``;
        ``flush`` is non-None when the pending ring reached K — or when a
        shape change forced the old-shape batch out early — and the
        caller must dispatch it (then publish ``flush.metas``)."""
        self._window_seq += 1
        zones_t = tuple(zone_names)
        need_w = max((len(r.report.cpu_deltas) for r in rows), default=1)
        prev_nb, prev_wb = self._ladder_n.bucket, self._ladder_w.bucket
        wb = self._ladder_w.fit(need_w)
        nb = self._ladder_n.fit(len(rows))
        if self._buffers and (nb > prev_nb or wb > prev_wb):
            if fault.fire("device.oom_on_grow") is not None:
                raise DeviceWindowError(
                    "oom_on_grow",
                    f"injected OOM growing buckets ({prev_nb}, {prev_wb})"
                    f" → ({nb}, {wb})")
        key = (nb, wb, zones_t)
        flush: FusedFlush | None = None
        if key != self._key or not self._buffers:
            # shape change: the pending windows were staged against the
            # OLD resident shape — cut their flush FIRST (against the old
            # key/buffer), marked no-rebind since the rebuild below
            # retires that buffer's shape. At most one flush per stage()
            # call: with K=1 the ring never holds a window across calls,
            # and with K>1 this interval leaves the fresh ring at
            # occupancy 1 < K.
            if self._pending:
                flush = self._make_flush(params)
                flush.rebind = False
            self._rebuild(rows, nb, wb, zones_t)
            width = self._empty_row.shape[0]
            staged = (np.zeros((0, width), np.float32),
                      np.zeros(0, np.int32))
        else:
            staged = self._stage_delta(rows, zones_t)
        self._buf_served[0] = self._window_seq
        meta = WindowMeta(
            zones=list(zones_t),
            names=[r.name for r in rows],
            rows=dict(self._row_of),
            mode=np.asarray(self._mode, np.int32),
            dt=np.asarray(self._dt, np.float32),
            counts=list(self._counts),
            ids=list(self._ids),
            kinds=list(self._kinds),
            n_live=len(rows),
            n_rows=nb,
        )
        model_idx = None
        if self._sparse:
            model_idx = np.flatnonzero(
                np.asarray(self._mode, np.int32) == MODE_MODEL
            ).astype(np.int32)
        self._pending.append((staged[0], staged[1], model_idx, meta))
        if flush is None and len(self._pending) >= self.fused_k:
            flush = self._make_flush(params)
        return meta, flush

    def _stage_delta(self, rows: Sequence[RowInput],
                     zones_t: tuple[str, ...]) -> tuple[np.ndarray,
                                                        np.ndarray]:
        """HOST-ONLY delta accounting: the base engine's live-set prune /
        join / content-identity bookkeeping, but the changed and cleared
        rows land in a FRESH host array that joins the pending ring — no
        device traffic until the flush replays the whole batch through
        the fused scan. Content identity advances at stage time: each
        interval's delta is computed against the state the PREVIOUS
        pending interval will have written, which is exactly what the
        in-order scan replay produces. (A failed flush never leaks
        staged-but-unapplied identity: :meth:`reset` discards it
        wholesale and the next stage full-rebuilds.)"""
        nb, wb, _ = self._key  # type: ignore[misc]
        live = {r.name for r in rows}
        content = self._content[0]  # single buffer → single identity plane
        for name, i in list(self._row_of.items()):
            if name not in live:
                del self._row_of[name]
                self._names[i] = None
                self._mode[i] = 0
                self._dt[i] = 0.0
                self._counts[i] = 0
                self._ids[i] = []
                self._kinds[i] = None
                self._free.append(i)
        changed: list[tuple[int, RowInput]] = []
        for r in rows:
            i = self._row_of.get(r.name)
            if i is None:
                i = self._free.pop()
                self._row_of[r.name] = i
                self._names[i] = r.name
                # no _DIRTY cross-marking: there are no other buffers
            elif (r.ident is not None and content[i] is not _EMPTY
                    and content[i] is not _DIRTY and content[i] == r.ident):
                continue
            self._mode[i] = r.report.mode
            self._dt[i] = r.report.dt_s
            self._counts[i] = len(r.report.cpu_deltas)
            self._ids[i] = r.report.workload_ids
            self._kinds[i] = r.report.workload_kinds
            content[i] = r.ident
            changed.append((i, r))
        changed_rows = {i for i, _ in changed}
        cleared = [i for i in range(nb)
                   if self._names[i] is None and content[i] is not _EMPTY
                   and i not in changed_rows]
        for i in cleared:
            content[i] = _EMPTY
        n_stage = len(changed) + len(cleared)
        width = self._empty_row.shape[0]
        stage = np.zeros((n_stage, width), np.float32)
        idx = np.empty(n_stage, np.int32)
        if changed:
            from kepler_tpu.parallel.packed import pack_reports_into

            reports = [r.report for _, r in changed]
            zd, zv = align_zone_matrices(
                reports, [r.zone_names for _, r in changed], zones_t)
            pack_reports_into(stage, reports, zd, zv, wb)
            idx[:len(changed)] = [i for i, _ in changed]
        for k, i in enumerate(cleared):
            stage[len(changed) + k] = self._empty_row
            idx[len(changed) + k] = i
        return stage, idx

    # -- flush building / dispatch -----------------------------------------

    def flush(self, params: Any) -> FusedFlush | None:
        """Force-flush the pending ring (drain/shutdown, or the
        aggregator's end-of-batch when reports stop arriving) — None when
        nothing is pending."""
        if not self._pending:
            return None
        return self._make_flush(params)

    def _make_flush(self, params: Any) -> FusedFlush:
        """Cut the pending ring into ONE dispatchable batch: pad each
        interval's delta to a common bucketed width and the batch to the
        compiled K (no-op tail intervals: zero rows, all-pad indices →
        scatter-dropped, their outputs never published), so one compiled
        program per shape key serves every occupancy."""
        nb, wb, zones_t = self._key  # type: ignore[misc]
        z = len(zones_t)
        pending, self._pending = self._pending, []
        k_live = len(pending)
        k = self.fused_k
        # changed+cleared are disjoint subsets of the nb resident rows,
        # so every per-interval delta fits the nb-capped bucket
        need_d = max(1, max(len(idx) for _, idx, _, _ in pending))
        db = min(self._ladder_d.fit(need_d), nb)
        width = self._empty_row.shape[0]
        rows_b = np.zeros((k, db, width), np.float32)
        idx_b = np.full((k, db), nb, np.int32)
        h2d = 0
        for j, (stage, idx, _, _) in enumerate(pending):
            n = len(idx)
            rows_b[j, :n] = stage
            idx_b[j, :n] = idx
            h2d += n
        jax = self._jax
        args_tail: list = []
        mb: int | None = None
        if self._sparse:
            need_m = max(1, max(len(mi) for _, _, mi, _ in pending))
            mb = self._ladder_m.fit(need_m)
            mrows = np.full((k, mb), nb, np.int32)
            for j, (_, _, mi, _) in enumerate(pending):
                mrows[j, :len(mi)] = mi
            args_tail.append(jax.device_put(mrows, self._sh_repl))
        entry = self._fused_program_for(nb, wb, z, mb, k, db)
        program, cold = entry[0], entry[1]
        args = (params, self._buffers[0],
                jax.device_put(rows_b, self._sh_repl),
                jax.device_put(idx_b, self._sh_repl),
                *args_tail)
        if cold:
            self._capture_cost(entry, program, args)
        entry[1] = False
        return FusedFlush(program=program, args=args, cold=cold,
                          metas=[m for _, _, _, m in pending],
                          k=k, k_live=k_live, h2d_rows=h2d)

    def dispatch(self, flush: FusedFlush) -> Any:
        """Run one fused batch → the ``[K, N, W+2, Z]`` f16 outputs. The
        donated scan consumes the resident handle; rebind to the returned
        carry immediately (KTL110) — unless the ring was rebuilt after
        this flush was cut (shape change), in which case the old-shape
        carry is dropped and the rebuilt buffer stays authoritative."""
        fused = flush.program  # keplint: donates=1
        params, resident = flush.args[0], flush.args[1]
        rest = flush.args[2:]
        pair = fused(params, resident, *rest)
        resident = pair[0]
        if flush.rebind:
            self._buffers[0] = resident
        return pair[1]

    def _fused_program_for(self, nb: int, wb: int, z: int,
                           mb: int | None, k: int, db: int) -> list:
        key = (nb, wb, z, self._model_mode or "", mb, k, db)
        entry = self._fused_programs.get(key)
        if entry is None:
            # fired BEFORE the entry caches (same contract as
            # _program_for): a failed compile leaves no poisoned entry
            if fault.fire("device.compile_error") is not None:
                raise DeviceWindowError(
                    "compile_error",
                    f"injected compile failure for fused key {key}")
            from kepler_tpu.parallel.packed import make_fused_window_program

            program = make_fused_window_program(
                self._mesh, n_workloads=wb, n_zones=z,
                model_mode=self._model_mode, backend=self._backend,
                model_bucket=mb)
            entry = [program, True, None, self._fused_label(key)]
            self._fused_programs[key] = entry
            self.compile_count += 1
            while len(self._fused_programs) > self._CACHE_CAP:
                self._fused_programs.pop(next(iter(self._fused_programs)))
        return entry

    def _fused_label(self, key: tuple) -> str:
        nb, wb, z, mode, mb, k, db = key
        label = f"fused_n{nb}_w{wb}_z{z}_{mode or 'ratio'}"
        if mb is not None:
            label += f"_m{mb}"
        return f"{label}_k{k}_d{db}"

    # -- failure recovery / introspection ----------------------------------

    def reset(self) -> None:
        """Abandon the resident block AND the pending host ring: windows
        staged but never flushed are re-published by the aggregator from
        its own report snapshots at the demoted rung (zero gaps), so
        holding their stale deltas here would only risk replaying them
        against a rebuilt block."""
        super().reset()
        self._pending = []

    def pending_occupancy(self) -> int:
        """Windows staged but not yet flushed (0 ≤ · < K)."""
        return len(self._pending)

    def cost_stats(self) -> dict[str, dict]:
        out = super().cost_stats()
        for entry in self._fused_programs.values():
            if entry[2] is not None:
                out[entry[2]["label"]] = entry[2]
        return out

    def introspect(self) -> dict:
        out = super().introspect()
        out["fused"] = {
            "k": self.fused_k,
            "pending": len(self._pending),
            "programs": [{"key": entry[3],
                          "cold": bool(entry[1]), "cost": entry[2]}
                         for entry in self._fused_programs.values()],
        }
        return out


class ShardedWindowEngine(PackedWindowEngine):
    """Packed resident batch SHARDED over the mesh's node axis — the
    production aggregator path for multi-device hosts (ROADMAP item 1:
    10k nodes / 1M pods per aggregator with near-linear device scaling).

    Layout: the global padded batch is ``n_shards × shard_bucket`` rows;
    shard ``k``'s slice lives as its OWN ring of single-device buffers
    committed to device ``k``. Per window:

    * **Sticky node→shard assignment.** A node keeps its shard for life
      (joiners go to the emptiest shard); a join or report change stages
      rows ONLY to the owning shard — the other shards see zero H2D, no
      recompiles, and their resident buffers are untouched. The whole
      fleet is rebalanced (round-robin over sorted names) only when the
      shard bucket itself moves: overflow growth (no shard has a free
      row), hysteretic shrink, or a workload/zone-axis shape change.
    * **Per-shard delta H2D + shard-local scatter.** Each shard's
      changed rows are packed into that shard's host staging slot and
      uploaded to that device alone, then scatter-updated in place
      through a donated single-device program (the same ping-pong /
      rebind discipline as the base engine, per shard; keplint KTL110
      covers the rebind lexically).
    * **One sharded dispatch.** The per-shard buffers are assembled
      zero-copy into one global array (``NamedSharding`` over ``node``)
      and the packed program runs SPMD across the mesh; with a model
      mode set the sparse MODE_MODEL gather stays shard-local
      (``shard_map`` — see ``parallel.packed``). The only cross-shard
      step in the whole window is the caller's result fetch at publish.

    Requires a 1-D mesh over the node axis (every device an independent
    shard); the aggregator falls back to :class:`PackedWindowEngine` for
    single-device and 2-D (node × model) meshes, and demotes to it on
    any shard's device failure (the ladder's single-device rungs).
    """

    _LOCAL_SPARSE = True

    def __init__(self, mesh: Any, backend: str = "einsum",
                 model_mode: str | None = None,
                 node_bucket: int = 8, workload_bucket: int = 256,
                 shrink_after: int = 16, staging_slots: int = 2) -> None:
        from kepler_tpu.parallel.mesh import NODE_AXIS

        n_dev = mesh.devices.size
        if dict(mesh.shape).get(NODE_AXIS, 0) != n_dev or n_dev < 2:
            raise ValueError(
                "ShardedWindowEngine needs a 1-D mesh over the node axis "
                f"with ≥ 2 devices; got shape {dict(mesh.shape)}")
        super().__init__(mesh, backend=backend, model_mode=model_mode,
                         node_bucket=node_bucket,
                         workload_bucket=workload_bucket,
                         shrink_after=shrink_after,
                         staging_slots=staging_slots)
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.n_shards = n_dev
        self._devices = list(mesh.devices.flat)
        # shards THIS engine stages/uploads to: every shard on the
        # single-process engine; the multi-host subclass narrows it to
        # the shards committed to this process's local devices (remote
        # shards' buffers stay None — never packed, never uploaded)
        self._owned_shards: list[int] = list(range(n_dev))
        # the node ladder sizes the PER-SHARD bucket here (global rows =
        # n_shards × bucket, evenly shardable by construction)
        self._ladder_n = BucketLadder(max(1, node_bucket // n_dev),
                                      shrink_after)
        # per-shard delta-staging ladders: shard 3's churn burst must not
        # inflate shard 0's staging shape (and recompile its update)
        self._ladder_ds = [BucketLadder(8, shrink_after)
                           for _ in range(n_dev)]
        self._sh_rows = NamedSharding(mesh, P(NODE_AXIS))
        self._n_slots = max(2, staging_slots)
        # slot-major ring: _buffers[slot][shard] (len(_buffers) stays the
        # ring depth, as on the base engine); _content mirrors it with
        # per-shard per-row identity, _stages with host staging arrays
        self._buffers = []  # type: ignore[assignment]
        self._content = []  # type: ignore[assignment]
        self._stages = []  # type: ignore[assignment]
        self._shard_of: dict[str, int] = {}
        self._free_by_shard: list[list[int]] = [[] for _ in range(n_dev)]
        self._width = 0

    # -- failure recovery --------------------------------------------------

    def reset(self) -> None:
        """Abandon every shard's ring + staging (see base docstring): a
        single shard's failed dispatch poisons the assembled global view,
        so all shard rings re-seed together on the next plan."""
        super().reset()
        self._shard_of = {}
        self._free_by_shard = [[] for _ in range(self.n_shards)]
        self._width = 0

    # -- per-shard update programs -----------------------------------------

    def _jit_scatter(self, scatter_rows: Callable[..., Any]) -> Any:
        """Shard-local donated scatter: jitted WITHOUT mesh shardings —
        placement follows the committed per-shard operands, so one cache
        entry serves every shard (jax re-specializes per device)."""
        return self._jax.jit(scatter_rows, donate_argnums=(0,))

    # -- introspection -----------------------------------------------------

    def introspect(self) -> dict:
        out = super().introspect()
        out["sticky"] = {
            "assigned": len(self._shard_of),
            "free_rows": [len(f) for f in self._free_by_shard],
        }
        out["buckets"]["delta_shards"] = [lad.bucket
                                          for lad in self._ladder_ds]
        return out

    # -- window planning ---------------------------------------------------

    # -- cross-host agreement hooks (identity on one process) --------------

    def _agree_window_needs(self, need_s: int, need_w: int,
                            zones_t: tuple[str, ...]) -> tuple[int, int]:
        """Agree the per-shard and workload bucket NEEDS across every
        process before fitting the ladders: the SPMD program's shapes
        must match on all hosts or the dispatch deadlocks. One process =
        nothing to agree."""
        return need_s, need_w

    def _agree_model_need(self, need_m: int) -> int:
        """Agree the sparse model-bucket need (same contract)."""
        return need_m

    def plan_window(self, rows: Sequence[RowInput],
                    zone_names: Sequence[str], params: Any) -> WindowPlan:
        self._window_seq += 1
        zones_t = tuple(zone_names)
        z = len(zones_t)
        k_sh = self.n_shards
        need_w = max((len(r.report.cpu_deltas) for r in rows), default=1)
        prev_sb, prev_wb = self._ladder_n.bucket, self._ladder_w.bucket

        overflow = False
        if self._buffers:
            # release departed nodes' rows, then stick joiners to the
            # emptiest shard (deterministic: ties break on shard index)
            live = {r.name for r in rows}
            for name in [n for n in self._shard_of if n not in live]:
                k = self._shard_of.pop(name)
                i = self._row_of.pop(name)
                self._names[i] = None
                self._mode[i] = 0
                self._dt[i] = 0.0
                self._counts[i] = 0
                self._ids[i] = []
                self._kinds[i] = None
                self._free_by_shard[k].append(i - k * prev_sb)
            headroom = [len(f) for f in self._free_by_shard]
            joiners = sorted((r for r in rows
                              if r.name not in self._shard_of),
                             key=lambda r: r.name)
            model_load: list[int] | None = None
            if joiners and any(r.report.mode == MODE_MODEL
                               for r in joiners):
                # per-shard MODE_MODEL occupancy, so model joiners land
                # on the estimator-lightest shard (the sparse bucket is
                # sized by the fullest shard — see _rebuild_shards)
                model_load = [0] * k_sh
                for name, q in self._shard_of.items():
                    i = self._row_of.get(name)
                    if i is not None and self._mode[i] == MODE_MODEL:
                        model_load[q] += 1
            for r in joiners:
                open_shards = [q for q in range(k_sh) if headroom[q] > 0]
                if not open_shards:
                    overflow = True  # no shard has a free row: rebalance
                    break
                if r.report.mode == MODE_MODEL:
                    k = min(open_shards,
                            key=lambda q: (model_load[q], -headroom[q], q))
                else:
                    k = max(open_shards, key=lambda q: (headroom[q], -q))
                headroom[k] -= 1
                if model_load is not None and r.report.mode == MODE_MODEL:
                    model_load[k] += 1
                self._shard_of[r.name] = k
        if overflow or not self._buffers:
            # ceil over the shards THIS process stages (rebalanced
            # occupancy; every shard on the single-process engine)
            need_s = -(-len(rows) // max(1, len(self._owned_shards)))
        else:
            occupancy = [0] * k_sh
            for k in self._shard_of.values():
                occupancy[k] += 1
            need_s = max(1, max(occupancy, default=1))
        need_s, need_w = self._agree_window_needs(need_s, need_w, zones_t)
        wb = self._ladder_w.fit(need_w)
        sb = self._ladder_n.fit(need_s)
        if self._buffers and (sb > prev_sb or wb > prev_wb):
            if fault.fire("device.oom_on_grow") is not None:
                raise DeviceWindowError(
                    "oom_on_grow",
                    f"injected OOM growing shard buckets ({prev_sb}, "
                    f"{prev_wb}) → ({sb}, {wb})")
        key = (sb, wb, zones_t)
        if key != self._key or not self._buffers or overflow:
            h2d_shards = self._rebuild_shards(rows, sb, wb, zones_t)
        else:
            self._buf_i = (self._buf_i + 1) % len(self._buffers)
            h2d_shards = self._delta_sync_shards(rows, zones_t)
        self._buf_served[self._buf_i] = self._window_seq
        nb = k_sh * sb
        meta = self._build_meta(rows, zones_t, sb)
        resident = self._assemble_resident(nb)
        args: tuple
        mb: int | None = None
        if self._sparse:
            mode_arr = np.asarray(self._mode, np.int32)
            local_rows = [np.flatnonzero(
                mode_arr[k * sb:(k + 1) * sb] == MODE_MODEL)
                for k in range(k_sh)]
            mb = self._ladder_m.fit(self._agree_model_need(
                max(1, max(len(lk) for lk in local_rows))))
            # shard-local indices, one mb-sized segment per shard; pad sb
            # is past the shard's rows → gather-clamped, scatter-dropped
            idx = np.full(k_sh * mb, sb, np.int32)
            for k, lk in enumerate(local_rows):
                idx[k * mb:k * mb + len(lk)] = lk
            args = (params, resident, self._put_model_rows(idx, mb))
        else:
            args = (params, resident)
        entry = self._program_for(nb, wb, z, mb)
        program, cold = entry[0], entry[1]
        if cold:
            self._capture_cost(entry, program, args)
        entry[1] = False
        return WindowPlan(program=program, args=args, cold=cold, meta=meta,
                          h2d_rows=sum(h2d_shards),
                          h2d_shards=tuple(h2d_shards),
                          n_shards=k_sh, fetch=self._fetch_plane)

    def _build_meta(self, rows: Sequence[RowInput],
                    zones_t: tuple[str, ...], sb: int) -> WindowMeta:
        """Per-window row-layout snapshot. Row indices are GLOBAL here;
        the multi-host subclass re-indexes into the LOCAL result plane
        (the only rows its publish fetch materializes)."""
        return WindowMeta(
            zones=list(zones_t),
            names=[r.name for r in rows],
            rows=dict(self._row_of),
            mode=np.asarray(self._mode, np.int32),
            dt=np.asarray(self._dt, np.float32),
            counts=list(self._counts),
            ids=list(self._ids),
            kinds=list(self._kinds),
            n_live=len(rows),
            n_rows=self.n_shards * sb,
        )

    def _assemble_resident(self, nb: int) -> Any:
        """Zero-copy global view over the per-shard device buffers
        (every buffer is already committed to its shard's device; the
        multi-host subclass passes only its ADDRESSABLE shards plus the
        global sharding — jax's multi-controller assembly contract)."""
        jax = self._jax
        arrays = [b for b in self._buffers[self._buf_i] if b is not None]
        return jax.make_array_from_single_device_arrays(
            (nb, self._width), self._sh_batch, arrays)

    def _put_model_rows(self, idx: np.ndarray, mb: int) -> Any:
        """Commit the shard-local sparse index vector onto the mesh."""
        return self._jax.device_put(idx, self._sh_rows)

    def _fetch_plane(self, out: Any) -> np.ndarray:
        """Publish fetch: materialize the dispatched output per ADDRESSABLE
        shard (each shard's D2H was already queued by
        ``copy_to_host_async``, so the per-shard ``np.asarray`` calls
        drain transfers that ran concurrently) and concatenate in global
        row order — never one monolithic device fetch of the assembled
        array. The multi-host subclass additionally narrows this to the
        shards it OWNS, so publish cost scales with owned rows, not
        fleet size."""
        shards = getattr(out, "addressable_shards", None)
        if not shards or len(shards) <= 1:
            return np.asarray(out)
        parts = sorted(shards, key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in parts], axis=0)

    # -- resident maintenance ----------------------------------------------

    def _rebuild_shards(self, rows: Sequence[RowInput], sb: int, wb: int,
                 zones_t: tuple[str, ...]) -> list[int]:
        """Full re-pack + REBALANCE: deal MODE_MODEL nodes first, then
        ratio nodes, round-robin over shards — per-shard occupancy stays
        within one row of even AND so does the per-shard estimator load
        (the sparse model bucket is sized by the FULLEST shard's model
        rows, so clustering model nodes on a shard subset would multiply
        the whole mesh's estimator FLOPs by the imbalance). Only bucket/
        zone moves land here — a steady fleet never migrates a node."""
        from kepler_tpu.parallel.packed import (PackedLayout,
                                                pack_fleet_inputs)

        jax = self._jax
        k_sh = self.n_shards
        z = len(zones_t)
        layout = PackedLayout(wb, z)
        width = layout.width
        by_name = sorted(rows, key=lambda r: r.name)
        ordered = ([r for r in by_name if r.report.mode == MODE_MODEL]
                   + [r for r in by_name if r.report.mode != MODE_MODEL])
        self._shard_of = {}
        self._row_of = {}
        self._names = [None] * (k_sh * sb)
        self._mode = [0] * (k_sh * sb)
        self._dt = [0.0] * (k_sh * sb)
        self._counts = [0] * (k_sh * sb)
        self._ids = [[] for _ in range(k_sh * sb)]
        self._kinds = [None] * (k_sh * sb)
        # deal members round-robin over the shards THIS process stages
        # (all of them single-process; the local subset multi-host)
        owned = list(self._owned_shards)
        pos_of = {k: pos for pos, k in enumerate(owned)}
        shard_packed: list[np.ndarray | None] = []
        shard_idents: list[list] = []
        h2d_shards: list[int] = []
        for k in range(k_sh):
            if k not in pos_of:
                # a remote host's shard: never packed, never uploaded —
                # its process stages it from its own report store
                shard_packed.append(None)
                shard_idents.append([_EMPTY] * sb)
                self._free_by_shard[k] = []
                h2d_shards.append(0)
                continue
            members = ordered[pos_of[k]::len(owned)]
            n_real = len(members)
            if n_real:
                reports = [r.report for r in members]
                zd, zv = align_zone_matrices(
                    reports, [r.zone_names for r in members], zones_t)
                batch = assemble_fleet_batch(
                    reports, n_zones=z, node_bucket=sb,
                    workload_bucket=wb, zone_deltas_mat=zd,
                    zone_valid_mat=zv)
                packed = pack_fleet_inputs(batch)
                if packed.shape != (sb, width):
                    raise AssertionError(
                        f"shard {k} packed shape {packed.shape} != "
                        f"({sb}, {width})")
                base = k * sb
                self._mode[base:base + sb] = batch.mode.tolist()
                self._dt[base:base + sb] = batch.dt_s.tolist()
                self._counts[base:base + sb] = list(batch.workload_counts)
                self._ids[base:base + sb] = list(batch.workload_ids)
                self._kinds[base:base + n_real] = [r.workload_kinds
                                                   for r in reports]
                for j, r in enumerate(members):
                    self._shard_of[r.name] = k
                    self._row_of[r.name] = base + j
                    self._names[base + j] = r.name
            else:
                packed = np.tile(layout.empty_row(), (sb, 1))
            shard_packed.append(packed)
            shard_idents.append([r.ident for r in members]
                                + [_EMPTY] * (sb - n_real))
            self._free_by_shard[k] = list(range(sb - 1, n_real - 1, -1))
            h2d_shards.append(n_real)
        self._buffers = [
            [(jax.device_put(shard_packed[k], self._devices[k])
              if shard_packed[k] is not None else None)
             for k in range(k_sh)]
            for _ in range(self._n_slots)]
        self._content = [[list(shard_idents[k]) for k in range(k_sh)]
                         for _ in range(self._n_slots)]
        self._stages = [[np.zeros((0, width), np.float32)
                         for _ in range(k_sh)]
                        for _ in range(self._n_slots)]
        self._buf_i = 0
        self._buf_served = [self._window_seq] * self._n_slots
        self._stage_i = 0
        self._key = (sb, wb, zones_t)
        self._width = width
        self._empty_row = layout.empty_row()
        return h2d_shards

    def _delta_sync_shards(self, rows: Sequence[RowInput],
                           zones_t: tuple[str, ...]) -> list[int]:
        """Per-shard delta: stage each shard's changed/joined/cleared
        rows into ITS host slot, upload to ITS device alone, donated
        shard-local scatter. Shards with nothing changed are untouched
        — no H2D, no dispatch, no staging writes."""
        from kepler_tpu import telemetry
        from kepler_tpu.parallel.packed import pack_reports_into

        sb, wb, _ = self._key  # type: ignore[misc]
        jax = self._jax
        k_sh = self.n_shards
        width = self._width
        content_slot = self._content[self._buf_i]
        changed_by: list[list[tuple[int, RowInput]]] = [
            [] for _ in range(k_sh)]
        for r in rows:
            k = self._shard_of[r.name]
            content = content_slot[k]
            i = self._row_of.get(r.name)
            if i is None:
                local = self._free_by_shard[k].pop()
                i = k * sb + local
                self._row_of[r.name] = i
                self._names[i] = r.name
                # other ring slots may still hold another node's data in
                # this row — restage on their next turn
                for slot, slot_content in enumerate(self._content):
                    if slot != self._buf_i:
                        slot_content[k][local] = _DIRTY
            else:
                local = i - k * sb
                if (r.ident is not None and content[local] is not _EMPTY
                        and content[local] is not _DIRTY
                        and content[local] == r.ident):
                    continue  # this shard's slot row is current
            self._mode[i] = r.report.mode
            self._dt[i] = r.report.dt_s
            self._counts[i] = len(r.report.cpu_deltas)
            self._ids[i] = r.report.workload_ids
            self._kinds[i] = r.report.workload_kinds
            content[local] = r.ident
            changed_by[k].append((local, r))
        h2d_shards = [0] * k_sh
        self._stage_i = (self._stage_i + 1) % len(self._stages)
        stage_slot = self._stages[self._stage_i]
        # only owned shards can hold rows (the sticky map never assigns a
        # node to a shard this process doesn't stage), so remote shards
        # are untouched by construction: zero H2D, zero staging writes
        for k in self._owned_shards:
            content = content_slot[k]
            changed = changed_by[k]
            changed_locals = {local for local, _ in changed}
            base = k * sb
            cleared = [local for local in range(sb)
                       if self._names[base + local] is None
                       and content[local] is not _EMPTY
                       and local not in changed_locals]
            for local in cleared:
                content[local] = _EMPTY
            n_stage = len(changed) + len(cleared)
            h2d_shards[k] = n_stage
            if n_stage == 0:
                continue
            # the span NAME keeps the shard id (trace readability); the
            # histogram observes one shared per-shard stage — stage-label
            # cardinality stays independent of mesh size (the outer
            # window.h2d_delta span in the aggregator keeps measuring the
            # whole-window staging total)
            with telemetry.span(f"window.h2d_delta.s{k}",
                                stage="window.h2d_delta.shard"):
                db = min(self._ladder_ds[k].fit(n_stage), sb)
                if stage_slot[k].shape != (db, width):
                    stage_slot[k] = np.zeros((db, width), np.float32)
                stage = stage_slot[k]
                idx = np.full(db, sb, np.int32)
                if changed:
                    reports = [r.report for _, r in changed]
                    zd, zv = align_zone_matrices(
                        reports, [r.zone_names for _, r in changed],
                        zones_t)
                    pack_reports_into(stage, reports, zd, zv, wb)
                    idx[:len(changed)] = [local for local, _ in changed]
                for j, local in enumerate(cleared):
                    stage[len(changed) + j] = self._empty_row
                    idx[len(changed) + j] = local
                dev = self._devices[k]
                entry = self._update_for(sb, width, db)
                update = entry[0]  # keplint: donates=0
                update_cold, entry[1] = entry[1], False
                rows_dev = jax.device_put(stage, dev)
                idx_dev = jax.device_put(idx, dev)
                # the donated handle dies inside the call; rebind and
                # store back immediately (KTL110 tracks `resident`)
                resident = self._buffers[self._buf_i][k]
                if update_cold:
                    self._capture_cost(entry, update,
                                       (resident, rows_dev, idx_dev))
                    with telemetry.span("window.compile"):
                        resident = update(resident, rows_dev, idx_dev)
                else:
                    resident = update(resident, rows_dev, idx_dev)
                self._buffers[self._buf_i][k] = resident
        return h2d_shards


class HostLocalFabric:
    """In-process stand-in for the cross-host mesh fabric.

    N virtual hosts run their :class:`MultiHostWindowEngine` on N
    threads; the fabric provides the two cross-host exchanges a real
    ``jax.distributed`` mesh performs over DCN:

    * :meth:`agree` — elementwise max over small int vectors (the
      bucket-need agreement that keeps every host compiling the same
      SPMD shapes);
    * :meth:`exchange` — merge per-shard single-device buffers for
      global assembly (in ONE process every device is addressable, so
      the simulated hosts hand each other the arrays a real
      multi-controller runtime already sees locally).

    :meth:`kill` breaks the fabric: every in-flight and future
    rendezvous raises ``DeviceWindowError("host_dead")`` on the
    survivors — the same failure surface a dead host's collective
    produces — which the aggregator's ladder turns into the
    "mesh minus one host" demotion. Used by tests,
    ``make multihost``'s virtual leg, and the bench multihost row;
    production multi-host runs with no fabric (``fabric=None``) and
    gets agreement from ``jax.experimental.multihost_utils`` instead.

    ``parties`` names the LIVE party ids explicitly (default
    ``range(n_parties)``). The elastic-membership plane uses it to
    stand up a fabric incarnation over a SURVIVOR subset whose ids
    keep their original process indices — e.g. ``parties=[1, 2]``
    after host 0 of a 3-host mesh died — so the survivors' rebuilt
    multi-host engines rendezvous among themselves without relabeling.
    A rejoin builds a fresh full-set incarnation (sequence numbers
    start aligned at zero on every party, matching the freshly rebuilt
    engines).
    """

    def __init__(self, n_parties: int | None = None,
                 timeout: float = 60.0,
                 parties: "Sequence[int] | None" = None) -> None:
        if parties is None:
            if n_parties is None or n_parties < 1:
                raise ValueError("fabric needs at least one party")
            parties = range(int(n_parties))
        ids = sorted({int(p) for p in parties})
        if not ids or any(p < 0 for p in ids):
            raise ValueError(
                f"fabric party ids must be non-negative, got {ids!r}")
        if n_parties is not None and len(ids) != int(n_parties):
            raise ValueError(
                f"n_parties={n_parties} but {len(ids)} party ids "
                f"given: {ids!r}")
        self._parties = tuple(ids)
        self._n = len(ids)
        self._timeout = float(timeout)
        self._lock = threading.Lock()
        self._barrier = threading.Barrier(self._n)
        self._dead = False
        self._seq = {p: 0 for p in ids}
        self._slots: dict = {}

    @property
    def n_parties(self) -> int:
        return self._n

    @property
    def parties(self) -> tuple:
        """The live party ids this incarnation rendezvouses over."""
        return self._parties

    def kill(self) -> None:
        """Simulate a host death: break every rendezvous, now and
        forever — survivors see ``DeviceWindowError("host_dead")``."""
        self._dead = True
        self._barrier.abort()

    def _rendezvous(self, party: int, name: str, value: Any) -> list:
        if self._dead:
            raise DeviceWindowError(
                "host_dead", "mesh fabric is down (peer host died)")
        if party not in self._seq:
            raise DeviceWindowError(
                "host_dead",
                f"party {party} is not in this fabric incarnation "
                f"(live parties {list(self._parties)})")
        key = (name, self._seq[party])
        self._seq[party] += 1
        with self._lock:
            entry = self._slots.setdefault(key,
                                           {"values": [], "reads": 0})
            entry["values"].append(value)
        try:
            self._barrier.wait(timeout=self._timeout)
        except threading.BrokenBarrierError:
            raise DeviceWindowError(
                "host_dead", f"mesh peer lost at {name} rendezvous")
        with self._lock:
            entry = self._slots[key]
            values = list(entry["values"])
            entry["reads"] += 1
            if entry["reads"] >= self._n:
                del self._slots[key]
        if len(values) != self._n:
            # parties rendezvoused on DIFFERENT call sites: their plan
            # paths diverged (a bug the SPMD contract cannot survive)
            raise DeviceWindowError(
                "mesh_desync",
                f"{len(values)}/{self._n} parties met at {name}")
        return values

    def agree(self, party: int, name: str, vec: np.ndarray) -> np.ndarray:
        return np.maximum.reduce(self._rendezvous(party, name,
                                                  np.asarray(vec)))

    def exchange(self, party: int, name: str,
                 mapping: dict) -> dict:
        merged: dict = {}
        for m in self._rendezvous(party, name, dict(mapping)):
            merged.update(m)
        return merged


class MultiHostWindowEngine(ShardedWindowEngine):
    """The multi-host tier of the sharded window (ISSUE 15): ONE logical
    aggregator whose packed resident batch spans every host's devices,
    with everything except the SPMD dispatch kept strictly HOST-LOCAL.

    The mesh is global (``initialize_multihost()`` + ``make_mesh()``
    span all processes' devices — ICI within a host, DCN/Gloo across);
    this engine narrows ``_owned_shards`` to the shards committed to
    THIS process's local devices, so the inherited machinery stages,
    packs, and donated-scatter-updates only local rings:

    * **Host-local staging + delta H2D.** Joins/changes/drops touch only
      local shards (the sticky map never assigns a node to a remote
      shard); remote shards' buffers are ``None`` — never packed, never
      uploaded, never read. Zero cross-host bytes on the ingest path.
    * **Assembly by contract, not transfer.**
      ``make_array_from_single_device_arrays`` over the LOCAL shards
      plus the global ``NamedSharding`` builds the global array view —
      jax's multi-controller assembly contract; no host ever sees
      another host's packed rows.
    * **Bucket agreement.** Before fitting the ladders, the per-shard /
      workload / model bucket NEEDS (and a zone-axis hash) are agreed
      across hosts with one tiny allgather-max — the SPMD program
      shapes must match everywhere or dispatch deadlocks. A zone-axis
      mismatch raises ``mesh_desync`` instead of wedging.
    * **Owned-rows publish fetch.** The publish fetch materializes only
      the ADDRESSABLE (owned) shards of the result plane, and the
      window meta is re-indexed into that local plane: each host
      publishes exactly the nodes it ingested (which
      ``fleet.ring.ring_from_mesh`` makes exactly the nodes whose rows
      live here). The only cross-host traffic in a window is the SPMD
      dispatch itself.

    ``fabric`` (a :class:`HostLocalFabric`) replaces the DCN exchanges
    for in-process simulation — tests, ``make multihost``'s virtual
    leg, bench. Production passes no fabric and agreement rides
    ``jax.experimental.multihost_utils.process_allgather``.
    """

    def __init__(self, mesh: Any, backend: str = "einsum",
                 model_mode: str | None = None,
                 node_bucket: int = 8, workload_bucket: int = 256,
                 shrink_after: int = 16, staging_slots: int = 2,
                 process_index: int | None = None,
                 device_process: Callable[[Any], int] | None = None,
                 fabric: HostLocalFabric | None = None) -> None:
        super().__init__(mesh, backend=backend, model_mode=model_mode,
                         node_bucket=node_bucket,
                         workload_bucket=workload_bucket,
                         shrink_after=shrink_after,
                         staging_slots=staging_slots)
        if device_process is None:
            def device_process(d: Any) -> int:
                return int(getattr(d, "process_index", 0))
        if process_index is None:
            process_index = int(self._jax.process_index())
        self._party = int(process_index)
        procs = [int(device_process(d)) for d in self._devices]
        self._shard_processes = procs
        self._owned_shards = [k for k, p in enumerate(procs)
                              if p == self._party]
        if not self._owned_shards:
            raise ValueError(
                f"process {self._party} owns no devices of the mesh "
                f"(shard processes {procs})")
        self._owned_devices = {self._devices[k]
                               for k in self._owned_shards}
        self._host_count = len(set(procs))
        self._fabric = fabric

    # -- cross-host agreement ----------------------------------------------

    def _agree_vec(self, name: str, vec: np.ndarray) -> np.ndarray:
        if self._fabric is not None:
            return self._fabric.agree(self._party, name, vec)
        if self._host_count <= 1:
            return vec
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(vec))
        return gathered.max(axis=0)

    def _agree_window_needs(self, need_s: int, need_w: int,
                            zones_t: tuple[str, ...]) -> tuple[int, int]:
        import hashlib

        zh = int.from_bytes(
            hashlib.blake2b(repr(zones_t).encode(),
                            digest_size=4).digest(), "big")
        # max(zh) and -max(-zh) = min(zh): equal iff every host packed
        # the same canonical zone axis (string sets can't ride the
        # allgather, their hash can)
        out = self._agree_vec(
            "window_needs", np.asarray([need_s, need_w, zh, -zh],
                                       np.int64))
        if int(out[2]) != zh or int(-out[3]) != zh:
            raise DeviceWindowError(
                "mesh_desync",
                "hosts disagree on the canonical zone axis")
        return int(out[0]), int(out[1])

    def _agree_model_need(self, need_m: int) -> int:
        out = self._agree_vec("model_need",
                              np.asarray([need_m], np.int64))
        return int(out[0])

    # -- host-local assembly / fetch ---------------------------------------

    def _exchange(self, name: str, local: dict) -> dict:
        if self._fabric is not None:
            return self._fabric.exchange(self._party, name, local)
        return local

    def _assemble_resident(self, nb: int) -> Any:
        jax = self._jax
        bufs = self._buffers[self._buf_i]
        local = {k: bufs[k] for k in self._owned_shards}
        arrays_map = self._exchange("resident", local)
        return jax.make_array_from_single_device_arrays(
            (nb, self._width), self._sh_batch,
            [arrays_map[k] for k in sorted(arrays_map)])

    def _put_model_rows(self, idx: np.ndarray, mb: int) -> Any:
        jax = self._jax
        local = {
            k: jax.device_put(np.ascontiguousarray(
                idx[k * mb:(k + 1) * mb]), self._devices[k])
            for k in self._owned_shards}
        arrays_map = self._exchange("model_rows", local)
        return jax.make_array_from_single_device_arrays(
            (self.n_shards * mb,), self._sh_rows,
            [arrays_map[k] for k in sorted(arrays_map)])

    def _build_meta(self, rows: Sequence[RowInput],
                    zones_t: tuple[str, ...], sb: int) -> WindowMeta:
        """LOCAL-plane meta: row indices point into the concatenation of
        the OWNED shards' result rows (what :meth:`_fetch_plane`
        materializes) — this host publishes exactly the nodes it
        ingested, never a remote host's rows."""
        owned = self._owned_shards
        pos_of = {k: pos for pos, k in enumerate(owned)}

        def seg(xs: list) -> list:
            return [x for k in owned for x in xs[k * sb:(k + 1) * sb]]

        local_rows = {}
        for name, i in self._row_of.items():
            k, local = divmod(i, sb)
            local_rows[name] = pos_of[k] * sb + local
        return WindowMeta(
            zones=list(zones_t),
            names=[r.name for r in rows],
            rows=local_rows,
            mode=np.asarray(seg(self._mode), np.int32),
            dt=np.asarray(seg(self._dt), np.float32),
            counts=seg(self._counts),
            ids=seg(self._ids),
            kinds=seg(self._kinds),
            n_live=len(rows),
            n_rows=len(owned) * sb,
        )

    def _fetch_plane(self, out: Any) -> np.ndarray:
        """Fetch ONLY the owned shards' result rows (the addressable
        subset a real multi-controller runtime exposes anyway; the
        in-process simulation filters explicitly) — publish cost scales
        with owned rows, not fleet size."""
        shards = getattr(out, "addressable_shards", None)
        if not shards:
            return np.asarray(out)
        parts = [s for s in shards if s.device in self._owned_devices]
        parts.sort(key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in parts],
                              axis=0)

    # -- introspection -----------------------------------------------------

    def introspect(self) -> dict:
        out = super().introspect()
        out["multihost"] = {
            "hosts": self._host_count,
            "process": self._party,
            "owned_shards": list(self._owned_shards),
            "simulated_fabric": self._fabric is not None,
        }
        return out
