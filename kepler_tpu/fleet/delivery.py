"""Pure delivery-plane decision layer: seq dedup, watermark seeding,
keyframe/delta choice, spool-cursor math.

PR 16 surfaced three ordering bugs that only specific event schedules
expose, and every one of them lived in a transition tangled into an
I/O path (`fleet/aggregator.py` ingest, `fleet/agent.py` send,
`fleet/spool.py` ack). Following the shape `fleet/membership.py`
proved — decisions as pure functions of explicit state, wiring kept in
the I/O modules — this module holds the delivery plane's transition
rules so the kepmc protocol model checker
(:mod:`kepler_tpu.analysis.protocol`) can drive the SAME functions
production runs, exhaustively, over every interleaving of a small
fleet. No sockets, no locks, no clocks, no file handles.

Every function (and mutating method) here that writes protocol state —
seq watermarks, dedup windows, ack cursors — is marked ``# keplint:
protocol-transition``; the KTL133 rule enforces that such writes happen
nowhere else in ``kepler_tpu/fleet/``.
"""

from __future__ import annotations

import collections
from typing import Sequence

__all__ = [
    "SeqTracker",
    "delta_base_matches",
    "keyframe_wanted",
    "plan_ack_cursor",
    "plan_rewind_tail",
    "reseed_on_ownership_return",
    "seed_fresh_tracker",
]


class SeqTracker:
    """Per-(node, run) sequence accounting: a bounded window of recently
    seen seqs (dedup — spool replays are idempotent) plus gap detection
    (a seq jump is LOST windows, surfaced as a per-node counter instead
    of silence). The aggregator holds its store lock around every call.
    """

    __slots__ = ("run", "max_seen", "seen", "order", "window", "touched",
                 "ring_epoch")

    # keplint: protocol-transition — birth state of the dedup window
    def __init__(self, run: str, window: int) -> None:
        self.run = run
        self.max_seen = 0
        self.seen: set[int] = set()
        self.order: collections.deque[int] = collections.deque()
        self.window = max(1, window)
        self.touched = 0.0  # aggregator clock; drives cap eviction
        self.ring_epoch = 0  # ring epoch at last observe (ownership-return)

    # keplint: protocol-transition
    def observe(self, seq: int) -> tuple[bool, int]:
        """→ (is_duplicate, windows_lost_by_this_arrival).

        A seq inside the dedup window that was already seen — or one so
        old it fell out of the window — is a duplicate (at-least-once
        redelivery): ack-worthy but not ingestable. A seq jumping past
        ``max_seen + 1`` reports the skipped windows as lost; a late
        out-of-order FILL of a previously-counted gap is ingested but
        cannot retroactively decrement the loss counter (counters only
        go up; ordered spool replay makes real fills rare).

        Accounting is CONSERVATIVE: loss = windows this tracker never
        saw. A fresh aggregator meeting a mid-run stream (aggregator
        restart) counts the pre-restart windows as a one-time spike —
        indistinguishable, from seq alone, from an agent whose first
        windows died before delivery, and the latter must be counted."""
        if seq in self.seen:
            return True, 0
        if seq <= self.max_seen - self.window:
            return True, 0  # beyond the window: can't tell — stay idempotent
        self.seen.add(seq)
        self.order.append(seq)
        while len(self.order) > self.window:
            self.seen.discard(self.order.popleft())
        lost = 0
        if seq > self.max_seen + 1:
            # seq numbers start at 1 within a run: a first-seen seq of N
            # means windows 1..N-1 died before delivery (ring overflow,
            # spool eviction, disk failure)
            lost = seq - self.max_seen - 1
        self.max_seen = max(self.max_seen, seq)
        return False, lost


# keplint: protocol-transition — the hand-off / restart seeding rule
def seed_fresh_tracker(tracker: SeqTracker, acked_through: int,
                       seq: int) -> None:
    """Seed a FRESH tracker's watermark from the agent's delivered
    watermark: the agent asserts every seq ≤ ``acked_through`` got a
    2xx from SOME replica — delivered to a previous owner (or a
    previous incarnation of this one), not lost. ``min()`` clamps a
    stale or hostile watermark to this report's own leading gap, so an
    agent can only vouch for (or hide) its OWN stream."""
    if acked_through > 0 and seq > 0:
        tracker.max_seen = min(acked_through, seq - 1)


# keplint: protocol-transition — the PR 16 ownership-return re-seed
def reseed_on_ownership_return(tracker: SeqTracker, ring_epoch: int,
                               acked_through: int, seq: int) -> None:
    """Ownership RETURN (elastic membership): this replica owned the
    node under an earlier epoch, lost it to a join/scale-up, and got
    it back on a leave/succession. Its tracker slept through the away
    period, but the agent's watermark vouches those windows were 2xx'd
    by the interim owner — delivered, not lost. Gated on an actual
    epoch advance and ``min()``-clamped exactly like fresh-tracker
    seeding, so with membership at rest an inflated watermark still
    hides nothing."""
    if ring_epoch > tracker.ring_epoch and acked_through > tracker.max_seen:
        tracker.max_seen = max(tracker.max_seen,
                               min(acked_through, seq - 1))
    tracker.ring_epoch = ring_epoch


def keyframe_wanted(*, needs_keyframe: bool, delivery_path: str,
                    has_base: bool, run_matches: bool,
                    since_keyframe: int, keyframe_every: int) -> bool:
    """Should the next v2 send ship FULL (keyframe) instead of delta?

    Yes when the server asked (409 needs-keyframe), when the window is
    a replay (a hand-off's new owner has no base state; the spool
    holds keyframes), when no acked base exists or it belongs to
    another run, or when the keyframe cadence is due. The checker pins
    the convergence property this predicate carries: after a 409 the
    next send is ALWAYS a keyframe, so a needs-keyframe loop cannot
    outlive one round-trip."""
    return (needs_keyframe or delivery_path != "fresh" or not has_base
            or not run_matches
            or since_keyframe + 1 >= keyframe_every)


def delta_base_matches(base_run: str, base_seq: int, run: str,
                       wanted_base_seq: int) -> bool:
    """Does a stored base row satisfy a delta frame's (run, base_seq)
    reference? A mismatch — hand-off, eviction, run change — is the
    structured 409 needs-keyframe answer, never a guess."""
    return base_run == run and base_seq == wanted_base_seq


def plan_ack_cursor(cursor: tuple[int, int], record: tuple[int, int],
                    record_end: int, cursor_segment_end: int,
                    next_segment: int | None) -> tuple[int, int] | None:
    """Validate one spool ack against the CURRENT cursor → the new
    ``(segment, offset)`` cursor, or None when the ack must be a no-op.

    ``record`` is the acked record's ``(segment, offset)`` position and
    ``record_end`` the offset just past its frame. An ack is honored
    when the record sits exactly at the cursor — or at the ONE hop
    batched acks legitimately produce: the cursor parked at a sealed
    segment's end (``cursor_segment_end``) while the record is the
    FIRST frame of the next segment (``next_segment``). Anything else
    means the cursor moved underneath the caller (cap eviction, a
    concurrent re-peek) and advancing would silently skip a record
    that was never sent."""
    if record == cursor:
        return record[0], record_end
    _seg, off = cursor
    if (off >= cursor_segment_end and next_segment is not None
            and record[0] == next_segment and record[1] == 0):
        return record[0], record_end
    return None


def plan_rewind_tail(starts: Sequence[int], cursor_offset: int,
                     max_records: int) -> tuple[int, ...]:
    """The already-acked record start offsets (current segment only)
    a rewind re-delivers: the last ``max_records`` frames strictly
    before the cursor. Bounded by segment retention — fully-acked
    sealed segments are deleted at ack time, so a rewind can never
    reach past the cursor segment's first frame, and never re-delivers
    a record the cursor has not concluded."""
    if max_records <= 0 or cursor_offset <= 0:
        return ()
    tail = [s for s in starts if s < cursor_offset]
    return tuple(tail[-max_records:])
