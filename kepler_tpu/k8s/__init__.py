"""Kubernetes integration (reference ``internal/k8s/``)."""
