"""Pod metadata informer.

Reference parity: ``internal/k8s/pod/pod.go`` — a cached, node-filtered view
of the K8s API: pods are watched with a ``spec.nodeName=<this node>`` field
selector (:139-144), indexed by every containerID including init and
ephemeral containers (:155-196, container IDs stripped of their
``scheme://`` prefix :198), giving O(1)
``lookup_by_container_id → (pod_id, pod_name, namespace, container_name)``.

Implementation: a dependency-free Kubernetes REST client (stdlib urllib +
ssl) — the runtime image carries no ``kubernetes`` package. LIST seeds the
cache; WATCH (chunked JSON stream with resourceVersion resume) keeps it warm;
a periodic full re-list guards against missed events. Credentials come from
an explicit kubeconfig path or the in-cluster service-account token.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import ssl
import tempfile
import threading
import urllib.error
import urllib.request
from typing import Mapping

import yaml

from kepler_tpu.service.lifecycle import CancelContext

log = logging.getLogger("kepler.k8s.pod")

_IN_CLUSTER_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"
_IN_CLUSTER_CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"


def _strip_scheme(container_id: str) -> str:
    """``containerd://abc…`` → ``abc…`` (reference extractContainerID :198)."""
    _, sep, rest = container_id.partition("://")
    return rest if sep else container_id


class KubeClient:
    """Minimal authenticated GET against the API server."""

    def __init__(self, kubeconfig: str = "") -> None:
        self.base_url = ""
        self._token = ""
        self._ssl_ctx: ssl.SSLContext | None = None
        if kubeconfig:
            self._from_kubeconfig(kubeconfig)
        else:
            self._from_in_cluster()

    def _from_kubeconfig(self, path: str) -> None:
        with open(path, encoding="utf-8") as f:
            cfg = yaml.safe_load(f)
        ctx_name = cfg.get("current-context", "")
        contexts = {c["name"]: c["context"] for c in cfg.get("contexts", [])}
        clusters = {c["name"]: c["cluster"] for c in cfg.get("clusters", [])}
        users = {u["name"]: u["user"] for u in cfg.get("users", [])}
        ctx = contexts.get(ctx_name) or next(iter(contexts.values()), None)
        if ctx is None:
            raise ValueError(f"kubeconfig {path} has no usable context")
        cluster = clusters[ctx["cluster"]]
        user = users.get(ctx.get("user", ""), {})
        self.base_url = cluster["server"].rstrip("/")
        self._ssl_ctx = self._build_ssl(cluster, user)
        if "token" in user:
            self._token = user["token"]

    def _build_ssl(self, cluster: Mapping, user: Mapping) -> ssl.SSLContext:
        ctx = ssl.create_default_context()
        if cluster.get("insecure-skip-tls-verify"):
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        ca_data = cluster.get("certificate-authority-data")
        ca_file = cluster.get("certificate-authority")
        if ca_data:
            ctx.load_verify_locations(
                cadata=base64.b64decode(ca_data).decode())
        elif ca_file:
            ctx.load_verify_locations(cafile=ca_file)
        cert_data = user.get("client-certificate-data")
        key_data = user.get("client-key-data")
        if cert_data and key_data:
            # stdlib ssl needs files for client certs
            cert_f = tempfile.NamedTemporaryFile(
                mode="wb", suffix=".pem", delete=False)
            cert_f.write(base64.b64decode(cert_data))
            cert_f.write(b"\n")
            cert_f.write(base64.b64decode(key_data))
            cert_f.close()
            ctx.load_cert_chain(cert_f.name)
        elif user.get("client-certificate") and user.get("client-key"):
            ctx.load_cert_chain(user["client-certificate"],
                                user["client-key"])
        return ctx

    def _from_in_cluster(self) -> None:
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host or not os.path.exists(_IN_CLUSTER_TOKEN):
            raise RuntimeError(
                "not running in a cluster and no kubeconfig provided")
        self.base_url = f"https://{host}:{port}"
        with open(_IN_CLUSTER_TOKEN, encoding="ascii") as f:
            self._token = f.read().strip()
        ctx = ssl.create_default_context()
        if os.path.exists(_IN_CLUSTER_CA):
            ctx.load_verify_locations(cafile=_IN_CLUSTER_CA)
        self._ssl_ctx = ctx

    def get(self, path: str, timeout: float = 30.0):
        """GET returning a file-like response (caller reads/streams)."""
        req = urllib.request.Request(self.base_url + path)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        return urllib.request.urlopen(
            req, timeout=timeout, context=self._ssl_ctx)


class PodInformer:
    """Node-filtered pod cache with containerID index."""

    def __init__(
        self,
        node_name: str,
        kubeconfig: str = "",
        resync_interval: float = 300.0,
        client: KubeClient | None = None,
        backoff_base: float = 1.0,
        backoff_cap: float = 30.0,
        rng=None,
    ) -> None:
        import random

        self._node_name = node_name
        self._kubeconfig = kubeconfig
        self._resync = resync_interval
        self._client = client
        # jittered exponential backoff for consecutive watch failures
        # (controller-runtime reflector analog; jitter keeps a fleet of
        # node agents from hitting a flapping API server in lockstep)
        self._backoff_base = backoff_base
        self._backoff_cap = (min(backoff_cap, resync_interval)
                             if resync_interval > 0 else backoff_cap)
        self._rng = rng or random.Random()
        self._made_progress = False
        self._lock = threading.Lock()
        # containerID → (pod_id, pod_name, namespace, container_name)
        self._index: dict[str, tuple[str, str, str, str]] = {}
        # pod uid → set of containerIDs (for delete handling)
        self._pod_containers: dict[str, set[str]] = {}
        self._resource_version = ""

    def name(self) -> str:
        return "pod-informer"

    # -- lifecycle ---------------------------------------------------------

    def init(self) -> None:
        if self._client is None:
            self._client = KubeClient(self._kubeconfig)
        self.relist()
        log.info("pod informer primed: %d containers on node %s",
                 len(self._index), self._node_name)

    def run(self, ctx: CancelContext) -> None:
        """Watch + periodic re-list (controller-runtime cache analog).

        A watch ``ERROR`` event (e.g. 410 Gone after an API-server restart
        compacts our resourceVersion) triggers an *immediate* re-list rather
        than waiting out the stream timeout — the recovery controller-runtime
        performs for the reference (``internal/k8s/pod/pod.go:136-196``).
        Only the FIRST consecutive failure gets the fast path; repeated
        failures (the server rejecting watch after watch, or the re-list
        itself failing) wait out a *jittered exponential backoff*
        (base·2^k capped, ×[0.5, 1.5) jitter) so a flapping API server is
        not hit in lockstep by every node agent — the reflector's backoff
        analog. Any successfully-applied watch event resets the streak.
        """
        failures = 0
        while not ctx.cancelled():
            expired = False
            failed = False
            self._made_progress = False
            try:
                expired = self._watch(ctx)
            except Exception as err:
                failed = True
                log.warning("pod watch interrupted: %s", err)
            if ctx.cancelled():
                return
            if self._made_progress:
                failures = 0  # the stream was healthy before it ended
            if expired and failures == 0:
                try:
                    self.relist()
                    failures = 1  # a second rejection backs off
                    continue  # fresh resourceVersion: re-watch right away
                except Exception as err:
                    failed = True
                    log.warning("pod re-list after ERROR failed: %s", err)
            if expired or failed:
                failures += 1
                delay = self._watch_backoff(failures)
                log.warning("pod watch failing (streak=%d); backing off "
                            "%.2fs", failures, delay)
            else:
                # clean close (even with zero events on a quiet node) is
                # healthy: isolated errors hours apart must not accumulate
                # into a "consecutive" streak
                failures = 0
                delay = min(5.0, self._resync)
            if ctx.wait(delay):
                return
            try:
                self.relist()
            except Exception as err:
                failures += 1
                log.warning("pod re-list failed: %s", err)

    def _watch_backoff(self, failures: int) -> float:
        """Jittered exponential delay for the k-th consecutive failure.
        The exponent is clamped — a multi-hour outage must saturate at the
        cap, not overflow float exponentiation (2.0**1024 raises)."""
        base = min(self._backoff_base * (2.0 ** min(failures - 1, 30)),
                   self._backoff_cap)
        return base * (0.5 + self._rng.random())

    # -- cache maintenance -------------------------------------------------

    def _pods_path(self, watch: bool = False) -> str:
        sel = f"spec.nodeName%3D{self._node_name}"
        path = f"/api/v1/pods?fieldSelector={sel}"
        if watch:
            path += (f"&watch=true&resourceVersion={self._resource_version}"
                     "&allowWatchBookmarks=true")
        return path

    def relist(self) -> None:
        assert self._client is not None
        with self._client.get(self._pods_path()) as resp:
            data = json.load(resp)
        with self._lock:
            self._index.clear()
            self._pod_containers.clear()
            for pod in data.get("items", []):
                self._upsert_locked(pod)
            self._resource_version = data.get("metadata", {}).get(
                "resourceVersion", "")

    def _watch(self, ctx: CancelContext) -> bool:
        """Consume one watch stream. Returns True when the stream must be
        abandoned because the server declared our resourceVersion stale
        (ERROR event, typically 410 Gone)."""
        assert self._client is not None
        with self._client.get(self._pods_path(watch=True),
                              timeout=60.0) as resp:
            buf = b""
            while not ctx.cancelled():
                chunk = resp.readline()
                if not chunk:
                    return False  # stream closed; caller re-lists
                buf += chunk
                if not buf.endswith(b"\n"):
                    continue
                try:
                    event = json.loads(buf)
                except json.JSONDecodeError:
                    continue  # partial frame
                finally:
                    buf = b""
                if self._apply_event(event):
                    return True
        return False

    def _apply_event(self, event: Mapping) -> bool:
        """Fold one watch event into the cache. Returns True when the watch
        is expired and the caller must re-list (reference relies on
        controller-runtime's reflector for this, ``pod.go:136-144``)."""
        kind = event.get("type")
        pod = event.get("object", {})
        if kind == "ERROR":
            # object is a v1.Status; 410 Gone means our resourceVersion was
            # compacted away. Drop it so the next LIST starts fresh.
            log.warning(
                "pod watch ERROR (code=%s reason=%s): re-listing",
                pod.get("code"), pod.get("reason"))
            with self._lock:
                self._resource_version = ""
            return True
        rv = pod.get("metadata", {}).get("resourceVersion")
        if kind in ("ADDED", "MODIFIED", "DELETED"):
            # only real object events count as progress — a BOOKMARK
            # applies nothing, and a server that serves bookmark-then-410
            # every cycle must still escalate the backoff, not reset it
            self._made_progress = True
        with self._lock:
            if rv:
                self._resource_version = rv
            if kind == "BOOKMARK":
                pass  # resourceVersion checkpoint only; no cache change
            elif kind in ("ADDED", "MODIFIED"):
                self._remove_locked(pod)
                self._upsert_locked(pod)
            elif kind == "DELETED":
                self._remove_locked(pod)
        return False

    def _upsert_locked(self, pod: Mapping) -> None:
        meta = pod.get("metadata", {})
        uid = meta.get("uid", "")
        pod_name = meta.get("name", "")
        namespace = meta.get("namespace", "")
        status = pod.get("status", {})
        ids: set[str] = set()
        # regular + init + ephemeral containers (reference indexerFunc
        # :167-196)
        for key in ("containerStatuses", "initContainerStatuses",
                    "ephemeralContainerStatuses"):
            for cs in status.get(key, []) or []:
                cid = _strip_scheme(cs.get("containerID", "") or "")
                if not cid:
                    continue
                ids.add(cid)
                self._index[cid] = (uid, pod_name, namespace,
                                    cs.get("name", ""))
        if ids:
            self._pod_containers[uid] = ids

    def _remove_locked(self, pod: Mapping) -> None:
        uid = pod.get("metadata", {}).get("uid", "")
        for cid in self._pod_containers.pop(uid, ()):
            self._index.pop(cid, None)

    # -- query API ---------------------------------------------------------

    def lookup_by_container_id(
        self, container_id: str
    ) -> tuple[str, str, str, str] | None:
        """O(1) containerID → pod metadata (reference LookupByContainerID
        :209-239)."""
        with self._lock:
            return self._index.get(_strip_scheme(container_id))
