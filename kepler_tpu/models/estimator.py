"""Estimator registry: pluggable power backends behind one interface.

The reference's monitor hard-codes ratio attribution; BASELINE.json's north
star puts ratio + learned models behind one switchable backend
(``power.estimator``). An estimator maps a feature window to per-workload
watts [W, Z]; the ratio backend additionally needs zone deltas.

Modes (BASELINE configs):
  "ratio"    — RAPL proportional attribution (configs 1-2)
  "linear"   — linear regression from features  (config 3)
  "mlp"      — MLP from features                (config 4)
  "temporal" — causal attention over feature HISTORY windows
               (features carry an extra trailing time axis [.., W, T, F];
               see kepler_tpu.models.temporal / kepler_tpu.monitor.history)
  "moe"      — mixture of per-node-type experts (expert-parallel capable;
               see kepler_tpu.models.moe)
Mixed fleets evaluate ratio and model in the same device program and select
per node (config 5; see ``kepler_tpu.parallel.aggregator``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp

from kepler_tpu.models.deep import init_deep, predict_deep
from kepler_tpu.models.features import build_features
from kepler_tpu.models.linear import init_linear, predict_linear
from kepler_tpu.models.mlp import init_mlp, predict_mlp
from kepler_tpu.models.moe import init_moe, predict_moe
from kepler_tpu.models.temporal import init_temporal, predict_temporal

RATIO = "ratio"
LINEAR = "linear"
MLP = "mlp"
TEMPORAL = "temporal"
MOE = "moe"
DEEP = "deep"

# registry contract: a predictor is callable as (params, features[.., W, F],
# workload_valid[.., W]) → watts — single-tick features. TEMPORAL is NOT
# here: it consumes [.., W, T, F] history windows and must be served via
# predict_temporal / parallel.make_temporal_program + monitor.HistoryBuffer.
_PREDICTORS: dict[str, Callable] = {
    LINEAR: predict_linear,
    MLP: predict_mlp,
    MOE: predict_moe,
    DEEP: predict_deep,
}

_INITIALIZERS: dict[str, Callable] = {
    LINEAR: init_linear,
    MLP: init_mlp,
    TEMPORAL: init_temporal,
    MOE: init_moe,
    DEEP: init_deep,
}


def initializer(mode: str) -> Callable:
    if mode == RATIO:
        raise ValueError(
            "ratio attribution has no learned parameters; only "
            f"{', '.join(_INITIALIZERS)} need initialization")
    if mode not in _INITIALIZERS:
        raise ValueError(f"unknown estimator mode {mode!r}; "
                         f"valid: {RATIO}, {', '.join(_INITIALIZERS)}")
    return _INITIALIZERS[mode]


def predictor(mode: str) -> Callable | None:
    """→ predict fn for a learned mode; None for RATIO (no model to run)."""
    if mode == RATIO:
        return None
    if mode == TEMPORAL:
        raise ValueError(
            "the temporal estimator needs [.., W, T, F] history windows, "
            "not single-tick features — serve it via "
            "models.temporal.predict_temporal (or "
            "parallel.make_temporal_program) fed by monitor.HistoryBuffer")
    if mode not in _PREDICTORS:
        raise ValueError(f"unknown estimator mode {mode!r}; "
                         f"valid: {RATIO}, {', '.join(_PREDICTORS)}")
    return _PREDICTORS[mode]


@dataclass
class ModelEstimator:
    """A trained model + its mode, usable wherever ratio attribution is."""

    mode: str
    params: Any

    @classmethod
    def create(cls, mode: str, n_zones: int, seed: int = 0,
               **kwargs) -> "ModelEstimator":
        key = jax.random.PRNGKey(seed)
        return cls(mode=mode,
                   params=initializer(mode)(key, n_zones, **kwargs))

    def predict_watts(
        self,
        cpu_deltas: jax.Array,
        workload_valid: jax.Array,
        node_cpu_delta: jax.Array,
        usage_ratio: jax.Array,
        dt_s: jax.Array,
    ) -> jax.Array:
        """Features → watts [..., W, Z] (µW = watts * 1e6 handled by caller)."""
        feats = build_features(cpu_deltas, workload_valid, node_cpu_delta,
                               usage_ratio, dt_s)
        return predictor(self.mode)(self.params, feats, workload_valid)


def save_params(path: str, params: Any) -> None:
    """Persist params as .npz — the train→serve handoff for the fleet
    aggregator. One level of nesting (DeepParams' ``blocks``) flattens to
    "outer/inner" keys. No pickle: arrays only, loadable on any host."""
    import numpy as np

    flat = {}
    for k, v in params.items():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                flat[f"{k}/{k2}"] = np.asarray(v2)
        else:
            flat[k] = np.asarray(v)
    np.savez(path, **flat)


def load_params(path: str) -> dict:
    """Load params saved by :func:`save_params`, rebuilding "outer/inner"
    keys into nested dicts (allow_pickle stays off — checkpoint files may
    come from untrusted storage)."""
    import numpy as np

    out: dict = {}
    with np.load(path, allow_pickle=False) as data:
        for k in data.files:
            arr = jnp.asarray(data[k])
            if "/" in k:
                outer, inner = k.split("/", 1)
                out.setdefault(outer, {})[inner] = arr
            else:
                out[k] = arr
    return out
