"""Training for the learned power models.

The models train against RAPL-ratio ground truth: on RAPL-capable nodes the
ratio attribution gives per-workload watts "labels"; the estimator learns to
reproduce them from features alone, then serves nodes without RAPL
(the kepler-model-server train/serve split, BASELINE.json configs 3-4).

``train_step`` is a pure jitted function (loss = masked MSE in watts);
the distributed variant in ``kepler_tpu.parallel.trainer`` shards batch
over the data axis and the MLP hidden dim over the model axis.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

Params = Any  # LinearParams | MLPParams pytree


class TrainState(NamedTuple):
    params: Params
    opt_state: optax.OptState
    step: jax.Array


def masked_mse(
    pred_watts: jax.Array,  # [..., W, Z]
    target_watts: jax.Array,  # [..., W, Z]
    workload_valid: jax.Array,  # bool [..., W]
    label_valid: jax.Array | None = None,  # bool [..., W, Z] per-zone mask
) -> jax.Array:
    """``label_valid`` excludes zones a node never reported: the aggregator
    writes 0 W there (absence, not a measurement), and counting those rows
    as labels would drag predictions for that zone toward zero."""
    err = (pred_watts - target_watts) ** 2
    mask = workload_valid[..., None].astype(err.dtype)
    if label_valid is not None:
        mask = mask * label_valid.astype(err.dtype)
    total = jnp.sum(err * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count


def masked_relative_mse(
    pred_watts: jax.Array,  # [..., W, Z]
    target_watts: jax.Array,  # [..., W, Z]
    workload_valid: jax.Array,  # bool [..., W]
    label_valid: jax.Array | None = None,  # bool [..., W, Z]
    floor_watts: float = 0.1,
) -> jax.Array:
    """MSE of (pred−target)/max(|target|, floor) — optimizes the metric the
    north star is stated in (percent of ground truth), so the tail of SMALL
    workloads converges instead of being drowned by the big ones plain MSE
    favors. ``floor_watts`` keeps near-zero labels from exploding the
    scale (below it, errors count absolutely in floor units)."""
    scale = jnp.maximum(jnp.abs(target_watts), floor_watts)
    err = ((pred_watts - target_watts) / scale) ** 2
    mask = workload_valid[..., None].astype(err.dtype)
    if label_valid is not None:
        mask = mask * label_valid.astype(err.dtype)
    total = jnp.sum(err * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count


def warm_start_wide(params: Params, features: jax.Array,
                    workload_valid: jax.Array, target_watts: jax.Array,
                    label_valid: jax.Array | None = None) -> Params:
    """Residual-fitting warm start for a wide-and-deep family: solve the
    wide path (``w_skip``) in closed form against the labels, so gradient
    training starts from the exact linear optimum and the trunk learns only
    the nonlinear correction. Works for any params dict with a ``w_skip
    [F, Z]`` leaf (mlp / temporal / deep — temporal callers pass the
    current-tick features)."""
    from kepler_tpu.models.linear import fit_linear_exact

    sol = fit_linear_exact(features, workload_valid, target_watts,
                           label_valid)
    return {**params, "w_skip": sol["weight"]}


def warm_start_moe(params: Params, features: jax.Array,
                   workload_valid: jax.Array, target_watts: jax.Array,
                   expert_id: jax.Array) -> Params:
    """Per-expert closed-form warm start of the MoE's ``w_skip [E, F, Z]``:
    each expert solves against only the rows routed to it (its node type's
    linear power curve)."""
    from kepler_tpu.models.linear import fit_linear_exact

    n_experts = int(params["w0"].shape[0])
    sols = []
    for e in range(n_experts):
        mask = workload_valid & jnp.expand_dims(expert_id == e, -1)
        sols.append(fit_linear_exact(features, mask, target_watts)["weight"])
    return {**params, "w_skip": jnp.stack(sols)}


def make_optimizer(learning_rate: float = 1e-3,
                   weight_decay: float = 1e-4) -> optax.GradientTransformation:
    return optax.adamw(learning_rate, weight_decay=weight_decay)


def create_train_state(params: Params,
                       optimizer: optax.GradientTransformation) -> TrainState:
    return TrainState(params=params, opt_state=optimizer.init(params),
                      step=jnp.zeros((), jnp.int32))


def make_train_step(
    predict_fn: Callable[..., jax.Array],
    optimizer: optax.GradientTransformation,
) -> Callable:
    """Build a jitted SGD step: (state, features, valid, targets) → state, loss.

    ``predict_fn`` must accept ``clamp=`` — the loss runs on UNclamped
    outputs so the serve-time non-negativity floor can't zero the gradients.
    """
    train_predict = functools.partial(predict_fn, clamp=False)

    @jax.jit
    def train_step(
        state: TrainState,
        features: jax.Array,  # [B, F] or [N, W, F]
        workload_valid: jax.Array,
        target_watts: jax.Array,
        label_valid: jax.Array | None = None,  # bool [..., W, Z]
    ) -> tuple[TrainState, jax.Array]:
        def loss_fn(params):
            pred = train_predict(params, features, workload_valid)
            return masked_mse(pred, target_watts, workload_valid,
                              label_valid)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return train_step


def temporal_step_fn(
    optimizer: optax.GradientTransformation,
    compute_dtype=None,
    attention_fn: Callable | None = None,
    remat: bool = False,
) -> Callable:
    """UNJITTED temporal train-step body — the single definition the local
    (:func:`make_temporal_train_step`) and sequence-parallel
    (``parallel.sequence.make_sequence_parallel_train_step``) variants jit
    with their own shardings.

    ``attention_fn`` is the trunk's plug-in seam (None = dense causal;
    the SP variant passes the shard-mapped ring kernel). ``remat`` wraps
    the forward in ``jax.checkpoint`` (recompute activations in backward —
    the FLOPs-for-memory trade for long windows).
    """
    import jax.numpy as jnp

    from kepler_tpu.models.temporal import predict_temporal

    cd = jnp.bfloat16 if compute_dtype is None else compute_dtype

    def forward(params, feat_hist, workload_valid, t_valid):
        return predict_temporal(params, feat_hist, workload_valid, t_valid,
                                clamp=False, compute_dtype=cd,
                                attention_fn=attention_fn)

    if remat:
        forward = jax.checkpoint(forward)

    def train_step(state, feat_hist, workload_valid, t_valid, target_watts,
                   label_valid=None):
        def loss_fn(params):
            pred = forward(params, feat_hist, workload_valid, t_valid)
            return masked_mse(pred, target_watts, workload_valid,
                              label_valid)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return train_step


def make_temporal_train_step(
    optimizer: optax.GradientTransformation,
    compute_dtype=None,
) -> Callable:
    """Train step for the TEMPORAL estimator (history-window inputs).

    (state, feat_hist [.., W, T, F], workload_valid [.., W],
    t_valid [.., W, T], target_watts [.., W, Z]) → (state, loss).
    Targets are the current tick's RAPL-ratio watts — the model learns to
    reproduce them from the trajectory (same labels as the single-tick
    models, richer conditioning).
    """
    return jax.jit(temporal_step_fn(optimizer, compute_dtype))


def fit(
    predict_fn: Callable,
    params: Params,
    features: jax.Array,
    workload_valid: jax.Array,
    target_watts: jax.Array,
    steps: int = 200,
    learning_rate: float = 1e-2,
) -> tuple[Params, float]:
    """Small full-batch fit loop (host-driven; used by tests/benchmarks)."""
    optimizer = make_optimizer(learning_rate)
    state = create_train_state(params, optimizer)
    step_fn = make_train_step(predict_fn, optimizer)
    loss = jnp.inf
    for _ in range(steps):
        state, loss = step_fn(state, features, workload_valid, target_watts)
    return state.params, float(loss)
