"""Shared NN building blocks for the estimator families.

One definition of weight init and layer norm so the families (mlp, moe,
temporal, deep) can't drift apart on fan conventions or epsilons.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

LN_EPS = 1e-6


def glorot(key: jax.Array, shape: tuple[int, ...]) -> jax.Array:
    """Glorot-normal over the LAST two dims (leading dims = stacked experts
    or stages, which share the per-matrix fan)."""
    scale = jnp.sqrt(2.0 / (shape[-2] + shape[-1]))
    return jax.random.normal(key, shape, jnp.float32) * scale


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array) -> jax.Array:
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + LN_EPS) * scale + bias
