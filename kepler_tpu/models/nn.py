"""Shared NN building blocks for the estimator families.

One definition of weight init and layer norm so the families (mlp, moe,
temporal, deep) can't drift apart on fan conventions or epsilons.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

LN_EPS = 1e-6


def glorot(key: jax.Array, shape: tuple[int, ...]) -> jax.Array:
    """Glorot-normal over the LAST two dims (leading dims = stacked experts
    or stages, which share the per-matrix fan)."""
    scale = jnp.sqrt(2.0 / (shape[-2] + shape[-1]))
    return jax.random.normal(key, shape, jnp.float32) * scale


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array) -> jax.Array:
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + LN_EPS) * scale + bias


def acc_matmul(a: jax.Array, b: jax.Array,
               compute_dtype: jnp.dtype) -> jax.Array:
    """Half-operand, f32-accumulator matmul.

    Operands cast to ``compute_dtype`` (bf16 on TPU → MXU throughput);
    the accumulator is pinned f32 via ``preferred_element_type``, so
    half precision flows through dot OPERANDS only and never through an
    accumulation — the invariant kepljax KTL120 (dtype-flow) enforces
    across every registered device program. A bare ``x16 @ w16`` rounds
    every partial sum to bf16 (~3 decimal digits), which is how trunk
    error quietly ate the 0.5%-of-RAPL budget before this seam existed.
    """
    return jnp.matmul(a.astype(compute_dtype), b.astype(compute_dtype),
                      preferred_element_type=jnp.float32)
