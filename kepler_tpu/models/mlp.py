"""MLP power estimator.

BASELINE.json config 4: "kepler-model-server MLP estimator (perf-counter
feature set, VM/non-RAPL node)".

Architecture: ``F → H → H → Z`` with GELU, matching the scale of
kepler-model-server's small regressors but shaped for the MXU: hidden dims
default to 128 (lane-width multiples), activations compute in bfloat16 with
float32 params and output (TPU-friendly mixed precision), and the whole
forward is a pair of matmuls XLA fuses with the surrounding attribution
program.

The hidden dimension is the tensor-parallel axis in the sharded trainer
(`kepler_tpu.parallel`): layer-0 weights shard column-wise, layer-1
row-wise, so the only collective is one psum on the output projection.
"""

from __future__ import annotations

from typing import TypedDict

import jax
import jax.numpy as jnp

from kepler_tpu.models.features import NUM_FEATURES
from kepler_tpu.models.nn import acc_matmul, glorot


class MLPParams(TypedDict):
    w0: jax.Array  # [F, H]
    b0: jax.Array  # [H]
    w1: jax.Array  # [H, H]
    b1: jax.Array  # [H]
    w2: jax.Array  # [H, Z]
    b2: jax.Array  # [Z]
    w_skip: jax.Array  # [F, Z] wide path (direct linear features → watts)


def init_mlp(
    key: jax.Array,
    n_zones: int,
    hidden: int = 128,
    n_features: int = NUM_FEATURES,
) -> MLPParams:
    k0, k1, k2 = jax.random.split(key, 3)
    return MLPParams(
        w0=glorot(k0, (n_features, hidden)),
        b0=jnp.zeros((hidden,), jnp.float32),
        w1=glorot(k1, (hidden, hidden)),
        b1=jnp.zeros((hidden,), jnp.float32),
        w2=jnp.zeros((hidden, n_zones), jnp.float32),  # zero-init output
        b2=jnp.zeros((n_zones,), jnp.float32),
        w_skip=jnp.zeros((n_features, n_zones), jnp.float32),
    )


def predict_mlp(
    params: MLPParams,
    features: jax.Array,  # [..., W, F]
    workload_valid: jax.Array,  # bool [..., W]
    clamp: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """→ watts f32 [..., W, Z]; bf16 matmul operands, f32 accumulators.

    Wide-and-deep: the ``w_skip`` path carries the dominant linear
    power-vs-CPU-time signal in full f32 (power models are linear to first
    order — the ratio formula itself is), the GELU trunk learns the
    nonlinear correction. Keeps the estimator within the 0.5% ground-truth
    budget even with a bf16 trunk: the trunk's head can shrink toward zero
    where the relationship is linear, taking its rounding noise with it.

    ``clamp`` as in ``predict_linear``: floor at 0 W for serving only —
    training needs gradients through negative raw outputs.
    """
    cd = compute_dtype
    # half operands, f32 accumulators throughout (KTL120 dtype-flow):
    # gelu/bias arithmetic runs f32, each matmul re-casts its operands
    h = jax.nn.gelu(acc_matmul(features, params["w0"], cd) + params["b0"])
    h = jax.nn.gelu(acc_matmul(h, params["w1"], cd) + params["b1"])
    watts = acc_matmul(h, params["w2"], cd)
    watts = watts + features.astype(jnp.float32) @ params["w_skip"]
    watts = watts + params["b2"]
    if clamp:
        watts = jnp.maximum(watts, 0.0)
    return jnp.where(workload_valid[..., None], watts, 0.0)
