"""Linear-regression power model.

BASELINE.json config 3: "linear-regression power model (no RAPL; cgroup
CPU-time features only)" — the kepler-model-server's simplest estimator.

``watts[W, Z] = relu(features[W, F] @ weight[F, Z] + bias[Z])`` — a single
matmul; batched over nodes it rides the MXU as ``[N*W, F] @ [F, Z]``.
Output is clamped non-negative (power can't be negative) and masked rows
predict zero.
"""

from __future__ import annotations

from typing import TypedDict

import jax
import jax.numpy as jnp

from kepler_tpu.models.features import NUM_FEATURES


class LinearParams(TypedDict):
    weight: jax.Array  # [F, Z]
    bias: jax.Array  # [Z]


def init_linear(
    key: jax.Array, n_zones: int, n_features: int = NUM_FEATURES
) -> LinearParams:
    wkey, _ = jax.random.split(key)
    return LinearParams(
        weight=jax.random.normal(wkey, (n_features, n_zones),
                                 jnp.float32) * 0.01,
        bias=jnp.zeros((n_zones,), jnp.float32),
    )


def predict_linear(
    params: LinearParams,
    features: jax.Array,  # [..., W, F]
    workload_valid: jax.Array,  # bool [..., W]
    clamp: bool = True,
) -> jax.Array:
    """→ watts f32 [..., W, Z].

    ``clamp=True`` (serving) floors predictions at 0 W; training passes
    ``clamp=False`` so gradients flow through negative raw outputs (a hard
    relu at the output dead-locks learning when init predictions are all
    negative).
    """
    watts = features @ params["weight"] + params["bias"]
    if clamp:
        watts = jnp.maximum(watts, 0.0)
    return jnp.where(workload_valid[..., None], watts, 0.0)
