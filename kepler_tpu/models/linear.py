"""Linear-regression power model.

BASELINE.json config 3: "linear-regression power model (no RAPL; cgroup
CPU-time features only)" — the kepler-model-server's simplest estimator.

``watts[W, Z] = relu(features[W, F] @ weight[F, Z] + bias[Z])`` — a single
matmul; batched over nodes it rides the MXU as ``[N*W, F] @ [F, Z]``.
Output is clamped non-negative (power can't be negative) and masked rows
predict zero.
"""

from __future__ import annotations

from typing import TypedDict

import jax
import jax.numpy as jnp

from kepler_tpu.models.features import NUM_FEATURES


class LinearParams(TypedDict):
    weight: jax.Array  # [F, Z]
    bias: jax.Array  # [Z]


def init_linear(
    key: jax.Array, n_zones: int, n_features: int = NUM_FEATURES
) -> LinearParams:
    wkey, _ = jax.random.split(key)
    return LinearParams(
        weight=jax.random.normal(wkey, (n_features, n_zones),
                                 jnp.float32) * 0.01,
        bias=jnp.zeros((n_zones,), jnp.float32),
    )


def fit_linear_exact(
    features: jax.Array,  # [..., W, F]
    workload_valid: jax.Array,  # bool [..., W]
    target_watts: jax.Array,  # [..., W, Z]
    label_valid: jax.Array | None = None,  # bool [..., W, Z]
) -> LinearParams:
    """Closed-form masked least squares → exact-optimum LinearParams.

    Linear regression is classically *solved*, not descended (the
    kepler-model-server fits its linear family offline with an exact
    solver); on TPU the solve is one small device program — an SVD-based
    ``lstsq`` on the flattened ``[R, F]`` design matrix, R = all valid
    workload rows. The bias column is feature 5 (constant 1), so the
    learned bias lives inside ``weight`` and ``bias`` stays zero.

    With ``label_valid`` each zone's column solves against only its own
    labelled rows (vmapped per-zone lstsq with that zone's row mask).
    """
    f = features.shape[-1]
    z = target_watts.shape[-1]
    x = features.reshape(-1, f)
    y = target_watts.reshape(-1, z)
    m = workload_valid.reshape(-1).astype(x.dtype)
    if label_valid is None:
        xm = x * m[:, None]
        w, _, _, _ = jnp.linalg.lstsq(xm, y * m[:, None])
    else:
        lm = label_valid.reshape(-1, z).astype(x.dtype) * m[:, None]

        def solve_zone(mz, yz):
            wz, _, _, _ = jnp.linalg.lstsq(x * mz[:, None], yz * mz)
            return wz  # [F]

        w = jax.vmap(solve_zone, in_axes=(1, 1), out_axes=1)(lm, y)
    return LinearParams(weight=w.astype(jnp.float32),
                        bias=jnp.zeros((z,), jnp.float32))


def predict_linear(
    params: LinearParams,
    features: jax.Array,  # [..., W, F]
    workload_valid: jax.Array,  # bool [..., W]
    clamp: bool = True,
) -> jax.Array:
    """→ watts f32 [..., W, Z].

    ``clamp=True`` (serving) floors predictions at 0 W; training passes
    ``clamp=False`` so gradients flow through negative raw outputs (a hard
    relu at the output dead-locks learning when init predictions are all
    negative).
    """
    watts = features @ params["weight"] + params["bias"]
    if clamp:
        watts = jnp.maximum(watts, 0.0)
    return jnp.where(workload_valid[..., None], watts, 0.0)
