"""Deep residual power estimator (the pipeline-parallel model family).

For large heterogeneous fleets a single shallow MLP underfits (the
kepler-model-server ecosystem answers this with per-type models — see
`kepler_tpu.models.moe`; this family instead scales **depth**): a stack of
S identical pre-LN residual GELU blocks between a feature embedding and a
zone head. Identical blocks are deliberate — uniform stages are what a
GPipe-style pipeline wants (`kepler_tpu.parallel.pipeline` shards the
stack's leading S axis over the ``stage`` mesh axis and streams
microbatches through with ppermute).

Dense evaluation below is the single-chip reference the pipelined program
must match exactly (`tests/test_pipeline.py`).
"""

from __future__ import annotations

from typing import TypedDict

import jax
import jax.numpy as jnp

from kepler_tpu.models.features import NUM_FEATURES
from kepler_tpu.models.nn import acc_matmul, glorot, layer_norm


class BlockParams(TypedDict):
    ln_scale: jax.Array  # [S, D]
    ln_bias: jax.Array  # [S, D]
    w0: jax.Array  # [S, D, 4D]
    b0: jax.Array  # [S, 4D]
    w1: jax.Array  # [S, 4D, D]
    b1: jax.Array  # [S, D]


class DeepParams(TypedDict):
    in_proj: jax.Array  # [F, D]
    in_bias: jax.Array  # [D]
    blocks: BlockParams  # leading S axis = pipeline stages
    w_head: jax.Array  # [D, Z]
    b_head: jax.Array  # [Z]
    w_skip: jax.Array  # [F, Z] wide path (features → watts, outside stack)


def init_deep(
    key: jax.Array,
    n_zones: int,
    n_stages: int = 4,
    d_model: int = 128,
    n_features: int = NUM_FEATURES,
) -> DeepParams:
    k_in, k0, k1, _ = jax.random.split(key, 4)
    d4 = 4 * d_model
    return DeepParams(
        in_proj=glorot(k_in, (n_features, d_model)),
        in_bias=jnp.zeros((d_model,), jnp.float32),
        blocks=BlockParams(
            ln_scale=jnp.ones((n_stages, d_model), jnp.float32),
            ln_bias=jnp.zeros((n_stages, d_model), jnp.float32),
            w0=glorot(k0, (n_stages, d_model, d4)),
            b0=jnp.zeros((n_stages, d4), jnp.float32),
            w1=glorot(k1, (n_stages, d4, d_model)),
            b1=jnp.zeros((n_stages, d_model), jnp.float32),
        ),
        w_head=jnp.zeros((d_model, n_zones), jnp.float32),
        b_head=jnp.zeros((n_zones,), jnp.float32),
        w_skip=jnp.zeros((n_features, n_zones), jnp.float32),
    )


def block_fn(block, x: jax.Array,
             compute_dtype: jnp.dtype = jnp.bfloat16) -> jax.Array:
    """One residual block: x [.., D] → [.., D]. ``block`` has NO stage axis —
    this is the uniform stage function the pipeline applies per device."""
    y = layer_norm(x, block["ln_scale"], block["ln_bias"])
    # half operands, f32 accumulators (KTL120 dtype-flow)
    y = jax.nn.gelu(acc_matmul(y, block["w0"], compute_dtype)
                    + block["b0"])
    return x + acc_matmul(y, block["w1"], compute_dtype) + block["b1"]


def embed(params: DeepParams, features: jax.Array,
          compute_dtype: jnp.dtype = jnp.bfloat16) -> jax.Array:
    """[.., F] → [.., D] (runs OUTSIDE the pipeline; it is one tiny matmul)."""
    x = acc_matmul(features, params["in_proj"], compute_dtype)
    return x + params["in_bias"]


def head(params: DeepParams, x: jax.Array, workload_valid: jax.Array,
         clamp: bool = True, features: jax.Array | None = None) -> jax.Array:
    """[.., D] → watts [.., Z] (also outside the pipeline). ``features``
    feeds the wide f32 skip path (see predict_mlp's w_skip note)."""
    watts = x @ params["w_head"] + params["b_head"]
    if features is not None:
        watts = watts + features.astype(jnp.float32) @ params["w_skip"]
    if clamp:
        watts = jnp.maximum(watts, 0.0)
    return jnp.where(workload_valid[..., None], watts, 0.0)


def predict_deep(
    params: DeepParams,
    features: jax.Array,  # f32 [..., W, F]
    workload_valid: jax.Array,  # bool [..., W]
    clamp: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """Dense single-device reference: scan the block stack in order."""
    x = embed(params, features, compute_dtype)

    def body(x, block):
        return block_fn(block, x, compute_dtype), None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    return head(params, x, workload_valid, clamp, features=features)
