"""Learned power models (kepler-model-server capability)."""

from kepler_tpu.models.estimator import (
    LINEAR,
    MLP,
    RATIO,
    ModelEstimator,
    initializer,
    predictor,
)
from kepler_tpu.models.features import NUM_FEATURES, build_features
from kepler_tpu.models.linear import LinearParams, init_linear, predict_linear
from kepler_tpu.models.mlp import MLPParams, init_mlp, predict_mlp
from kepler_tpu.models.train import (
    TrainState,
    create_train_state,
    fit,
    make_optimizer,
    make_train_step,
    masked_mse,
)

__all__ = [
    "LINEAR",
    "LinearParams",
    "MLP",
    "MLPParams",
    "ModelEstimator",
    "NUM_FEATURES",
    "RATIO",
    "TrainState",
    "build_features",
    "create_train_state",
    "fit",
    "init_linear",
    "init_mlp",
    "initializer",
    "make_optimizer",
    "make_train_step",
    "masked_mse",
    "predict_linear",
    "predict_mlp",
    "predictor",
]
