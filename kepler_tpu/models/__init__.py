"""Learned power models (kepler-model-server capability)."""

from kepler_tpu.models.checkpoint import TrainCheckpointer
from kepler_tpu.models.deep import DeepParams, init_deep, predict_deep
from kepler_tpu.models.estimator import (
    LINEAR,
    MLP,
    MOE,
    RATIO,
    TEMPORAL,
    ModelEstimator,
    initializer,
    predictor,
)
from kepler_tpu.models.features import NUM_FEATURES, build_features
from kepler_tpu.models.linear import LinearParams, init_linear, predict_linear
from kepler_tpu.models.mlp import MLPParams, init_mlp, predict_mlp
from kepler_tpu.models.moe import MoEParams, init_moe, predict_moe
from kepler_tpu.models.temporal import (
    TemporalParams,
    init_temporal,
    predict_temporal,
)
from kepler_tpu.models.train import (
    TrainState,
    create_train_state,
    fit,
    make_optimizer,
    make_temporal_train_step,
    make_train_step,
    masked_mse,
)

__all__ = [
    "LINEAR",
    "LinearParams",
    "MLP",
    "MLPParams",
    "MOE",
    "ModelEstimator",
    "MoEParams",
    "NUM_FEATURES",
    "RATIO",
    "TEMPORAL",
    "DeepParams",
    "TemporalParams",
    "TrainCheckpointer",
    "TrainState",
    "build_features",
    "create_train_state",
    "fit",
    "init_deep",
    "init_linear",
    "init_mlp",
    "init_moe",
    "init_temporal",
    "initializer",
    "make_optimizer",
    "make_temporal_train_step",
    "make_train_step",
    "masked_mse",
    "predict_deep",
    "predict_linear",
    "predict_mlp",
    "predict_moe",
    "predict_temporal",
    "predictor",
]
