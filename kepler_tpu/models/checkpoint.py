"""Training checkpoint/resume (orbax-backed).

The reference has NO checkpointing — all its state re-seeds from the
hardware's cumulative RAPL counters on restart (SURVEY §5,
`internal/monitor/monitor.go:326-330`), and this framework keeps that
property for the attribution path. The one place durable state *does*
exist here is estimator training: a long fit on fleet history should
survive preemption (TPU pools get preempted as a matter of course). This
wraps `orbax.checkpoint.CheckpointManager` around the trainer's
``TrainState`` (params + optimizer moments + step), so resume continues
mid-run rather than refitting from scratch.

Serve-time handoff stays `estimator.save_params`/`load_params` (.npz —
arrays only, no pickle); orbax checkpoints are the *training* artifact.
Restore is sharding-aware: pass the abstract state built from your
sharded TrainState and orbax lays shards out directly on device.
"""

from __future__ import annotations

import os

import jax

from kepler_tpu.models.train import TrainState


class TrainCheckpointer:
    """Periodic save / latest-restore for a training run.

    ``directory`` is created on first save; ``max_to_keep`` bounds disk
    (old steps are garbage-collected by orbax).
    """

    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, state: TrainState, force: bool = False) -> bool:
        """Persist ``state`` under its own step number. → saved?"""
        import orbax.checkpoint as ocp

        return self._mgr.save(int(state.step), args=ocp.args.StandardSave(
            state._asdict()), force=force)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore_latest(self, state_like: TrainState) -> TrainState | None:
        """→ the newest checkpoint laid out like ``state_like`` (shapes,
        dtypes, shardings), or None if the directory has none."""
        import orbax.checkpoint as ocp

        step = self._mgr.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct,
                                state_like._asdict())
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract))
        return TrainState(**restored)

    def wait(self) -> None:
        """Block until async saves are durable (call before exiting)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "TrainCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
