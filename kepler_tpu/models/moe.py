"""Mixture-of-experts power estimator (one expert per node type).

The reference ecosystem's kepler-model-server publishes a *different*
trained model per platform (machine spec / CPU family) and each node
downloads its own. A heterogeneous fleet evaluated centrally therefore
needs per-node-type models inside ONE device program — which is exactly a
mixture of experts: expert ``e`` is the power model for node type ``e``,
and routing is either explicit (the aggregator knows each node's type) or
learned from the feature vector (softmax gate) when the type is unknown.

Each expert is a small ``F → H → Z`` GELU MLP; expert weights stack on a
leading ``E`` axis so the whole mixture is three batched einsums on the
MXU. Dense evaluation (every expert on every row, gate-weighted) is the
single-chip serving path; `kepler_tpu.parallel.expert` shards the ``E``
axis over devices and dispatches rows with ``all_to_all`` — real expert
parallelism for many/large experts.
"""

from __future__ import annotations

from typing import TypedDict

import jax
import jax.numpy as jnp

from kepler_tpu.models.features import NUM_FEATURES
from kepler_tpu.models.nn import glorot


class MoEParams(TypedDict):
    gate_w: jax.Array  # [F, E] learned router (used when no explicit type)
    w0: jax.Array  # [E, F, H]
    b0: jax.Array  # [E, H]
    w1: jax.Array  # [E, H, Z]
    b1: jax.Array  # [E, Z]
    w_skip: jax.Array  # [E, F, Z] per-expert wide path (linear watts)


def init_moe(
    key: jax.Array,
    n_zones: int,
    n_experts: int = 8,
    hidden: int = 128,
    n_features: int = NUM_FEATURES,
) -> MoEParams:
    kg, k0, k1 = jax.random.split(key, 3)
    return MoEParams(
        gate_w=glorot(kg, (n_features, n_experts)),
        w0=glorot(k0, (n_experts, n_features, hidden)),
        b0=jnp.zeros((n_experts, hidden), jnp.float32),
        w1=jnp.zeros((n_experts, hidden, n_zones), jnp.float32),  # zero-init
        b1=jnp.zeros((n_experts, n_zones), jnp.float32),
        w_skip=jnp.zeros((n_experts, n_features, n_zones), jnp.float32),
    )


def expert_forward(
    params: MoEParams,
    x: jax.Array,  # [E, C, F] rows already grouped per expert
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """Batched per-expert MLP → f32 [E, C, Z]. Shared by dense and EP paths.

    Wide-and-deep per expert: each node type's dominant linear power curve
    rides the f32 ``w_skip`` einsum (Z is tiny, so it's free); the GELU
    trunk learns the type-specific nonlinearity (see predict_mlp's note).
    """
    cd = compute_dtype
    h = jax.nn.gelu(
        jnp.einsum("ecf,efh->ech", x.astype(cd), params["w0"].astype(cd),
                   preferred_element_type=jnp.float32)
        + params["b0"][:, None, :])
    return (
        jnp.einsum("ech,ehz->ecz", h.astype(cd), params["w1"].astype(cd),
                   preferred_element_type=jnp.float32)
        + jnp.einsum("ecf,efz->ecz", x.astype(jnp.float32),
                     params["w_skip"])
        + params["b1"][:, None, :])


def gate_logits(params: MoEParams, features: jax.Array) -> jax.Array:
    """[..., F] → router logits [..., E] (f32 — routing wants full precision)."""
    return features.astype(jnp.float32) @ params["gate_w"]


def predict_moe(
    params: MoEParams,
    features: jax.Array,  # f32 [..., W, F]
    workload_valid: jax.Array,  # bool [..., W]
    clamp: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    expert_id: jax.Array | None = None,  # int32 [...] explicit node type
) -> jax.Array:
    """Dense MoE → watts f32 [..., W, Z].

    With ``expert_id`` (the aggregator's per-node type column) routing is a
    hard one-hot; otherwise the learned gate soft-mixes experts. Dense =
    every expert runs on every row; the ``E``-fold FLOP cost is fine on one
    chip (experts are tiny) and is what the EP path's output must match.
    """
    lead = features.shape[:-1]
    x = features.reshape(1, -1, features.shape[-1])  # [1, N, F]
    e = params["w0"].shape[0]
    per_expert = expert_forward(
        params, jnp.broadcast_to(x, (e, *x.shape[1:])), compute_dtype)
    if expert_id is not None:
        wl = features.ndim - expert_id.ndim - 1  # workload axes to broadcast
        gates = jax.nn.one_hot(expert_id.reshape(*expert_id.shape,
                                                 *([1] * wl)), e)
        gates = jnp.broadcast_to(gates, (*lead, e))
    else:
        gates = jax.nn.softmax(gate_logits(params, features), axis=-1)
    watts = jnp.einsum("enz,ne->nz", per_expert, gates.reshape(-1, e))
    watts = watts.reshape(*lead, -1)
    if clamp:
        watts = jnp.maximum(watts, 0.0)
    return jnp.where(workload_valid[..., None], watts, 0.0)
