"""Temporal power estimator: causal attention over feature history.

The reference attributes power from the *last* tick's deltas only
(`internal/monitor/process.go:123-145` — a single ratio per window). A
single tick is noisy: procfs sampling jitter and RAPL wraparound leave
per-window spikes that Prometheus rate() can only smooth after the fact.
This estimator instead conditions on a **history window** of the last T
ticks per workload (`kepler_tpu.monitor.history` maintains the window) and
predicts the current-tick watts from the whole trajectory — the learned
analog of a cross-tick smoother, and the subsystem that introduces the
sequence axis (SURVEY §5: "if per-workload feature history windows are
added … a time axis appears").

Architecture (shaped for the MXU — all dims lane-width multiples):

    [.., T, F] → in-proj F→D → +learned positional embedding
               → pre-LN causal self-attention (H heads) + residual
               → pre-LN GELU MLP (D→4D→D) + residual
               → LN → head D→Z on the LAST timestep → watts [.., Z]

Short windows (serving default, T≤128) evaluate dense attention on one
chip; long windows shard T over the ``seq`` mesh axis and run ring
attention (`kepler_tpu.parallel.ring`) — same maths, verified equivalent
in tests/test_ring.py.
"""

from __future__ import annotations

from typing import TypedDict

import jax
import jax.numpy as jnp

from kepler_tpu.models.features import NUM_FEATURES
from kepler_tpu.models.nn import acc_matmul, glorot, layer_norm
from kepler_tpu.ops.attention import full_attention


class TemporalParams(TypedDict):
    in_proj: jax.Array  # [F, D]
    pos_emb: jax.Array  # [T_max, D]
    ln1_scale: jax.Array  # [D]
    ln1_bias: jax.Array  # [D]
    wq: jax.Array  # [D, D]
    wk: jax.Array  # [D, D]
    wv: jax.Array  # [D, D]
    wo: jax.Array  # [D, D]
    ln2_scale: jax.Array  # [D]
    ln2_bias: jax.Array  # [D]
    w_mlp0: jax.Array  # [D, 4D]
    b_mlp0: jax.Array  # [4D]
    w_mlp1: jax.Array  # [4D, D]
    b_mlp1: jax.Array  # [D]
    ln_f_scale: jax.Array  # [D]
    ln_f_bias: jax.Array  # [D]
    w_head: jax.Array  # [D, Z]
    b_head: jax.Array  # [Z]
    w_skip: jax.Array  # [F, Z] wide path from the CURRENT tick's features


N_HEADS = 4


def init_temporal(
    key: jax.Array,
    n_zones: int,
    d_model: int = 128,
    t_max: int = 128,
    n_features: int = NUM_FEATURES,
) -> TemporalParams:
    ks = jax.random.split(key, 8)
    d4 = 4 * d_model
    return TemporalParams(
        in_proj=glorot(ks[0], (n_features, d_model)),
        pos_emb=jax.random.normal(ks[1], (t_max, d_model), jnp.float32) * 0.02,
        ln1_scale=jnp.ones((d_model,), jnp.float32),
        ln1_bias=jnp.zeros((d_model,), jnp.float32),
        wq=glorot(ks[2], (d_model, d_model)),
        wk=glorot(ks[3], (d_model, d_model)),
        wv=glorot(ks[4], (d_model, d_model)),
        wo=glorot(ks[5], (d_model, d_model)),
        ln2_scale=jnp.ones((d_model,), jnp.float32),
        ln2_bias=jnp.zeros((d_model,), jnp.float32),
        w_mlp0=glorot(ks[6], (d_model, d4)),
        b_mlp0=jnp.zeros((d4,), jnp.float32),
        w_mlp1=glorot(ks[7], (d4, d_model)),
        b_mlp1=jnp.zeros((d_model,), jnp.float32),
        ln_f_scale=jnp.ones((d_model,), jnp.float32),
        ln_f_bias=jnp.zeros((d_model,), jnp.float32),
        w_head=jnp.zeros((d_model, n_zones), jnp.float32),
        b_head=jnp.zeros((n_zones,), jnp.float32),
        w_skip=jnp.zeros((n_features, n_zones), jnp.float32),
    )


def temporal_trunk(
    params: TemporalParams,
    feat_hist: jax.Array,  # f32 [B, T, F]
    t_valid: jax.Array,  # bool [B, T]
    attention_fn=None,  # (q, k, v, t_valid) → out; default dense causal
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """Shared trunk → hidden states f32 [B, T, D].

    ``attention_fn`` is the seam where ring attention plugs in: the
    sequence-parallel program passes the shard-mapped ring kernel, serving
    passes nothing and gets dense causal attention.
    """
    b, t, _ = feat_hist.shape
    d = params["in_proj"].shape[1]
    h = N_HEADS
    cd = compute_dtype

    # half operands, f32 accumulators (KTL120 dtype-flow): every matmul
    # goes through acc_matmul; residual/bias/softmax arithmetic stays f32
    x = acc_matmul(feat_hist, params["in_proj"], cd)
    x = x + params["pos_emb"][:t]
    x = jnp.where(t_valid[..., None], x, 0.0)

    # -- attention block (pre-LN, residual) --------------------------------
    y = layer_norm(x, params["ln1_scale"], params["ln1_bias"])
    q = acc_matmul(y, params["wq"], cd).reshape(b, t, h, d // h)
    k = acc_matmul(y, params["wk"], cd).reshape(b, t, h, d // h)
    v = acc_matmul(y, params["wv"], cd).reshape(b, t, h, d // h)
    if attention_fn is None:
        attn = full_attention(q, k, v, causal=True, t_valid=t_valid,
                              compute_dtype=cd)
    else:
        attn = attention_fn(q, k, v, t_valid)
    attn = attn.reshape(b, t, d)
    x = x + acc_matmul(attn, params["wo"], cd)

    # -- MLP block ---------------------------------------------------------
    y = layer_norm(x, params["ln2_scale"], params["ln2_bias"])
    y = jax.nn.gelu(acc_matmul(y, params["w_mlp0"], cd)
                    + params["b_mlp0"])
    x = x + acc_matmul(y, params["w_mlp1"], cd) + params["b_mlp1"]

    return layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])


def _last_query_trunk(
    params: TemporalParams,
    feat_hist: jax.Array,  # f32 [B, T, F]
    t_valid: jax.Array,  # bool [B, T]
    compute_dtype: jnp.dtype,
) -> jax.Array:
    """Dense-serving fast path → pooled hidden f32 [B, D].

    Only the LAST valid timestep feeds the head, so the attention block
    needs one query row per sequence (K/V still span the window): at the
    last valid position the causal mask plus right-padding reduces to
    ``t_valid`` itself. Cuts the trunk's matmul FLOPs ~4× vs computing
    all T positions (Q/O/MLP shrink by T; K/V stay) — same math as
    ``temporal_trunk`` + take_along_axis, verified in tests.
    """
    b, t, _ = feat_hist.shape
    d = params["in_proj"].shape[1]
    h = N_HEADS
    dh = d // h
    cd = compute_dtype

    x = acc_matmul(feat_hist, params["in_proj"], cd)
    x = x + params["pos_emb"][:t]
    x = jnp.where(t_valid[..., None], x, 0.0)
    last = jnp.maximum(jnp.sum(t_valid, axis=-1) - 1, 0).astype(jnp.int32)

    y = layer_norm(x, params["ln1_scale"], params["ln1_bias"])
    y_last = jnp.take_along_axis(y, last[:, None, None], axis=1)[:, 0]
    q = acc_matmul(y_last, params["wq"], cd).reshape(b, h, dh)
    k = acc_matmul(y, params["wk"], cd).reshape(b, t, h, dh)
    v = acc_matmul(y, params["wv"], cd).reshape(b, t, h, dh)
    scores = jnp.einsum("bhd,bthd->bht", q.astype(cd), k.astype(cd),
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(dh, jnp.float32))
    # finite mask value (not -inf): an all-invalid window must yield 0
    # attention, not softmax(-inf…)=NaN — parity with full_attention's
    # l_safe clamping for fully-masked rows. The causal constraint
    # (position ≤ last) keeps this path exact on gapped t_valid masks,
    # not just the contiguous right-padded prefixes history windows
    # produce — full parity with the all-positions trunk.
    causal = jnp.arange(t, dtype=jnp.int32)[None, :] <= last[:, None]
    scores = jnp.where((t_valid & causal)[:, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    any_valid = t_valid.any(axis=-1)
    probs = jnp.where(any_valid[:, None, None], probs, 0.0)
    attn = jnp.einsum("bht,bthd->bhd", probs.astype(cd), v.astype(cd),
                      preferred_element_type=jnp.float32).reshape(b, d)

    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
    x_last = x_last + acc_matmul(attn, params["wo"], cd)

    y = layer_norm(x_last, params["ln2_scale"], params["ln2_bias"])
    y = jax.nn.gelu(acc_matmul(y, params["w_mlp0"], cd)
                    + params["b_mlp0"])
    x_last = x_last + acc_matmul(y, params["w_mlp1"], cd) + params["b_mlp1"]
    return layer_norm(x_last, params["ln_f_scale"], params["ln_f_bias"])


def predict_temporal(
    params: TemporalParams,
    feat_hist: jax.Array,  # f32 [..., W, T, F]
    workload_valid: jax.Array,  # bool [..., W]
    t_valid: jax.Array | None = None,  # bool [..., W, T]
    clamp: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    attention_fn=None,  # override for sequence-parallel ring attention
) -> jax.Array:
    """→ watts f32 [..., W, Z] predicted from each workload's history.

    Leading axes flatten into the attention batch; the LAST valid timestep's
    hidden state feeds the head (ragged histories right-pad, so that is the
    last ``t_valid`` position, falling back to position 0 when empty).
    Dense serving (no ``attention_fn``) uses the single-query fast path;
    a custom attention_fn (ring attention over a sharded T axis) keeps the
    full-sequence trunk.
    """
    lead = feat_hist.shape[:-2]
    t, f = feat_hist.shape[-2:]
    x = feat_hist.reshape(-1, t, f)
    tv = (jnp.ones(x.shape[:2], bool) if t_valid is None
          else t_valid.reshape(-1, t))
    last = jnp.maximum(jnp.sum(tv, axis=-1) - 1, 0).astype(jnp.int32)
    if attention_fn is None:
        pooled = _last_query_trunk(params, x, tv, compute_dtype)
    else:
        hidden = temporal_trunk(params, x, tv, attention_fn=attention_fn,
                                compute_dtype=compute_dtype)
        pooled = jnp.take_along_axis(
            hidden, last[:, None, None], axis=1)[:, 0]
    # wide-and-deep: the current (= last valid) tick's raw features carry
    # the first-order linear power signal in f32; the attention trunk adds
    # the history-conditioned correction (see predict_mlp's w_skip note)
    feat_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
    watts = (pooled @ params["w_head"]
             + feat_last.astype(jnp.float32) @ params["w_skip"]
             + params["b_head"])
    watts = watts.reshape(*lead, -1)
    if clamp:
        watts = jnp.maximum(watts, 0.0)
    return jnp.where(workload_valid[..., None], watts, 0.0)
