"""Feature engineering for learned power models.

The kepler-model-server (the reference ecosystem's model-serving sidecar,
referenced by BASELINE.json configs 3-4) predicts workload power from
resource-usage counters when RAPL isn't available (VMs, non-Intel nodes).
Here the feature pipeline is a pure function from the informer's
``FeatureBatch`` (+ node context) to a dense ``[W, F]`` matrix, so the model
evaluation fuses with ratio attribution in one device program.

Feature vector (F = 7):
    0: cpu_time_delta       seconds of CPU in the window
    1: cpu_share            workload delta / node delta (the ratio feature)
    2: node_usage_ratio     broadcast node active/total ratio
    3: dt                   window length (s)
    4: cpu_rate             cpu_time_delta / dt (cores actively used)
    5: bias                 constant 1.0
    6: node_cpu_log         broadcast log1p(node cpu delta) — node-level
                            load, the input nonlinear power curves (load-
                            dependent efficiency) are functions of; without
                            it a trunk would have to reconstruct node load
                            as cpu/share, a division GELU stacks learn
                            poorly (kepler-model-server's feature sets
                            likewise carry node-scope counters)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NUM_FEATURES = 7


def build_features(
    cpu_deltas: jax.Array,  # f32 [..., W]
    workload_valid: jax.Array,  # bool [..., W]
    node_cpu_delta: jax.Array,  # f32 [...]
    usage_ratio: jax.Array,  # f32 [...]
    dt_s: jax.Array,  # f32 [...]
) -> jax.Array:
    """→ f32 [..., W, F]; masked rows are all-zero (bias included)."""
    from kepler_tpu.ops.attribution import _workload_ratios

    deltas = jnp.where(workload_valid, cpu_deltas, 0.0)
    # the exact ratio the attribution kernel uses — the model's share
    # feature must match the labels it is trained to reproduce
    share = _workload_ratios(cpu_deltas, workload_valid, node_cpu_delta)
    dt = jnp.maximum(dt_s[..., None], 1e-30)
    rate = jnp.where(dt_s[..., None] > 0, deltas / dt, 0.0)
    broadcast = jnp.broadcast_to
    w_shape = deltas.shape
    node_log = jnp.log1p(jnp.maximum(node_cpu_delta, 0.0))
    feats = jnp.stack(
        [
            deltas,
            share,
            broadcast(usage_ratio[..., None], w_shape),
            broadcast(dt_s[..., None], w_shape),
            rate,
            jnp.ones_like(deltas),
            broadcast(node_log[..., None], w_shape),
        ],
        axis=-1,
    )
    return jnp.where(workload_valid[..., None], feats, 0.0)
