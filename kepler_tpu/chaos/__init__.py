"""kepchaos: randomized fault-schedule conductor over the real fleet.

The concrete-execution complement to ``kepler_tpu.kepmc`` (which model-
checks the pure decision layer exhaustively at small scope): kepchaos
generates randomized, time-phased fault schedules over the full
composed surface — fault-site injections, replica kill/restart,
membership join/leave/autoscale ops — drives them against an
in-process fleet of real aggregators and wire-faithful agents, and
judges five global invariants on every run. Runs are keyed by
``(seed, schedule index)`` and replay bit-identically; failing
schedules shrink to a minimal fault subsequence via delta debugging.

Exports resolve lazily (PEP 562): ``python -m kepler_tpu.chaos``
imports this module before ``__main__`` gets a chance to pin the JAX
platform env, so nothing here may import the fleet (and thus jax) at
module import time.

See docs/developer/resilience.md "Randomized chaos" and run
``python -m kepler_tpu.chaos --help``.
"""

from typing import Any

_EXPORTS = {
    "ChaosAgent": "kepler_tpu.chaos.harness",
    "ChaosConfig": "kepler_tpu.chaos.harness",
    "ChaosEvent": "kepler_tpu.chaos.schedule",
    "ChaosFleet": "kepler_tpu.chaos.harness",
    "ChaosReport": "kepler_tpu.chaos.conductor",
    "EXCLUDED_SITES": "kepler_tpu.chaos.schedule",
    "FAULT_POOL": "kepler_tpu.chaos.schedule",
    "MembershipView": "kepler_tpu.chaos.invariants",
    "RowRecord": "kepler_tpu.chaos.invariants",
    "RunRecord": "kepler_tpu.chaos.invariants",
    "RunResult": "kepler_tpu.chaos.conductor",
    "Schedule": "kepler_tpu.chaos.schedule",
    "Trace": "kepler_tpu.chaos.trace",
    "Violation": "kepler_tpu.chaos.invariants",
    "WindowRecord": "kepler_tpu.chaos.invariants",
    "check_all": "kepler_tpu.chaos.invariants",
    "compile_fault_specs": "kepler_tpu.chaos.schedule",
    "ddmin": "kepler_tpu.chaos.schedule",
    "generate": "kepler_tpu.chaos.schedule",
    "repro_command": "kepler_tpu.chaos.conductor",
    "run_many": "kepler_tpu.chaos.conductor",
    "run_schedule": "kepler_tpu.chaos.conductor",
    "shrink": "kepler_tpu.chaos.conductor",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str) -> Any:
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)
