"""kepchaos event traces: canonical, hashable run transcripts.

Every conductor run appends typed events (spawn, send outcome, publish
digest, membership op, final counters) to a :class:`Trace`. The trace
serializes to *canonical JSON* (sorted keys, no whitespace, numpy
scalars coerced to Python) and hashes with SHA-256 — the determinism
pin asserts that replaying the same ``(seed, schedule)`` yields a
bit-identical canonical form, so ``trace_hash`` equality is the whole
test. Nothing wall-clock-derived may enter a trace event; all ``t``
fields are virtual-clock seconds.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any


def jsonable(value: Any) -> Any:
    """Coerce numpy scalars/arrays (and nested containers) to plain
    Python so canonical JSON never depends on numpy repr details."""
    if isinstance(value, dict):
        return {str(k): jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [jsonable(v) for v in value]
    if isinstance(value, (str, int, bool)) or value is None:
        return value
    if isinstance(value, float):
        return value
    if hasattr(value, "item"):        # numpy scalar
        return jsonable(value.item())
    if hasattr(value, "tolist"):      # numpy array
        return jsonable(value.tolist())
    return str(value)


class Trace:
    """Append-only event transcript for one conductor run."""

    def __init__(self) -> None:
        self.events: list[dict[str, Any]] = []

    def emit(self, kind: str, **fields: Any) -> None:
        event = {"kind": kind}
        event.update(jsonable(fields))
        self.events.append(event)

    def canonical(self) -> str:
        return json.dumps(self.events, sort_keys=True,
                          separators=(",", ":"))

    def hash(self) -> str:
        return hashlib.sha256(self.canonical().encode()).hexdigest()

    def __len__(self) -> int:
        return len(self.events)


def digest_rows(rows: list[dict[str, Any]]) -> str:
    """Stable content digest for one published window's rows (used in
    ``publish`` trace events so traces stay small but still pin the
    numeric content bit-for-bit)."""
    canon = json.dumps(jsonable(rows), sort_keys=True,
                       separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:16]
