"""kepchaos harness: a real in-process fleet under conductor control.

No protocol logic is mocked. The fleet is real ``Aggregator`` replicas
(window engines included, ``model_mode=None`` so no trained model is
needed) wired through the same injected seams production uses: the
``membership_topology`` seam for peer probes and membership delivery,
the ``clock`` seam for all time. Agents speak the real v2 wire format
through ``Aggregator._handle_report`` — the same entry the HTTP server
calls — and consult the real ``fault.fire`` sites on their send path,
mirroring ``kepler_tpu.fleet.agent`` behavior (failover rotation,
421-redirect following, 429 throttle obedience, ``acked_through``
stamping) in a deterministically schedulable form.

Determinism rules (the trace-hash pin depends on them):

- all time is the fleet's virtual clock; nothing reads the wall clock;
- all report content derives from ``crc32(f"{seed}:{name}:{win}")`` —
  never builtin ``hash``, which CPython salts per process;
- every iteration over replicas/agents is in sorted order.
"""

from __future__ import annotations

import json
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from kepler_tpu import fault
from kepler_tpu.chaos.trace import Trace
from kepler_tpu.fleet import wire
from kepler_tpu.fleet.aggregator import Aggregator
from kepler_tpu.fleet.journal import EventJournal
from kepler_tpu.parallel.fleet import MODE_RATIO, NodeReport
from kepler_tpu.server.http import APIServer

ZONES: tuple[str, ...] = ("package", "dram")
# published windows carry zones in sorted order — precompute the
# permutation so the emission ledger matches row-for-row
_CANON = tuple(int(i) for i in np.argsort(np.array(ZONES)))


class _Req:
    """Stand-in for the HTTP handler's request object (same shape the
    membership/report tests use)."""

    command = "POST"

    def __init__(self, body: bytes) -> None:
        self.body = body


def content_rng(seed: int, name: str, win: int) -> np.random.Generator:
    """Per-(agent, window) content stream, stable across processes."""
    key = zlib.crc32(f"{seed}:{name}:{win}".encode())
    return np.random.default_rng(key)


@dataclass
class ChaosConfig:
    """Harness shape knobs. Defaults are sized so one schedule (horizon
    + cooldown windows) runs in well under a second of wall time after
    the per-replica warm-up compiles."""

    replicas: int = 3
    standbys: int = 1
    agents: int = 4
    workloads: int = 3
    interval: float = 5.0          # virtual seconds per window
    horizon: int = 12              # windows with faults/ops scheduled
    cooldown: int = 12             # clean windows before convergence
    repromote_after: int = 1
    attempts_per_tick: int = 8     # agent send attempts per window

    @property
    def degraded_ttl(self) -> float:
        # quarantine flags must decay within the cooldown
        return self.interval * max(2, self.cooldown // 3)

    @property
    def total_windows(self) -> int:
        return self.horizon + self.cooldown


class ChaosAgent:
    """A deterministic stand-in for ``fleet.agent``: emits one report
    per window into an ordered pending queue and drains it against the
    fleet, consulting the real fault sites the production agent does.
    Pending windows are never abandoned, so any ``windows_lost_total``
    the servers count is fabricated by definition."""

    def __init__(self, name: str, seed: int, endpoints: list[str],
                 cfg: ChaosConfig) -> None:
        self.name = name
        self.seed = seed
        self.cfg = cfg
        self.run = f"chaos-{seed}"
        self.endpoints = list(endpoints)
        self._cursor = zlib.crc32(name.encode()) % len(endpoints)
        self.target = endpoints[self._cursor]
        self.pending: deque[tuple[int, NodeReport]] = deque()
        self.acked_through = 0

    def _rotate(self) -> None:
        self._cursor = (self._cursor + 1) % len(self.endpoints)
        self.target = self.endpoints[self._cursor]

    def emit(self, win: int,
             ledger: dict[str, dict[int, dict[str, Any]]]) -> None:
        rng = content_rng(self.seed, self.name, win)
        w = self.cfg.workloads
        cpu = rng.uniform(0.1, 5.0, w).astype(np.float32)
        deltas = rng.uniform(1e7, 5e8, len(ZONES)).astype(np.float32)
        ratio = float(rng.uniform(0.2, 0.9))
        valid = np.ones(len(ZONES), bool)
        spec = fault.fire("device.read_error")
        if spec is not None:
            valid[int(spec.arg or 0) % len(ZONES)] = False
        report = NodeReport(
            node_name=self.name,
            zone_deltas_uj=deltas,
            zone_valid=valid,
            usage_ratio=ratio,
            cpu_deltas=cpu,
            workload_ids=[f"{self.name}-w{k}" for k in range(w)],
            node_cpu_delta=float(cpu.sum()),
            dt_s=self.cfg.interval,
            mode=MODE_RATIO,
            workload_kinds=np.ones(w, np.int8))
        masked = np.where(valid, deltas, 0.0)
        ledger.setdefault(self.name, {})[win] = {
            "energy": [float(masked[i]) for i in _CANON],
            "ratio": ratio}
        self.pending.append((win, report))

    def drain(self, fleet: "ChaosFleet", now: float, trace: Trace
              ) -> None:
        budget = self.cfg.attempts_per_tick
        while self.pending and budget > 0:
            budget -= 1
            seq, report = self.pending[0]
            outcome = self._attempt(fleet, now, seq, report, trace)
            if outcome == "acked":
                self.pending.popleft()
                self.acked_through = seq
            elif outcome == "stop":
                break
            # "retry": loop again against the (possibly rotated) target

    def _attempt(self, fleet: "ChaosFleet", now: float, seq: int,
                 report: NodeReport, trace: Trace) -> str:
        if fault.fire("net.refuse") is not None:
            trace.emit("send", agent=self.name, seq=seq, out="refused")
            self._rotate()
            return "stop"
        spec = fault.fire("net.throttle")
        if spec is not None:
            # the production agent honors Retry-After: no failover, no
            # breaker — just back off until the next window
            trace.emit("send", agent=self.name, seq=seq, out="throttled")
            return "stop"
        sent_at = now
        spec = fault.fire("report.clock_skew")
        if spec is not None:
            sent_at += spec.arg if spec.arg is not None else 300.0
        data = wire.encode_report_v2(
            report, list(ZONES), seq=seq, run=self.run, sent_at=sent_at)
        data = wire.restamp_transmit(
            data, sent_at=sent_at, acked_through=self.acked_through)
        if fault.fire("net.corrupt_body") is not None:
            data = data[:max(8, len(data) // 2)]
        target = self.target
        result = fleet.post_report(target, data)
        if result is None:   # connection refused: peer is down
            trace.emit("send", agent=self.name, seq=seq, out="down",
                       target=target)
            self._rotate()
            return "retry"
        status, _, body = result
        if fault.fire("net.partition") is not None:
            # delivered, but the response is lost: the agent keeps the
            # window pending and re-sends — dedup must absorb it
            trace.emit("send", agent=self.name, seq=seq,
                       out="partitioned", status=status, target=target)
            return "retry"
        trace.emit("send", agent=self.name, seq=seq, out=status,
                   target=target)
        if status == 204:
            return "acked"
        if status == 421:
            try:
                owner = json.loads(body).get("owner", "")
            except Exception:
                owner = ""
            if owner and owner in self.endpoints:
                self.target = owner
                self._cursor = self.endpoints.index(owner)
            else:
                self._rotate()
            return "retry"
        if status == 503:
            self._rotate()
            return "stop"
        if status == 429:
            return "stop"
        # 400/422/409: this attempt is burned (quarantine counted
        # server-side); the window stays pending for the next tick
        return "stop"


class _StubAdmission:
    """Feeds ``_autoscale_tick`` a fixed load signal (same shape as the
    membership tests' stub) so autoscale ops are deterministic."""

    def __init__(self, load: float) -> None:
        self._load = load

    def load(self) -> float:
        return self._load

    def shed_by_reason(self) -> dict[str, int]:
        return {}

    def latency_ewma(self) -> float:
        return 0.0


class ChaosFleet:
    """Replicated aggregators + membership seams + conductor ops."""

    def __init__(self, cfg: ChaosConfig, trace: Trace) -> None:
        self.cfg = cfg
        self.trace = trace
        self.ticks = [1e9]
        base = [f"10.99.0.{i + 1}:28283"
                for i in range(cfg.replicas + cfg.standbys)]
        self.members0 = base[:cfg.replicas]
        self.standby_peers = base[cfg.replicas:]
        self.endpoints = list(base)
        self.alive: set[str] = set()
        self.aggs: dict[str, Aggregator] = {}
        # counter/timeline snapshots from replicas at kill time, keyed
        # by incarnation ("peer#generation")
        self.retired_stats: dict[str, dict[str, int]] = {}
        self.retired_timelines: dict[str, list[dict[str, Any]]] = {}
        self.retired_journals: dict[str, list[dict[str, Any]]] = {}
        # ground truth for invariant 6: schedule ops whose fleet effect
        # is CERTAIN (a kill is only certain once a succession tick saw
        # the peer still dead; a restart/join only when it actually
        # re-registers an absent peer; autoscale only on an epoch bump)
        self.op_log: list[dict[str, Any]] = []
        self._pending_kills: list[dict[str, Any]] = []
        self._generation: dict[str, int] = {}
        for peer in self.members0:
            self._spawn(peer, self.members0)

    # -- seams ------------------------------------------------------------

    def clock(self) -> float:
        return self.ticks[0]

    def _peer_alive(self, peer: str) -> bool:
        return peer in self.alive

    def _deliver(self, target: str, payload: dict) -> dict:
        if target not in self.alive:
            raise OSError(f"connection refused: {target}")
        status, _, body = self.aggs[target]._handle_membership(
            _Req(json.dumps(payload).encode()))
        del status
        return json.loads(body)

    def post_report(self, target: str, data: bytes
                    ) -> tuple[int, dict, bytes] | None:
        if target not in self.alive:
            return None
        return self.aggs[target]._handle_report(_Req(data))

    # -- lifecycle --------------------------------------------------------

    def _spawn(self, peer: str, ring_hint: list[str]) -> Aggregator:
        agg = Aggregator(
            APIServer(),
            peers=sorted(set(ring_hint) | {peer}),
            self_peer=peer,
            model_mode=None,
            node_bucket=8,
            workload_bucket=8,
            stale_after=1e9,
            pipeline_depth=1,
            repromote_after=self.cfg.repromote_after,
            degraded_ttl=self.cfg.degraded_ttl,
            dispatch_timeout=120.0,
            clock=self.clock,
            membership_topology={"peer_alive": self._peer_alive,
                                 "deliver": self._deliver},
            # autoscale stays DISARMED between ops: the per-window tick
            # with no admission controller reads load 0.0, which with
            # autoApply would scale the fleet down on its own — the
            # conductor installs a policy only for commanded ticks
            membership_autoscale=False,
            membership_auto_apply=True,
            membership_standby_peers=list(self.standby_peers),
            # black-box journal on the fleet's virtual clock: HLC stamps
            # derive from self.clock, so the merged timeline is as
            # replay-stable as the trace
            journal=EventJournal(enabled=True, node=peer,
                                 clock=self.clock))
        agg.init()
        self.aggs[peer] = agg
        self.alive.add(peer)
        self.trace.emit("spawn", peer=peer, t=self.clock())
        return agg

    def incarnation(self, peer: str) -> str:
        return f"{peer}#{self._generation.get(peer, 0)}"

    def _now_us(self) -> int:
        return int(self.clock() * 1e6)

    def _member_epoch(self) -> int:
        """Ring epoch in the stable member view (0 when none)."""
        for peer in sorted(self.alive):
            ring = self.aggs[peer]._ring
            if ring is not None and peer in ring.peers:
                return int(ring.epoch)
        return 0

    def kill(self, peer: str) -> bool:
        if peer not in self.alive:
            return False
        members = self.member_peers()
        if peer in members and not [
                m for m in members if m != peer and m in self.alive]:
            return False   # never kill the last live member
        if peer in members:
            # not yet CERTAIN: a restart in this same window would
            # revive the peer before any succession demotes it — the
            # op is sealed into op_log by the next succession tick
            self._pending_kills.append({
                "op": "kill", "peer": peer, "t_us": self._now_us(),
                "epoch_before": self._member_epoch()})
        agg = self.aggs[peer]
        self.retired_stats[self.incarnation(peer)] = dict(agg._stats)
        self.retired_timelines[self.incarnation(peer)] = [
            dict(e) for e in agg._rung_timeline]
        self.retired_journals[self.incarnation(peer)] = \
            agg._journal.snapshot()
        self.alive.discard(peer)
        agg.shutdown()
        del self.aggs[peer]
        self._generation[peer] = self._generation.get(peer, 0) + 1
        self.trace.emit("kill", peer=peer, t=self.clock())
        return True

    def restart(self, peer: str) -> bool:
        if peer in self.alive:
            return False
        hint = self.member_peers() or list(self.members0)
        # a revive before the succession tick voids any pending kill:
        # the excluding succession apply it would witness never happens
        self._pending_kills = [
            op for op in self._pending_kills if op["peer"] != peer]
        was_member = peer in self.member_peers()
        epoch_before = self._member_epoch()
        agg = self._spawn(peer, hint)
        try:
            agg.request_join()
            self.trace.emit("join", peer=peer, t=self.clock(), ok=True)
            if not was_member:
                # certain: registering an absent peer forces a
                # membership apply that names it
                self.op_log.append({
                    "op": "restart", "peer": peer,
                    "t_us": self._now_us(),
                    "epoch_before": epoch_before})
            return True
        except Exception as err:
            self.trace.emit("join", peer=peer, t=self.clock(), ok=False,
                            reason=type(err).__name__)
            return False

    def join_op(self, peer: str) -> bool:
        """Join semantics for every starting state: dead peer -> spawn
        and register; live retired peer (left earlier) -> re-register;
        live member -> no-op."""
        if peer not in self.alive:
            return self.restart(peer)
        agg = self.aggs[peer]
        ring = agg._ring
        if ring is not None and peer in ring.peers:
            return False
        was_member = peer in self.member_peers()
        epoch_before = self._member_epoch()
        try:
            agg.request_join()
            self.trace.emit("join", peer=peer, t=self.clock(), ok=True)
            if not was_member:
                self.op_log.append({
                    "op": "join", "peer": peer, "t_us": self._now_us(),
                    "epoch_before": epoch_before})
            return True
        except Exception as err:
            self.trace.emit("join", peer=peer, t=self.clock(), ok=False,
                            reason=type(err).__name__)
            return False

    def leave(self, peer: str) -> bool:
        members = self.member_peers()
        if peer not in members or len(members) <= 1:
            return False
        start = sorted(m for m in members if m in self.alive)
        if not start:
            return False
        epoch_before = self._member_epoch()
        target = start[0]
        for _ in range(len(members) + 2):
            try:
                reply = self._deliver(target,
                                      {"op": "leave", "peer": peer})
            except OSError:
                break
            if reply.get("reason") == "not_leader":
                nxt = reply.get("holder", "")
                if not nxt or nxt == target or nxt not in self.alive:
                    break
                target = nxt
                continue
            self.trace.emit("leave", peer=peer, via=target,
                            ok=bool(reply.get("ok")), t=self.clock())
            if reply.get("ok"):
                # certain: an ok reply means the leader applied the
                # excluding membership with an epoch bump
                self.op_log.append({
                    "op": "leave", "peer": peer, "t_us": self._now_us(),
                    "epoch_before": epoch_before})
                # a dead member leaving is the same excluding apply a
                # pending kill of THAT peer is waiting on: certain now
                self.op_log.extend(op for op in self._pending_kills
                                   if op["peer"] == peer)
                self._pending_kills = [op for op in self._pending_kills
                                       if op["peer"] != peer]
            return bool(reply.get("ok"))
        self.trace.emit("leave", peer=peer, ok=False, t=self.clock())
        return False

    def autoscale(self, up: bool) -> bool:
        from kepler_tpu.fleet.membership import AutoscalePolicy

        holder = self.current_holder()
        if not holder or holder not in self.alive:
            return False
        agg = self.aggs[holder]
        epoch_before = int(agg._ring.epoch)
        agg._admission = _StubAdmission(2.0 if up else 0.0)
        agg._autoscale = AutoscalePolicy(up_windows=1, down_windows=1)
        try:
            agg._autoscale_tick()
        finally:
            agg._admission = None
            agg._autoscale = None
        self.trace.emit("autoscale", direction="up" if up else "down",
                        holder=holder, t=self.clock(),
                        epoch=agg._ring.epoch)
        if int(agg._ring.epoch) > epoch_before:
            # certain only when the tick actually enacted a scale (at
            # the replica floor/ceiling nothing changes)
            self.op_log.append({
                "op": "autoscale", "peer": "", "t_us": self._now_us(),
                "epoch_before": epoch_before})
        if up:
            # the autoscaler "provisioned" the promoted standby: give
            # any member peer without a live process one, and have it
            # register to adopt the incumbent lease
            for peer in sorted(agg._ring.peers):
                if peer not in self.alive:
                    self.restart(peer)
        return True

    # -- views ------------------------------------------------------------

    def member_peers(self) -> list[str]:
        """Membership as seen by live replicas that are members of
        their own ring (the stable view once converged)."""
        for peer in sorted(self.alive):
            ring = self.aggs[peer]._ring
            if ring is not None and peer in ring.peers:
                return list(ring.peers)
        return []

    def current_holder(self) -> str:
        for peer in sorted(self.alive):
            agg = self.aggs[peer]
            ring = agg._ring
            if ring is None or peer not in ring.peers:
                continue
            lease = agg._lease
            if lease is not None and lease.holder:
                return str(lease.holder)
        return ""

    def succession_tick(self) -> None:
        """What the health-probe loop does in production: every live
        member that sees a dead ring peer runs mesh demotion, which
        probes survivors and lets exactly one issuer drive the epoch
        bump + broadcast."""
        if self._pending_kills:
            # a peer still dead at succession time WILL be demoted by
            # this tick (the membership seams are deterministic): the
            # pending kill's fleet effect is certain now
            self.op_log.extend(op for op in self._pending_kills
                               if op["peer"] not in self.alive)
            self._pending_kills.clear()
        for peer in sorted(self.alive):
            agg = self.aggs[peer]
            ring = agg._ring
            if ring is None or peer not in ring.peers:
                continue
            if any(p not in self.alive for p in ring.peers):
                agg._demote_mesh("host_dead")

    def shutdown(self) -> None:
        for peer in sorted(self.aggs):
            self.aggs[peer].shutdown()
        self.aggs.clear()
        self.alive.clear()
