"""kepchaos CLI: run randomized schedules, replay a key, shrink.

Exit status: 0 = all schedules green, 1 = an invariant violation (the
failing ``(seed, schedule)`` key, its violations, and copy-paste repro
commands — full and shrunk — are printed), 2 = usage error.

Examples::

    python -m kepler_tpu.chaos --schedules 25          # make chaos
    python -m kepler_tpu.chaos --seed 7 --schedule 3   # replay one key
    python -m kepler_tpu.chaos --seed 7 --schedule 3 --keep 1,4
    python -m kepler_tpu.chaos --schedules 100 --artifact CHAOS_r02.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _pin_cpu_env() -> None:
    """Same pinning tests/conftest.py does: a virtual 8-device CPU mesh
    so window engines shard identically everywhere (the trace hash
    depends on it) and no real accelerator is touched."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kepler_tpu.chaos",
        description="randomized fault-schedule conductor (kepchaos)")
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--schedules", type=int, default=25,
                        help="number of schedule indices to sweep")
    parser.add_argument("--schedule", type=int, default=None,
                        help="replay exactly this schedule index")
    parser.add_argument("--keep", type=str, default="",
                        help="comma-separated event indices (replay a "
                             "shrunk subsequence; needs --schedule)")
    parser.add_argument("--no-shrink", action="store_true",
                        help="report the first failure without "
                             "delta-debugging it")
    parser.add_argument("--windows", type=int, default=None,
                        help="override the scheduled-fault horizon")
    parser.add_argument("--agents", type=int, default=None)
    parser.add_argument("--replicas", type=int, default=None)
    parser.add_argument("--artifact", type=str, default="",
                        help="write the ChaosReport JSON here")
    args = parser.parse_args(argv)

    if args.keep and args.schedule is None:
        parser.error("--keep requires --schedule")

    _pin_cpu_env()
    # heavy imports only after the env pin (they pull in jax)
    from kepler_tpu.chaos.conductor import (
        ChaosReport, repro_command, run_many, run_schedule, shrink)
    from kepler_tpu.chaos.harness import ChaosConfig
    from kepler_tpu.chaos.schedule import generate

    cfg = ChaosConfig()
    if args.windows is not None:
        cfg.horizon = max(1, args.windows)
    if args.agents is not None:
        cfg.agents = max(1, args.agents)
    if args.replicas is not None:
        cfg.replicas = max(1, args.replicas)
    members = [f"10.99.0.{i + 1}:28283" for i in range(cfg.replicas)]
    standbys = [f"10.99.0.{i + 1}:28283"
                for i in range(cfg.replicas,
                               cfg.replicas + cfg.standbys)]

    if args.schedule is not None:
        schedule = generate(args.seed, args.schedule,
                            horizon=cfg.horizon, members=members,
                            standbys=standbys)
        if args.keep:
            schedule = schedule.subset(
                [int(k) for k in args.keep.split(",") if k != ""])
        result = run_schedule(schedule, cfg)
        report = ChaosReport(seed=args.seed, requested=1,
                             results=[result],
                             failure=None if result.ok else result)
        if not result.ok and not args.no_shrink and not args.keep:
            report.shrunk, report.shrink_runs = shrink(schedule, cfg)
    else:
        report = run_many(args.seed, args.schedules, cfg,
                          do_shrink=not args.no_shrink)

    for result in report.results:
        sched = result.schedule
        verdict = "green" if result.ok else "RED"
        print(f"schedule (seed={sched.seed}, index={sched.index}): "
              f"{verdict} — {len(sched.events)} events, "
              f"{result.windows_published} windows published, "
              f"trace {result.trace_hash[:16]}")
    if report.failure is not None:
        fail = report.failure
        print()
        print(f"FAILED (seed={fail.schedule.seed}, "
              f"index={fail.schedule.index}):")
        for violation in fail.violations:
            print(f"  {violation}")
        print(f"repro: {repro_command(fail.schedule)}")
        if report.shrunk is not None:
            print(f"shrunk to {len(report.shrunk.events)} events in "
                  f"{report.shrink_runs} replays:")
            for event in report.shrunk.events:
                print(f"  {event.to_dict()}")
            print(f"repro (shrunk): {repro_command(report.shrunk)}")
    else:
        print(f"all {len(report.results)} schedules green "
              f"(seed={report.seed})")
    if args.artifact:
        with open(args.artifact, "w") as fh:
            json.dump(report.to_artifact(), fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"artifact: {args.artifact}")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
