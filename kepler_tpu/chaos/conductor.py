"""kepchaos conductor: drive a schedule against the fleet, judge it.

One :func:`run_schedule` call builds a fresh fleet + agents, arms the
schedule's fault events on a virtual-clock ``FaultPlan``, executes its
op events at their window indices, records every observable into a
:class:`Trace`, assembles the :class:`RunRecord`, and returns the
invariant verdicts. :func:`run_many` iterates schedule indices from one
seed; on the first red verdict it delta-debugs the schedule down to a
minimal failing subsequence (:func:`shrink`) and attaches copy-paste
repro commands for both the full and the shrunk key.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence

from kepler_tpu import fault
from kepler_tpu.chaos.harness import ChaosAgent, ChaosConfig, ChaosFleet
from kepler_tpu.chaos.invariants import MembershipView, RowRecord, \
    RunRecord, Violation, WindowRecord, check_all
from kepler_tpu.chaos.schedule import Schedule, compile_fault_specs, \
    ddmin, generate
from kepler_tpu.chaos.trace import Trace, digest_rows
from kepler_tpu.fault import FaultPlan

# stats keys worth pinning in the trace (all integer counters)
_STAT_KEYS = ("reports_total", "rejected_total", "quarantined_total",
              "malformed_total", "clock_skew_total", "duplicates_total",
              "windows_lost_total")


@dataclass
class RunResult:
    schedule: Schedule
    violations: list[Violation]
    trace: Trace
    trace_hash: str
    record: RunRecord
    windows_published: int
    fault_fires: dict[str, int] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations


def _ops_by_window(schedule: Schedule) -> dict[int, list]:
    out: dict[int, list] = {}
    for ev in schedule.events:
        if ev.kind != "fault":
            out.setdefault(ev.at, []).append(ev)
    return out


def _execute_op(fleet: ChaosFleet, ev: Any, trace: Trace) -> None:
    if ev.kind == "kill":
        done = fleet.kill(ev.target)
    elif ev.kind == "restart":
        done = fleet.restart(ev.target)
    elif ev.kind == "join":
        done = fleet.join_op(ev.target)
    elif ev.kind == "leave":
        done = fleet.leave(ev.target)
    elif ev.kind == "autoscale_up":
        done = fleet.autoscale(up=True)
    else:   # autoscale_down
        done = fleet.autoscale(up=False)
    if not done:
        trace.emit("op_skipped", op=ev.kind, target=ev.target,
                   t=fleet.clock())


def _match_emitted(ledger_node: dict[int, dict[str, Any]],
                   energy: list[float]
                   ) -> tuple[list[float] | None, float | None]:
    """Find the emitted window whose masked zone energy best matches a
    published row; returns (emitted energy, its usage ratio). The
    conservation checker judges the match — a published row that
    matches nothing the agent ever emitted fails loudly."""
    best_key: tuple[float, int] | None = None
    best: tuple[list[float] | None, float | None] = (None, None)
    for win, entry in ledger_node.items():
        emitted = entry["energy"]
        err = sum((a - b) * (a - b) for a, b in zip(energy, emitted))
        key = (err, win)
        if best_key is None or key < best_key:
            best_key = key
            best = (list(emitted), float(entry["ratio"]))
    return best


def run_schedule(schedule: Schedule, cfg: ChaosConfig | None = None
                 ) -> RunResult:
    cfg = cfg or ChaosConfig()
    trace = Trace()
    trace.emit("schedule", seed=schedule.seed, index=schedule.index,
               events=[e.to_dict() for e in schedule.events],
               keep=list(schedule.keep))
    fleet = ChaosFleet(cfg, trace)
    agents = [ChaosAgent(f"cn{i:02d}", schedule.seed, fleet.endpoints,
                         cfg) for i in range(cfg.agents)]
    # agent name -> win -> {"energy": canonical masked uJ, "ratio": r}
    ledger: dict[str, dict[int, dict[str, Any]]] = {}
    plan = FaultPlan(compile_fault_specs(schedule.events, cfg.interval),
                     seed=schedule.seed * 1_000_003 + schedule.index,
                     clock=fleet.clock)
    ops = _ops_by_window(schedule)
    windows: list[WindowRecord] = []
    try:
        with fault.installed(plan):
            for win in range(1, cfg.total_windows + 1):
                fleet.ticks[0] += cfg.interval
                now = fleet.clock()
                for ev in ops.get(win - 1, ()):
                    _execute_op(fleet, ev, trace)
                fleet.succession_tick()
                for agent in agents:
                    agent.emit(win, ledger)
                for agent in agents:
                    agent.drain(fleet, now, trace)
                for peer in sorted(fleet.alive):
                    res = fleet.aggs[peer].aggregate_once()
                    if res is None or not res.names:
                        continue
                    wr = _window_record(peer, win, res, ledger)
                    windows.append(wr)
                    trace.emit(
                        "publish", replica=peer, win=win,
                        names=sorted(res.names),
                        digest=digest_rows([_row_dict(r)
                                            for r in wr.rows]))
        record = _assemble(fleet, agents, windows, cfg)
        record_final_trace(trace, fleet, record, plan)
        violations = check_all(record)
        trace.emit("verdict",
                   violations=[str(v) for v in violations])
        return RunResult(schedule=schedule, violations=violations,
                         trace=trace, trace_hash=trace.hash(),
                         record=record,
                         windows_published=len(windows),
                         fault_fires=dict(plan.fires))
    finally:
        fleet.shutdown()


def _row_dict(row: RowRecord) -> dict[str, Any]:
    return {"node": row.node, "dt": row.dt,
            "energy_uj": list(row.energy_uj),
            "power_uw": list(row.power_uw),
            "wl_sum_uw": list(row.wl_power_sum_uw),
            "wl_ids": list(row.wl_ids)}


def _window_record(peer: str, win: int, res: Any,
                   ledger: dict[str, dict[int, dict[str, Any]]]
                   ) -> WindowRecord:
    rows: list[RowRecord] = []
    for name in sorted(res.rows):
        i = res.rows[name]
        w = int(res.counts[i])
        energy = [float(x) for x in res.node_energy_uj[i]]
        power = [float(x) for x in res.node_power_uw[i]]
        wl_sum = [float(x)
                  for x in res.wl_power_uw[i, :w].sum(axis=0)]
        emitted, ratio = _match_emitted(ledger.get(name, {}), energy)
        rows.append(RowRecord(
            node=name, dt=float(res.dt[i]),
            energy_uj=tuple(energy), power_uw=tuple(power),
            wl_power_sum_uw=tuple(wl_sum),
            wl_ids=tuple(res.workload_ids[i]),
            usage_ratio=ratio,
            emitted_energy_uj=(None if emitted is None
                               else tuple(emitted))))
    return WindowRecord(replica=peer, win=win, rows=rows)


def _assemble(fleet: ChaosFleet, agents: list[ChaosAgent],
              windows: list[WindowRecord], cfg: ChaosConfig
              ) -> RunRecord:
    stats: dict[str, dict[str, int]] = dict(fleet.retired_stats)
    timelines: dict[str, list[dict[str, Any]]] = {
        k: list(v) for k, v in fleet.retired_timelines.items()}
    journals: dict[str, list[dict[str, Any]]] = {
        k: list(v) for k, v in fleet.retired_journals.items()}
    membership: dict[str, MembershipView] = {}
    health_ok: dict[str, bool] = {}
    window_health_ok: dict[str, bool] = {}
    for peer in sorted(fleet.alive):
        agg = fleet.aggs[peer]
        stats[fleet.incarnation(peer)] = dict(agg._stats)
        timelines[fleet.incarnation(peer)] = [
            dict(e) for e in agg._rung_timeline]
        journals[fleet.incarnation(peer)] = agg._journal.snapshot()
        ring = agg._ring
        lease = agg._lease
        if ring is not None:
            membership[peer] = MembershipView(
                epoch=int(ring.epoch), peers=tuple(ring.peers),
                holder=str(lease.holder) if lease is not None else "")
        health_ok[peer] = bool(agg.health().get("ok"))
        window_health_ok[peer] = bool(agg.window_health().get("ok"))
    return RunRecord(
        windows=windows, stats=stats,
        timelines={k: _clean_timeline(v) for k, v in timelines.items()},
        repromote_after=cfg.repromote_after,
        abandoned_windows=0,
        membership=membership, alive=frozenset(fleet.alive),
        health_ok=health_ok, window_health_ok=window_health_ok,
        pending={a.name: len(a.pending) for a in agents},
        journals=journals, schedule_ops=list(fleet.op_log))


def _clean_timeline(timeline: list[dict[str, Any]]
                    ) -> list[dict[str, Any]]:
    """Strip wall-clock fields so records (and the trace) stay replay-
    stable; the ladder checker only needs the transition shape."""
    keep = ("rung", "rung_name", "from_rung", "from_rung_name",
            "reason", "windows_at_prev_rung")
    return [{k: e[k] for k in keep if k in e} for e in timeline]


def record_final_trace(trace: Trace, fleet: ChaosFleet,
                       record: RunRecord, plan: FaultPlan) -> None:
    trace.emit(
        "final",
        t=fleet.clock(),
        alive=sorted(record.alive),
        membership={p: {"epoch": v.epoch, "peers": list(v.peers),
                        "holder": v.holder}
                    for p, v in sorted(record.membership.items())},
        stats={inc: {k: int(s.get(k, 0)) for k in _STAT_KEYS}
               for inc, s in sorted(record.stats.items())},
        timelines={inc: list(tl)
                   for inc, tl in sorted(record.timelines.items())},
        pending=dict(sorted(record.pending.items())),
        fault_fires=dict(sorted(plan.fires.items())))


def _sum_fires(results: Sequence[RunResult]) -> dict[str, int]:
    total: dict[str, int] = {}
    for r in results:
        for site, n in r.fault_fires.items():
            total[site] = total.get(site, 0) + int(n)
    return dict(sorted(total.items()))


def repro_command(schedule: Schedule) -> str:
    cmd = (f"python -m kepler_tpu.chaos --seed {schedule.seed} "
           f"--schedule {schedule.index}")
    if schedule.keep:
        cmd += " --keep " + ",".join(str(k) for k in schedule.keep)
    return cmd


def shrink(schedule: Schedule, cfg: ChaosConfig | None = None
           ) -> tuple[Schedule, int]:
    """Delta-debug a failing schedule to a 1-minimal failing event
    subsequence. Returns (shrunk schedule, number of replay runs)."""
    cfg = cfg or ChaosConfig()
    runs = 0

    def fails(keep: Sequence[int]) -> bool:
        nonlocal runs
        runs += 1
        return not run_schedule(schedule.subset(keep), cfg).ok

    minimal = ddmin(range(len(schedule.events)), fails)
    return schedule.subset(minimal), runs


@dataclass
class ChaosReport:
    """Aggregate verdict for a ``run_many`` sweep (the CHAOS_*.json
    artifact shape)."""

    seed: int
    requested: int
    results: list[RunResult] = field(default_factory=list)
    failure: RunResult | None = None
    shrunk: Schedule | None = None
    shrink_runs: int = 0

    @property
    def ok(self) -> bool:
        return self.failure is None

    def to_artifact(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "seed": self.seed,
            "schedules_requested": self.requested,
            "schedules_run": len(self.results),
            "events_total": sum(len(r.schedule.events)
                                for r in self.results),
            "windows_published": sum(r.windows_published
                                     for r in self.results),
            "fault_fires": _sum_fires(self.results),
            "verdicts": {
                "green": sum(1 for r in self.results if r.ok),
                "red": sum(1 for r in self.results if not r.ok)},
            "trace_hashes": {str(r.schedule.index): r.trace_hash
                             for r in self.results},
        }
        if self.failure is not None:
            fail: dict[str, Any] = {
                "index": self.failure.schedule.index,
                "violations": [str(v) for v in self.failure.violations],
                "repro": repro_command(self.failure.schedule)}
            if self.shrunk is not None:
                fail["shrunk_events"] = len(self.shrunk.events)
                fail["shrink_runs"] = self.shrink_runs
                fail["repro_shrunk"] = repro_command(self.shrunk)
            out["failure"] = fail
        return out


def run_many(seed: int, count: int, cfg: ChaosConfig | None = None,
             *, do_shrink: bool = True, start: int = 0) -> ChaosReport:
    cfg = cfg or ChaosConfig()
    members = [f"10.99.0.{i + 1}:28283" for i in range(cfg.replicas)]
    standbys = [f"10.99.0.{i + 1}:28283"
                for i in range(cfg.replicas,
                               cfg.replicas + cfg.standbys)]
    report = ChaosReport(seed=seed, requested=count)
    for index in range(start, start + count):
        schedule = generate(seed, index, horizon=cfg.horizon,
                            members=members, standbys=standbys)
        result = run_schedule(schedule, cfg)
        report.results.append(result)
        if not result.ok:
            report.failure = result
            if do_shrink:
                report.shrunk, report.shrink_runs = shrink(schedule, cfg)
            break
    return report
