"""kepchaos global invariants, checked over a :class:`RunRecord`.

The record is a plain-data snapshot the conductor assembles from a run
(published windows, counter snapshots — including ones captured from
replicas at kill time — rung timelines, final membership/health views,
agent backlogs). Keeping it hand-buildable is the point: every checker
has a test that constructs a *violating* record by hand and asserts the
checker fires (a checker that cannot fail is worse than none).

The five invariants, matching docs/developer/resilience.md:

1. **Conservation** — per published row: ``energy ≈ power × dt``, the
   workload plane sums to the node envelope (ratio mode), and when the
   agents' emission ledger is available, published energy matches what
   was emitted (masked zones included).
2. **No fabricated loss** — ``windows_lost_total`` summed over every
   replica incarnation never exceeds the windows agents really
   abandoned (zero in the conductor harness: agents never drop
   pending windows).
3. **Idempotent merge** — a node appears in at most one replica's
   published window per window index, and workload ids never repeat
   within a row.
4. **Ladder monotonicity** — demotions move exactly one rung down with
   a known failure reason; repromotions move exactly one rung up and
   only after ``repromote_after`` clean windows.
5. **Convergence** — within the cooldown after the last scheduled
   fault: all member replicas agree on (epoch, peers, holder); the
   lease holder is a live member; health and window-health probes are
   green; every agent has drained its backlog.
6. **Journal completeness + causal order** — every conductor schedule
   op with a certain effect (a kill of a member, an accepted
   join/leave/restart, an enacted autoscale) leaves matching evidence
   in the merged black-box journal, each per-incarnation journal is
   strictly HLC-increasing, and no event's HLC physical component
   precedes the conductor's virtual-clock time of the op that caused
   it — the journal can NEVER tell a story the ground-truth schedule
   contradicts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Sequence

# reasons _record_rung_transition_locked may carry for a one-rung demote
DEMOTION_REASONS: frozenset[str] = frozenset({
    "dispatch_error", "compile_error", "oom_on_grow", "stall",
    "runtime_error"})

RTOL = 1e-2       # f32 window planes, f16 workload plane
ATOL_UW = 1e3     # 1 mW absolute floor — masks pure float noise at 0


@dataclass(frozen=True)
class Violation:
    # conservation | loss | duplicates | ladder | convergence | journal
    invariant: str
    detail: str

    def __str__(self) -> str:
        return f"[{self.invariant}] {self.detail}"


@dataclass
class RowRecord:
    """One node's row in one published window (canonical zone order)."""

    node: str
    dt: float
    energy_uj: tuple[float, ...] = ()
    power_uw: tuple[float, ...] = ()
    wl_power_sum_uw: tuple[float, ...] = ()
    wl_ids: tuple[str, ...] = ()
    usage_ratio: float | None = None
    emitted_energy_uj: tuple[float, ...] | None = None


@dataclass
class WindowRecord:
    replica: str
    win: int
    rows: list[RowRecord] = field(default_factory=list)


@dataclass
class MembershipView:
    epoch: int
    peers: tuple[str, ...]
    holder: str


@dataclass
class RunRecord:
    windows: list[WindowRecord] = field(default_factory=list)
    # replica incarnation -> counter snapshot (live replicas at run end,
    # killed replicas at kill time — loss must be counted across both)
    stats: dict[str, Mapping[str, int]] = field(default_factory=dict)
    timelines: dict[str, Sequence[Mapping[str, object]]] = \
        field(default_factory=dict)
    repromote_after: int = 1
    abandoned_windows: int = 0
    membership: dict[str, MembershipView] = field(default_factory=dict)
    alive: frozenset[str] = frozenset()
    health_ok: dict[str, bool] = field(default_factory=dict)
    window_health_ok: dict[str, bool] = field(default_factory=dict)
    pending: dict[str, int] = field(default_factory=dict)
    # black box (invariant 6): replica incarnation -> journal snapshot
    # (live replicas at run end, killed incarnations at kill time) and
    # the conductor's ground-truth op log — only ops whose EFFECT was
    # certain (kill of a member, accepted join/leave/restart, enacted
    # autoscale), each with the virtual-clock time it happened at
    journals: dict[str, Sequence[Mapping[str, object]]] = \
        field(default_factory=dict)
    schedule_ops: list[Mapping[str, object]] = field(default_factory=list)


def _close(a: float, b: float) -> bool:
    return abs(a - b) <= ATOL_UW + RTOL * max(abs(a), abs(b))


def check_conservation(rec: RunRecord) -> list[Violation]:
    out: list[Violation] = []
    for wr in rec.windows:
        for row in wr.rows:
            where = f"win={wr.win} replica={wr.replica} node={row.node}"
            if len(row.energy_uj) != len(row.power_uw):
                out.append(Violation(
                    "conservation", f"{where}: zone arity mismatch"))
                continue
            for z, (e, p) in enumerate(zip(row.energy_uj, row.power_uw)):
                if not _close(e, p * row.dt):
                    out.append(Violation(
                        "conservation",
                        f"{where} zone={z}: energy {e:.1f} uJ != power "
                        f"{p:.1f} uW x dt {row.dt:.3f} s"))
            if row.emitted_energy_uj is not None:
                for z, (e, g) in enumerate(
                        zip(row.energy_uj, row.emitted_energy_uj)):
                    if not _close(e, g):
                        out.append(Violation(
                            "conservation",
                            f"{where} zone={z}: published {e:.1f} uJ != "
                            f"emitted {g:.1f} uJ"))
            if row.usage_ratio is not None and row.wl_power_sum_uw:
                for z, (s, p) in enumerate(
                        zip(row.wl_power_sum_uw, row.power_uw)):
                    want = p * row.usage_ratio
                    if not _close(s, want):
                        out.append(Violation(
                            "conservation",
                            f"{where} zone={z}: workload plane sums to "
                            f"{s:.1f} uW, node envelope gives "
                            f"{want:.1f} uW"))
    return out


def check_no_fabricated_loss(rec: RunRecord) -> list[Violation]:
    total = sum(int(s.get("windows_lost_total", 0))
                for s in rec.stats.values())
    if total > rec.abandoned_windows:
        return [Violation(
            "loss",
            f"windows_lost_total={total} across all replica "
            f"incarnations, but agents only abandoned "
            f"{rec.abandoned_windows} windows")]
    return []


def check_no_duplicates(rec: RunRecord) -> list[Violation]:
    out: list[Violation] = []
    owners: dict[tuple[int, str], str] = {}
    for wr in rec.windows:
        for row in wr.rows:
            key = (wr.win, row.node)
            prev = owners.get(key)
            if prev is not None and prev != wr.replica:
                out.append(Violation(
                    "duplicates",
                    f"win={wr.win} node={row.node} published by both "
                    f"{prev} and {wr.replica}"))
            owners[key] = wr.replica
            if len(set(row.wl_ids)) != len(row.wl_ids):
                out.append(Violation(
                    "duplicates",
                    f"win={wr.win} replica={wr.replica} "
                    f"node={row.node}: repeated workload id"))
    return out


def check_ladder(rec: RunRecord) -> list[Violation]:
    out: list[Violation] = []
    for replica, timeline in rec.timelines.items():
        for entry in timeline:
            rung = int(entry.get("rung", -1))        # type: ignore[arg-type]
            from_rung = int(entry.get("from_rung", -1))  # type: ignore[arg-type]
            reason = str(entry.get("reason", ""))
            where = (f"{replica}: {entry.get('from_rung_name')} -> "
                     f"{entry.get('rung_name')} ({reason})")
            if reason == "repromoted":
                if rung != from_rung - 1:
                    out.append(Violation(
                        "ladder",
                        f"{where}: repromotion must climb exactly one "
                        f"rung"))
                clean = int(entry.get("windows_at_prev_rung", 0))  # type: ignore[arg-type]
                if clean < rec.repromote_after:
                    out.append(Violation(
                        "ladder",
                        f"{where}: repromoted after {clean} clean "
                        f"windows < repromote_after="
                        f"{rec.repromote_after}"))
            else:
                if reason not in DEMOTION_REASONS:
                    out.append(Violation(
                        "ladder", f"{where}: unknown transition reason"))
                if rung != from_rung + 1:
                    out.append(Violation(
                        "ladder",
                        f"{where}: demotion must drop exactly one rung"))
    return out


def check_convergence(rec: RunRecord) -> list[Violation]:
    out: list[Violation] = []
    # member replicas = live replicas that appear in their own ring
    members = {r: v for r, v in rec.membership.items()
               if r in rec.alive and r in v.peers}
    if not members:
        out.append(Violation("convergence", "no live member replicas"))
        return out
    views = {(v.epoch, tuple(sorted(v.peers)), v.holder)
             for v in members.values()}
    if len(views) > 1:
        out.append(Violation(
            "convergence",
            f"member views diverge: "
            f"{sorted(str(v) for v in views)}"))
    for replica, view in sorted(members.items()):
        if view.holder not in view.peers:
            out.append(Violation(
                "convergence",
                f"{replica}: lease holder {view.holder} is not a ring "
                f"member"))
        elif view.holder not in rec.alive:
            out.append(Violation(
                "convergence",
                f"{replica}: lease holder {view.holder} is dead"))
        if not rec.health_ok.get(replica, False):
            out.append(Violation(
                "convergence", f"{replica}: health probe still red "
                f"after cooldown"))
        if not rec.window_health_ok.get(replica, False):
            out.append(Violation(
                "convergence", f"{replica}: window health still red "
                f"after cooldown"))
    for agent, backlog in sorted(rec.pending.items()):
        if backlog:
            out.append(Violation(
                "convergence",
                f"agent {agent} still holds {backlog} undelivered "
                f"windows"))
    return out


def _hlc_of(entry: Mapping[str, object]) -> tuple[int, int, str]:
    h = entry.get("hlc")
    if not isinstance(h, Mapping):
        return (0, 0, "")
    return (int(h.get("phys_us", 0)),    # type: ignore[arg-type]
            int(h.get("logical", 0)),    # type: ignore[arg-type]
            str(h.get("node", "")))


def _op_evidence(op: Mapping[str, object],
                 entry: Mapping[str, object]) -> bool:
    """Does one journal event witness one schedule op?"""
    kind = str(entry.get("kind", ""))
    fields = entry.get("fields")
    fields = fields if isinstance(fields, Mapping) else {}
    peer = str(op.get("peer", ""))
    epoch_before = int(op.get("epoch_before", 0))  # type: ignore[arg-type]
    name = str(op.get("op", ""))
    if name == "autoscale":
        return (kind == "autoscale.enact"
                and int(fields.get("epoch", 0)) > epoch_before)  # type: ignore[arg-type]
    if kind != "membership.apply":
        return False
    peers = fields.get("peers")
    peers = list(peers) if isinstance(peers, (list, tuple)) else []
    epoch = int(fields.get("epoch", 0))  # type: ignore[arg-type]
    if name == "kill":
        # the survivors' succession apply: peer gone, epoch advanced
        return peer not in peers and epoch > epoch_before
    if name in ("restart", "join"):
        return peer in peers
    if name == "leave":
        return peer not in peers and epoch > epoch_before
    return False


def check_journal_vs_schedule(rec: RunRecord) -> list[Violation]:
    """Invariant 6: merged-journal completeness against the conductor's
    ground-truth op log, per-node HLC monotonicity, and no HLC stamp
    that predates the virtual-clock time of the op it witnesses."""
    out: list[Violation] = []
    merged: list[Mapping[str, object]] = []
    for inc in sorted(rec.journals):
        entries = list(rec.journals[inc])
        merged.extend(entries)
        # (a) strictly HLC-increasing within one incarnation's journal
        for prev, cur in zip(entries, entries[1:]):
            if _hlc_of(cur) <= _hlc_of(prev):
                out.append(Violation(
                    "journal",
                    f"{inc}: journal not strictly HLC-increasing at "
                    f"{_hlc_of(prev)} -> {_hlc_of(cur)}"))
    if not rec.schedule_ops:
        return out
    if not merged:
        out.append(Violation(
            "journal",
            f"{len(rec.schedule_ops)} schedule op(s) with certain "
            f"effects but the merged journal is empty"))
        return out
    for op in rec.schedule_ops:
        t_us = int(op.get("t_us", 0))  # type: ignore[arg-type]
        witnesses = [e for e in merged if _op_evidence(op, e)]
        label = (f"op={op.get('op')} peer={op.get('peer')} "
                 f"t_us={t_us} epoch_before={op.get('epoch_before')}")
        if not witnesses:
            out.append(Violation(
                "journal",
                f"schedule {label}: no witnessing event in the merged "
                f"journal"))
            continue
        # (b) causal order vs the conductor's virtual clock: at least
        # one witness must be stamped AT or AFTER the op happened — a
        # journal whose every witness precedes its cause is lying
        if all(_hlc_of(e)[0] < t_us for e in witnesses):
            stamps = sorted(_hlc_of(e)[0] for e in witnesses)
            out.append(Violation(
                "journal",
                f"schedule {label}: every witnessing event is stamped "
                f"before the op's virtual time ({stamps[-1]} < {t_us})"))
    return out


def check_all(rec: RunRecord) -> list[Violation]:
    return (check_conservation(rec)
            + check_no_fabricated_loss(rec)
            + check_no_duplicates(rec)
            + check_ladder(rec)
            + check_convergence(rec)
            + check_journal_vs_schedule(rec))
