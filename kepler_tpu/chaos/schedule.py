"""kepchaos schedule grammar: randomized, time-phased fault schedules.

A :class:`Schedule` is a flat, ordered list of :class:`ChaosEvent`
entries, each pinned to a *window index* on the conductor's virtual
clock. Two event families share the grammar:

- **fault** events compile onto the existing :class:`FaultSpec`
  machinery (``kepler_tpu.fault``) with ``start``/``duration`` expressed
  in virtual seconds, so the same injection points the hand-written
  chaos tests use are exercised — nothing is mocked around them;
- **op** events (``kill``/``restart``/``join``/``leave``/
  ``autoscale_up``/``autoscale_down``) are executed by the conductor
  against the in-process fleet (replica teardown, ``POST
  /v1/membership`` traffic, autoscale enactment).

Everything is derived from ``(seed, index)`` through one
``random.Random`` — no wall clock, no process entropy — so
``generate(seed, index)`` is a pure function and a failing schedule is
a two-integer repro key. Shrinking (:func:`ddmin`) minimizes a failing
schedule to a subsequence of its events by classic delta-debugging.

Only *deterministic-under-virtual-time* fault sites enter the generator
pool; sites whose observable effect couples to the wall clock (real
``time.sleep``, watchdog races) or that sit off the composed fleet
surface (node-local spool/telemetry paths) are listed in
``EXCLUDED_SITES`` with the reason, and a fence test asserts the pool
and the exclusions exactly partition ``KNOWN_SITES``.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Mapping, Sequence

from kepler_tpu.fault import KNOWN_SITES, FaultSpec

# Sites the generator draws from: deterministic effect under the
# conductor's virtual clock, consulted on the composed fleet surface.
FAULT_POOL: tuple[str, ...] = (
    "device.read_error",
    "net.refuse",
    "net.corrupt_body",
    "report.clock_skew",
    "device.dispatch_error",
    "device.compile_error",
    "device.oom_on_grow",
    "net.partition",
    "replica.down",
    "net.throttle",
)

# Excluded from randomized schedules — site -> reason. Kept exhaustive
# against KNOWN_SITES by tests/test_fault_fence.py so a new site must be
# either scheduled or explicitly excluded here.
EXCLUDED_SITES: dict[str, str] = {
    "net.slow": "real agent-side sleep; delivery latency couples to the "
                "wall clock, breaking bit-identical replay",
    "aggregator.ingest_slow": "real time.sleep in ingest; the admission "
                              "latency EWMA it drives is wall-clock fed",
    "device.stall": "demotion depends on the real dispatch-watchdog "
                    "race, not the virtual clock",
    "device.counter_wrap": "consulted in the node monitor's sysfs read "
                           "path, below the wire surface this harness "
                           "drives",
    "disk.write_error": "spool runs on the node agent's disk path, not "
                        "in the in-process fleet",
    "disk.fsync_error": "spool runs on the node agent's disk path, not "
                        "in the in-process fleet",
    "disk.torn_tail": "spool runs on the node agent's disk path, not "
                      "in the in-process fleet",
    "telemetry.drop": "telemetry span ring lives in the node process, "
                      "off the fleet surface",
}

OP_KINDS: tuple[str, ...] = (
    "kill", "restart", "join", "leave", "autoscale_up", "autoscale_down")


@dataclass(frozen=True)
class ChaosEvent:
    """One schedule entry. ``at`` is a 0-based window index; fault
    events stay armed for ``windows`` windows, op events execute once
    at the top of window ``at``."""

    at: int
    kind: str               # "fault" or one of OP_KINDS
    site: str = ""          # fault events only
    target: str = ""        # op events: the peer acted on
    windows: int = 1        # fault events: armed duration in windows
    count: int | None = None
    probability: float = 1.0
    arg: float | None = None

    def __post_init__(self) -> None:
        if self.kind == "fault":
            if self.site not in KNOWN_SITES:
                raise ValueError(f"unknown fault site {self.site!r}")
        elif self.kind not in OP_KINDS:
            raise ValueError(f"unknown event kind {self.kind!r}")
        if self.at < 0:
            raise ValueError("event window index must be >= 0")
        if self.windows < 1:
            raise ValueError("fault duration must be >= 1 window")

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"at": self.at, "kind": self.kind}
        if self.site:
            out["site"] = self.site
        if self.target:
            out["target"] = self.target
        if self.windows != 1:
            out["windows"] = self.windows
        if self.count is not None:
            out["count"] = self.count
        if self.probability != 1.0:
            out["probability"] = self.probability
        if self.arg is not None:
            out["arg"] = self.arg
        return out

    @classmethod
    def from_dict(cls, raw: Mapping[str, Any]) -> "ChaosEvent":
        allowed = {"at", "kind", "site", "target", "windows", "count",
                   "probability", "arg"}
        unknown = set(raw) - allowed
        if unknown:
            raise ValueError(f"chaos event has unknown keys "
                             f"{sorted(unknown)}")
        return cls(
            at=int(raw["at"]), kind=str(raw["kind"]),
            site=str(raw.get("site", "")),
            target=str(raw.get("target", "")),
            windows=int(raw.get("windows", 1)),
            count=(None if raw.get("count") is None
                   else int(raw["count"])),
            probability=float(raw.get("probability", 1.0)),
            arg=(None if raw.get("arg") is None else float(raw["arg"])))


@dataclass(frozen=True)
class Schedule:
    """A generated (or replayed) fault schedule, keyed by
    ``(seed, index)``. ``keep`` records which original event indices
    survived shrinking — empty means the full schedule."""

    seed: int
    index: int
    events: tuple[ChaosEvent, ...]
    keep: tuple[int, ...] = field(default=())

    def subset(self, keep: Sequence[int]) -> "Schedule":
        keep_t = tuple(sorted(set(int(k) for k in keep)))
        if any(k < 0 or k >= len(self.events) for k in keep_t):
            raise ValueError("keep index out of range")
        return Schedule(seed=self.seed, index=self.index,
                        events=tuple(self.events[k] for k in keep_t),
                        keep=keep_t)

    def to_json(self) -> str:
        out: dict[str, Any] = {
            "seed": self.seed, "index": self.index,
            "events": [e.to_dict() for e in self.events]}
        if self.keep:
            out["keep"] = list(self.keep)
        return json.dumps(out, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "Schedule":
        raw = json.loads(text)
        sched = cls(seed=int(raw["seed"]), index=int(raw["index"]),
                    events=tuple(ChaosEvent.from_dict(e)
                                 for e in raw.get("events", [])),
                    keep=tuple(int(k) for k in raw.get("keep", [])))
        return sched


# sites that demote the device-window ladder: capped per schedule so a
# fixed cooldown always re-promotes to the top rung before convergence
# is judged (probe back-off doubles on failed retries, so unbounded
# stacks could out-run any constant K)
LADDER_SITES: frozenset[str] = frozenset({
    "device.dispatch_error", "device.compile_error",
    "device.oom_on_grow"})
MAX_LADDER_EVENTS = 2


def _fault_event(rng: random.Random, horizon: int,
                 ladder_left: int) -> ChaosEvent:
    site = rng.choice(FAULT_POOL)
    if site in LADDER_SITES and ladder_left <= 0:
        site = rng.choice(tuple(s for s in FAULT_POOL
                                if s not in LADDER_SITES))
    at = rng.randrange(max(1, horizon))
    windows = rng.randint(1, 3)
    probability = rng.choice((1.0, 1.0, 1.0, 0.5))
    arg: float | None = None
    count: int | None
    if site in LADDER_SITES:
        count = 1           # one demotion per event, shallow walks
        probability = 1.0
    elif site == "device.read_error":
        count = rng.randint(1, 2)
        arg = float(rng.randrange(4))       # which zone to mask
    else:
        count = rng.randint(1, 3)
        if site == "report.clock_skew":
            # well past the 120 s tolerance, both directions
            arg = rng.choice((300.0, -300.0))
        elif site == "net.throttle":
            arg = 1.0                       # Retry-After seconds
    return ChaosEvent(at=at, kind="fault", site=site, windows=windows,
                      count=count, probability=probability, arg=arg)


def _op_event(rng: random.Random, horizon: int, members: Sequence[str],
              standbys: Sequence[str]) -> list[ChaosEvent]:
    kind = rng.choice(OP_KINDS)
    everyone = list(members) + list(standbys)
    out: list[ChaosEvent] = []
    if kind in ("autoscale_up", "autoscale_down"):
        out.append(ChaosEvent(at=rng.randrange(max(1, horizon)),
                              kind=kind))
    elif kind in ("kill", "leave"):
        at = rng.randrange(max(1, horizon))
        target = rng.choice(list(members))
        out.append(ChaosEvent(at=at, kind=kind, target=target))
        # usually bring the peer back so schedules stay productive —
        # the executor no-ops a restart/join of a live member
        if rng.random() < 0.75:
            back = "restart" if kind == "kill" else "join"
            out.append(ChaosEvent(at=at + rng.randint(2, 4), kind=back,
                                  target=target))
    else:  # restart / join of anyone (live ones no-op at runtime)
        out.append(ChaosEvent(at=rng.randrange(max(1, horizon)),
                              kind=kind, target=rng.choice(everyone)))
    return out


def generate(seed: int, index: int, *, horizon: int,
             members: Sequence[str], standbys: Sequence[str],
             min_events: int = 3, max_events: int = 8) -> Schedule:
    """Pure function ``(seed, index) -> Schedule``: every draw comes
    from one ``random.Random(seed * 1_000_003 + index)``, so the key
    alone replays the schedule on any host (no string hashing — CPython
    salts ``hash(str)`` per process)."""
    rng = random.Random(seed * 1_000_003 + index)
    n = rng.randint(min_events, max_events)
    events: list[ChaosEvent] = []
    while len(events) < n:
        if rng.random() < 0.7:
            ladder_used = sum(1 for e in events if e.site in LADDER_SITES)
            events.append(_fault_event(
                rng, horizon, MAX_LADDER_EVENTS - ladder_used))
        else:
            events.extend(_op_event(rng, horizon, members, standbys))
    events.sort(key=lambda e: (e.at, e.kind, e.site, e.target))
    return Schedule(seed=seed, index=index, events=tuple(events))


def compile_fault_specs(events: Iterable[ChaosEvent],
                        interval: float) -> list[FaultSpec]:
    """Lower fault events onto ``FaultSpec`` windows in virtual seconds.

    The conductor arms the plan at virtual t0 and advances the clock by
    ``interval`` before processing window ``w`` (1-based), so elapsed
    time at window ``w`` is ``w * interval``; an event at 0-based index
    ``a`` targeting windows ``a+1 .. a+windows`` therefore opens at
    ``(a + 0.5) * interval``."""
    specs: list[FaultSpec] = []
    for ev in events:
        if ev.kind != "fault":
            continue
        specs.append(FaultSpec(
            site=ev.site, probability=ev.probability, count=ev.count,
            start=(ev.at + 0.5) * interval,
            duration=ev.windows * interval, arg=ev.arg))
    return specs


def ddmin(indices: Sequence[int],
          fails: Callable[[Sequence[int]], bool]) -> tuple[int, ...]:
    """Classic delta debugging over event indices: returns a minimal
    (1-minimal) subsequence for which ``fails`` still holds. ``fails``
    must hold for the full ``indices``."""
    work = list(indices)
    if not fails(work):
        raise ValueError("ddmin precondition: full set must fail")
    granularity = 2
    while len(work) >= 2:
        size = len(work) // granularity
        chunks = [work[i:i + size]
                  for i in range(0, len(work), size)] if size else [work]
        reduced = False
        for i, chunk in enumerate(chunks):
            if fails(chunk):                    # subset reproduces
                work = list(chunk)
                granularity = 2
                reduced = True
                break
            complement = [x for j, c in enumerate(chunks) if j != i
                          for x in c]
            if complement and fails(complement):  # complement reproduces
                work = complement
                granularity = max(granularity - 1, 2)
                reduced = True
                break
        if not reduced:
            if granularity >= len(work):
                break
            granularity = min(len(work), granularity * 2)
    return tuple(work)
