"""Build/version metadata.

Reference parity: ``internal/version/version.go:27`` exposes ldflags-injected
version/buildTime/branch/commit via ``version.Info()``. Here the same fields
are module attributes, optionally overridden at package-build time.
"""

from __future__ import annotations

import platform
from dataclasses import dataclass

__version__ = "0.1.0"

# Populated by the build (analog of Go ldflags -X injection, Makefile:45-49).
BUILD_TIME = "unknown"
GIT_BRANCH = "unknown"
GIT_COMMIT = "unknown"


@dataclass(frozen=True)
class VersionInfo:
    version: str
    build_time: str
    git_branch: str
    git_commit: str
    python_version: str
    platform: str


def info() -> VersionInfo:
    """Return structured version info (reference ``version.Info()``)."""
    return VersionInfo(
        version=__version__,
        build_time=BUILD_TIME,
        git_branch=GIT_BRANCH,
        git_commit=GIT_COMMIT,
        python_version=platform.python_version(),
        platform=f"{platform.system()}/{platform.machine()}",
    )
