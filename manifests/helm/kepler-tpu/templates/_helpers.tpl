{{- define "kepler-tpu.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}

{{- define "kepler-tpu.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag | default .Chart.AppVersion }}
{{- end }}
