#!/usr/bin/env python3
"""`make blackbox`: a 2-replica kill + succession + rejoin scenario on
the chaos harness's virtual clock, reconstructed through the REAL
black-box pipeline — per-incarnation journals and live `/debug/bundle`
documents written to disk, merged by `python -m kepler_tpu.blackbox`.

Proves the reconstruction contract end to end:

- the merged timeline NAMES the succession (a membership apply that
  excludes the dead peer at a bumped epoch, then a re-join apply that
  readmits it, in causally-consistent HLC order), and
- the CLI is bit-deterministic: the same bundles — in any source
  order — produce byte-identical canonical JSON and one SHA-256.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _check(cond: bool, what: str) -> None:
    if not cond:
        print(f"FAIL: {what}", file=sys.stderr)
        sys.exit(1)


def _cli(args: list[str]) -> str:
    from kepler_tpu.blackbox.__main__ import main as blackbox_main

    raw = io.BytesIO()
    out = io.TextIOWrapper(raw, encoding="utf-8")   # --format json
    with contextlib.redirect_stdout(out):           # writes to .buffer
        code = blackbox_main(args)
        out.flush()
    _check(code == 0, f"blackbox CLI exited {code} for {args}")
    return raw.getvalue().decode()


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from kepler_tpu.blackbox import analyze, merge_events
    from kepler_tpu.chaos.harness import ChaosConfig, ChaosFleet
    from kepler_tpu.chaos.trace import Trace
    from kepler_tpu.fleet.journal import canonical_json

    cfg = ChaosConfig(replicas=2, standbys=0, agents=0, workloads=1)
    fleet = ChaosFleet(cfg, Trace())
    try:
        victim, survivor = fleet.members0
        step = cfg.interval

        fleet.ticks[0] += step
        _check(fleet.kill(victim), f"kill {victim}")
        fleet.ticks[0] += step
        fleet.succession_tick()            # survivor demotes the corpse
        fleet.ticks[0] += step
        _check(fleet.restart(victim), f"restart {victim}")
        fleet.ticks[0] += step

        with tempfile.TemporaryDirectory() as tmp:
            sources: list[str] = []
            # the dead incarnation's journal, snapshotted at kill time
            # (what an operator recovers from the crashed host's spool)
            for inc, events in sorted(fleet.retired_journals.items()):
                path = os.path.join(tmp, inc.replace(":", "_") + ".json")
                with open(path, "w") as f:
                    json.dump(list(events), f)
                sources.append(path)
            # live replicas: the real incident-bundle documents
            for peer in sorted(fleet.alive):
                bundle = fleet.aggs[peer].bundle()
                path = os.path.join(
                    tmp, peer.replace(":", "_") + ".bundle.json")
                with open(path, "wb") as f:
                    f.write(canonical_json(bundle) + b"\n")
                sources.append(path)
            _check(len(sources) == 3,
                   f"3 sources (1 retired + 2 live), got {len(sources)}")

            # -- the merged timeline names the succession -----------------
            journals = []
            for src in sources:
                from kepler_tpu.blackbox import load_source
                journals.extend(load_source(src))
            merged = merge_events(journals)
            _check(merged, "merged timeline is non-empty")
            keys = [(e["hlc"]["phys_us"], e["hlc"]["logical"],
                     e["hlc"]["node"]) for e in merged]
            _check(keys == sorted(keys), "timeline is in HLC order")

            applies = [e for e in merged
                       if e["kind"] == "membership.apply"]
            succession = [e for e in applies
                          if victim not in e["fields"]["peers"]
                          and e["fields"]["epoch"] > 1]
            _check(succession, "succession apply excludes the victim")
            rejoin = [e for e in applies
                      if victim in e["fields"]["peers"]
                      and e["fields"]["epoch"]
                      > succession[0]["fields"]["epoch"]]
            _check(rejoin, "re-join apply readmits the victim")
            _check(merged.index(succession[0]) < merged.index(rejoin[0]),
                   "succession precedes re-join causally")
            adopts = [e for e in merged if e["kind"] == "lease.adopt"]
            _check(any(e["fields"]["holder"] == survivor
                       for e in adopts),
                   f"lease adoption names the survivor {survivor}")
            brains = [f for f in analyze(merged)
                      if f["finding"].startswith("split_brain")]
            _check(not brains, f"no split-brain findings: {brains}")

            # -- bit-determinism: same bundles -> one SHA-256 -------------
            sha_fwd = _cli(sources + ["--sha"]).strip()
            sha_rev = _cli(list(reversed(sources)) + ["--sha"]).strip()
            _check(len(sha_fwd) == 64, f"sha shape {sha_fwd!r}")
            _check(sha_fwd == sha_rev,
                   f"source order changed the timeline: "
                   f"{sha_fwd} != {sha_rev}")
            json_fwd = _cli(sources + ["--format", "json"])
            json_rev = _cli(list(reversed(sources)) + ["--format",
                                                       "json"])
            _check(json_fwd == json_rev, "canonical JSON not "
                                         "byte-identical across runs")
            n_events = len(json.loads(json_fwd)["events"])
            _check(n_events == len(merged),
                   f"CLI merged {n_events} events, library {len(merged)}")

        print(f"blackbox smoke OK: events={len(merged)} "
              f"succession_epoch={succession[0]['fields']['epoch']} "
              f"rejoin_epoch={rejoin[0]['fields']['epoch']} "
              f"sha={sha_fwd[:16]}")
        return 0
    finally:
        fleet.shutdown()


if __name__ == "__main__":
    sys.exit(main())
