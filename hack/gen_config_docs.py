#!/usr/bin/env python3
"""Generate ``docs/user/configuration.md`` from the live config schema.

The reference ships a hand-written option catalog
(``docs/user/configuration.md`` upstream); here the catalog is GENERATED
the same way ``hack/gen_metric_docs.py`` generates the metrics doc: walk
the ``Config`` dataclass tree for every key, default, and type; pull the
flag spellings out of the real argparse registration; and render the
user-facing reference. Teeth:

  * every config leaf MUST have a description below — adding a field
    without documenting it fails the generator (and the freshness test);
  * every registered CLI flag must be mentioned — a flag the doc doesn't
    know about fails the generator.

Usage:  python hack/gen_config_docs.py [--check]
  --check   exit 1 if docs/user/configuration.md is stale (CI mode).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kepler_tpu.config.config import (  # noqa: E402
    _CANONICAL_YAML_KEYS,
    default_config,
    register_flags,
)
from kepler_tpu.config.level import Level  # noqa: E402

OUT_PATH = os.path.join(REPO, "docs", "user", "configuration.md")

# one description per leaf (dotted snake_case path). The generator fails
# on any undocumented field, so this dict can never silently lag the
# schema.
DESCRIPTIONS = {
    "log.level": "Log verbosity: `debug`, `info`, `warn`, `error`.",
    "log.format": "Log output format: `text` or `json`.",
    "host.sysfs": "Sysfs mount point (RAPL zones are discovered under "
                  "`<sysfs>/class/powercap`).",
    "host.procfs": "Procfs mount point (process scan, `/proc/stat` usage "
                   "ratio, cpuinfo).",
    "monitor.interval": "Refresh interval for the attribution loop "
                        "(Go-style duration; reference default 5s).",
    "monitor.staleness": "Snapshot freshness window: a scrape older than "
                         "this triggers a refresh; two scrapes inside it "
                         "see identical data (HA Prometheus pairs).",
    "monitor.max_terminated": "Terminated workloads kept for export, "
                              "top-N by primary-zone energy; 0 disables "
                              "tracking, negative is unbounded.",
    "monitor.min_terminated_energy_threshold":
        "Joules a terminated workload must have consumed to be tracked.",
    "monitor.stall_after": "Watchdog threshold: a refresh loop silent "
                           "longer than this is flagged stalled and the "
                           "snapshot marked stale on `/healthz` "
                           "(`0` = auto, 3 × `monitor.interval`).",
    "monitor.state_path": "Counter-state file (atomic-rename JSON): the "
                          "last raw RAPL/TPU readings survive a restart "
                          "so the first window attributes the energy "
                          "consumed across it instead of reseeding "
                          "(empty disables).",
    "monitor.state_max_age": "Freshness bound on the restored counter "
                             "state: an older state file is ignored with "
                             "a warning (a stale baseline would "
                             "misattribute long-dead energy; `0` = no "
                             "bound).",
    "rapl.zones": "Zone-name filter (e.g. `[package, dram]`); empty "
                  "means every discovered zone.",
    "msr.enabled": "Opt-in MSR fallback: read RAPL counters from "
                   "`/dev/cpu/*/msr` when powercap is unavailable. "
                   "SECURITY: MSR reads enable PLATYPUS-class side "
                   "channels (CVE-2020-8694/95) — deliberately YAML-only, "
                   "no CLI flag.",
    "msr.force": "Use the MSR meter even when powercap works (testing "
                 "only).",
    "msr.device_path": "MSR device tree (mounted as `host/dev/cpu` in "
                       "containers).",
    "exporter.stdout.enabled": "Periodic node-power table on stdout "
                               "(logs move to stderr).",
    "exporter.prometheus.enabled": "Serve `/metrics` on the API server.",
    "exporter.prometheus.debug_collectors":
        "Extra runtime collectors (`go` = python runtime analog of the "
        "reference's Go collector set).",
    "exporter.prometheus.metrics_level":
        "Bitmask of exported families: any of `node`, `process`, "
        "`container`, `vm`, `pod` (cumulative `--metrics` flag).",
    "web.config_file": "exporter-toolkit-style web config (TLS, basic "
                       "auth) applied to every listener.",
    "web.listen_addresses": "API server listen addresses (repeatable "
                            "`--web.listen-address`).",
    "web.max_connections": "Concurrent-connection cap per listener: an "
                           "accept over the cap is answered `503 + "
                           "Connection: close` immediately, with NO "
                           "handler thread spawned — a connection "
                           "storm can't grow threads without bound "
                           "(`0` = unbounded).",
    "debug.pprof.enabled": "Mount the pprof-style debug service "
                           "(`/debug/pprof/`: stacks, profile, JAX "
                           "trace).",
    "kube.enabled": "Enable the pod informer (node-filtered LIST+WATCH) "
                    "so containers resolve to pods.",
    "kube.config": "Kubeconfig path; empty uses in-cluster service "
                   "account.",
    "kube.node_name": "This node's name (the informer watch filters "
                      "`spec.nodeName`; also the `node_name` metric "
                      "label).",
    "tpu.platform": "Device selection for the attribution program: "
                    "`auto`, `tpu`, or `cpu`.",
    "tpu.workload_bucket": "Workload-axis padding bucket — ragged "
                           "workload counts round up to a multiple so "
                           "the jit cache sees O(buckets) shapes.",
    "tpu.node_bucket": "Node-axis padding bucket for the fleet batch "
                       "(rounded up to the mesh size).",
    "tpu.mesh_shape": "Device mesh shape for the aggregator program "
                      "(empty = all visible devices, 1-D).",
    "tpu.mesh_axes": "Mesh axis names (the node axis shards the fleet).",
    "tpu.fleet_backend": "Attribution contraction backend: `einsum` "
                         "(XLA-fused) or `pallas` (hand-written Mosaic "
                         "kernel).",
    "tpu.compilation_cache_dir": "Persistent XLA compilation cache "
                                 "directory (empty = off): "
                                 "bucket-crossing and restart compiles "
                                 "become disk hits.",
    "aggregator.enabled": "Run the cluster-aggregator role (ingest node "
                          "reports, batched fleet attribution).",
    "aggregator.listen_address": "Aggregator API listen address.",
    "aggregator.endpoint": "Agent role: aggregator base URL to POST "
                           "window reports to (empty disables the "
                           "agent).",
    "aggregator.tls_skip_verify": "Agent: skip TLS certificate "
                                  "verification toward the aggregator.",
    "aggregator.interval": "Fleet attribution cadence (duration).",
    "aggregator.stale_after": "A node whose newest report is older than "
                              "this falls out of the batch (duration).",
    "aggregator.model": "Estimator family serving non-RAPL nodes: "
                        "`linear`, `mlp`, `moe`, `deep`, `temporal` "
                        "(empty = ratio-only).",
    "aggregator.params_path": "Trained estimator params (`.npz` from "
                              "`kepler-tpu-train`); empty serves "
                              "untrained initialization with a warning.",
    "aggregator.accuracy_mode": "Serve estimators at f32/highest matmul "
                                "precision (the configuration validated "
                                "to ≤0.5% error) instead of bf16 "
                                "throughput mode.",
    "aggregator.history_window": "Temporal model: feature-history ticks "
                                 "kept per workload.",
    "aggregator.training_dump_dir": "Capture RAPL nodes' windows + ratio "
                                    "watts as training files for "
                                    "`kepler-tpu-train` (empty "
                                    "disables).",
    "aggregator.training_dump_max_files": "Training-dump retention: "
                                          "oldest files beyond this are "
                                          "pruned.",
    "aggregator.node_mode": "Agent: report as a `ratio` (RAPL ground "
                            "truth) or `model` (estimator-served) node.",
    "aggregator.backoff_initial": "Agent: initial send-retry backoff "
                                  "(exponential, jittered).",
    "aggregator.backoff_max": "Agent: send-retry backoff ceiling.",
    "aggregator.breaker_threshold": "Agent: consecutive send failures "
                                    "that open the circuit breaker "
                                    "(sends are shed while open).",
    "aggregator.breaker_cooldown": "Agent: breaker cooldown before a "
                                   "half-open probe (doubles per failed "
                                   "probe, capped).",
    "aggregator.flush_timeout": "Agent: bound on the best-effort flush "
                                "of queued reports during shutdown "
                                "(a clean drain delivers its final "
                                "window).",
    "aggregator.skew_tolerance": "Aggregator: quarantine reports whose "
                                 "sender clock is skewed beyond this "
                                 "(`0` disables the check).",
    "aggregator.degraded_ttl": "Aggregator: how long a node stays marked "
                               "degraded on `/healthz` after its last "
                               "quarantined report.",
    "aggregator.dedup_window": "Aggregator: per-node `(run, seq)` dedup "
                               "window — redelivered reports (spool "
                               "replay, retries) are absorbed "
                               "idempotently; seq jumps beyond it count "
                               "as `kepler_fleet_windows_lost_total`.",
    "aggregator.pipeline_depth": "Aggregator: in-flight fleet windows. "
                                 "`1` = serial assemble→dispatch→fetch; "
                                 "`2` (default) overlaps window N's "
                                 "fetch/scatter behind window N+1's "
                                 "assembly+dispatch — results are at "
                                 "most `pipelineDepth−1` intervals "
                                 "stale; shutdown drains in-flight "
                                 "windows deterministically.",
    "aggregator.fused_window_k": "Aggregator: intervals batched into "
                                 "one fused device scan at rung 0's top "
                                 "tier. `1` (default) = unfused "
                                 "per-window dispatch; `K>1` stages "
                                 "delta rows host-side and pays the "
                                 "host↔device sync once per K windows "
                                 "(one `lax.scan` dispatch + one "
                                 "batched fetch) — results are at most "
                                 "`fusedWindowK−1` intervals stale. See "
                                 "observability.md \"Fused window "
                                 "loop\".",
    "aggregator.bucket_shrink_after": "Aggregator: consecutive windows "
                                      "at under half bucket occupancy "
                                      "before a padded batch bucket "
                                      "shrinks one geometric step "
                                      "(growth is immediate; hysteresis "
                                      "prevents recompile thrash at a "
                                      "bucket edge).",
    "aggregator.fallback_enabled": "Aggregator: demote the window's "
                                   "device leg down the degradation "
                                   "ladder (packed pipelined → packed "
                                   "serial → einsum-f32 serial → "
                                   "pure-NumPy host) on any device "
                                   "failure instead of crashing the "
                                   "aggregation loop.",
    "aggregator.repromote_after": "Aggregator: consecutive clean windows "
                                  "at a demoted ladder rung before the "
                                  "rung above is retried (hysteresis, "
                                  "like the breaker's half-open probe).",
    "aggregator.dispatch_timeout": "Aggregator: stall watchdog on the "
                                   "window fetch — a dispatch that "
                                   "hasn't produced output within this "
                                   "bound demotes the ladder instead of "
                                   "wedging the loop (`0` disables).",
    "aggregator.mesh_shape": "Device mesh shape for the fleet window "
                             "path (`[]` = every device on a 1-D node "
                             "axis). With > 1 device on a 1-D node "
                             "mesh the packed window runs SHARDED: "
                             "per-shard resident rings, per-shard "
                             "delta H2D, sticky node→shard assignment.",
    "aggregator.mesh_axes": "Mesh axis names for the fleet window path; "
                            "must lead with `node` (the axis the fleet "
                            "batch shards over).",
    "aggregator.scoreboard_cap": "Fleet scoreboard LRU cap: per-node "
                                 "health rows kept (bounds memory AND "
                                 "`kepler_fleet_node_state` "
                                 "cardinality; least-recently-updated "
                                 "node evicted beyond it).",
    "aggregator.anomaly_z": "Rolling z-score threshold flagging a "
                            "node's self-reported power as anomalous "
                            "on the scoreboard (`0` disables the "
                            "flag).",
    "aggregator.peers": "HA ingest ring: every replica's dialable "
                        "endpoint (the SAME list on every replica and "
                        "agent). Each replica accepts only the nodes "
                        "the consistent-hash ring assigns it and "
                        "answers the rest with a `421 + owner + epoch` "
                        "redirect agents follow. Empty = "
                        "single-replica ingest.",
    "aggregator.self_peer": "Which `aggregator.peers` entry THIS "
                            "replica is (replica role only; agents "
                            "leave it empty).",
    "aggregator.ring_epoch": "Ingest-ring membership epoch — bump it "
                             "when rolling out a changed peers list so "
                             "agents re-resolve ownership (monotonic, "
                             ">= 1).",
    "aggregator.ring_vnodes": "Virtual nodes per ring peer: ownership "
                              "granularity (higher = smoother "
                              "distribution, slower ring build).",
    "aggregator.admission_enabled": "Ingest admission control: shed "
                                    "with `429 + Retry-After` BEFORE "
                                    "decode work when the inflight or "
                                    "latency budget is blown — "
                                    "priority-aware (replay backlogs "
                                    "first, live RAPL ground truth "
                                    "last). Loss-free: shed records "
                                    "stay spooled on the agent and "
                                    "replay later.",
    "aggregator.admission_max_inflight": "Inflight-ingest budget: "
                                         "admitted requests being "
                                         "decoded/merged concurrently "
                                         "before the shed ladder "
                                         "engages.",
    "aggregator.admission_latency_budget": "Per-record ingest service-"
                                           "time budget (EWMA) the "
                                           "shed ladder is scaled "
                                           "against (`0` disables the "
                                           "latency signal).",
    "aggregator.admission_retry_after": "Base `Retry-After` answered "
                                        "on a shed; multiplied by the "
                                        "measured load and jittered "
                                        "±50% so a throttled herd "
                                        "doesn't re-arrive in phase.",
    "aggregator.admission_retry_after_max": "Clamp on the shed "
                                            "`Retry-After` — the "
                                            "longest an agent is ever "
                                            "asked to stay away.",
    "aggregator.multihost.enabled":
        "Multi-host SPMD fleet window: join a `jax.distributed` cluster "
        "and run rung 0 over every host's devices — host-local donated "
        "rings and delta H2D, ONE SPMD dispatch, owned-rows publish "
        "fetch, and (with `aggregator.peers` set) ingest ownership "
        "derived from the mesh shard map so each replica ingests "
        "exactly the agents whose rows live on its local devices.",
    "aggregator.multihost.coordinator":
        "`jax.distributed` coordinator address (empty = "
        "`JAX_COORDINATOR_ADDRESS`, the TPU pod runtime convention).",
    "aggregator.multihost.num_processes":
        "Process count of the multi-host job (`-1` = "
        "`JAX_NUM_PROCESSES`). With `aggregator.peers` set, the peer "
        "list must carry one endpoint per process in process-index "
        "order.",
    "aggregator.multihost.process_id":
        "This process's id in the multi-host job (`-1` = "
        "`JAX_PROCESS_ID`).",
    "aggregator.multihost.init_timeout":
        "Bound on the coordinator join (duration; `0` = jax's default "
        "deadline). An unreachable coordinator surfaces as the distinct "
        "`coordinator_unreachable` failure reason in the log and the "
        "`fleet-window` health probe — never a generic decline.",
    "aggregator.multihost.takeover":
        "On a mesh demotion (\"mesh minus one host\"), heal the ring "
        "by DETERMINISTIC SUCCESSION at any mesh size: every survivor "
        "probes the peer set and computes the same entitled issuer "
        "(the incumbent lease holder while it survives, else the "
        "lowest surviving peer), so exactly ONE survivor bumps the "
        "epoch and broadcasts the survivor membership — displaced "
        "agents follow 421s and replay their spool tails. Disabled, "
        "survivors hold position \"degraded, awaiting membership\" "
        "until an operator `apply_membership`.",
    "aggregator.membership.auto_apply":
        "Let the lease holder ENACT membership changes the autoscale "
        "policy recommends (promote a standby, retire the "
        "highest-sorting peer). Off (the default), recommendations "
        "are surfaced only — logs, `/debug/ring`, and "
        "`kepler_fleet_autoscale_recommended_replicas` — and "
        "operator behavior is byte-for-byte unchanged.",
    "aggregator.membership.autoscale_enabled":
        "Feed each aggregation window's recorded signals (admission "
        "load, shed deltas, ingest-latency EWMA, scoreboard states) "
        "into the hysteresis autoscale policy. Pure function of the "
        "signal trace: replaying the same metrics reproduces the "
        "same decisions.",
    "aggregator.membership.scale_up_load":
        "Admission-load threshold at or above which a window counts "
        "toward the scale-up streak (any shed traffic in the window "
        "also counts).",
    "aggregator.membership.scale_down_load":
        "Admission-load threshold at or below which a window counts "
        "toward the scale-down streak (only with zero shed and zero "
        "flagged nodes). Must sit below `scaleUpLoad`; the gap is the "
        "hysteresis dead band, where both streaks are preserved.",
    "aggregator.membership.up_windows":
        "Consecutive overloaded windows required before a scale-up "
        "fires (the streak resets after firing).",
    "aggregator.membership.down_windows":
        "Consecutive idle windows required before a scale-down fires "
        "— deliberately slower than scale-up so diurnal troughs "
        "don't flap the fleet.",
    "aggregator.membership.min_replicas":
        "Floor the autoscale policy never recommends below.",
    "aggregator.membership.max_replicas":
        "Ceiling the autoscale policy never recommends above (`0` = "
        "one step above the current replica count).",
    "aggregator.membership.standby_peers":
        "Warm standby replica endpoints (repeatable) the lease holder "
        "may promote into the ring on an enacted scale-up; must not "
        "overlap `aggregator.peers`.",
    "aggregator.membership.probe_timeout":
        "Per-peer bound on the liveness probe (`GET /healthz`) behind "
        "succession and the autoscale live-node count (duration). ANY "
        "HTTP answer proves a listener; only transport failures read "
        "as death.",
    "aggregator.base_row_cache": "Wire-v2 delta-base LRU size: per-"
                                 "node last-keyframe state the delta "
                                 "frames merge against. Eviction "
                                 "costs the node one structured 409 "
                                 "needs-keyframe round-trip (it "
                                 "resends full), never data.",
    "agent.spool.dir": "Crash-safe report spool directory: windows are "
                       "appended (CRC-framed) before any send and only "
                       "acked on 2xx, so crashes/outages replay instead "
                       "of losing data (empty = in-memory ring only).",
    "agent.spool.max_bytes": "Spool byte cap; the oldest segment is "
                             "evicted beyond it and every unacked record "
                             "lost is counted "
                             "(`kepler_fleet_spool_evicted_total`).",
    "agent.spool.max_records": "Spool record cap (same eviction and "
                               "accounting as the byte cap).",
    "agent.spool.segment_bytes": "Spool segment rotation size — the "
                                 "granularity of cap eviction and of "
                                 "acked-data reclamation.",
    "agent.spool.fsync": "Spool durability policy: `batch` (default — "
                         "at most one fsync per `fsyncInterval`, none "
                         "on the per-send path), `always`, or `none`.",
    "agent.spool.fsync_interval": "Minimum spacing between batched spool "
                                  "fsyncs.",
    "agent.drain.batch_max": "Spooled records shipped per `/v1/reports` "
                             "request during recovery replay (`1` = "
                             "the single-record drain; per-record "
                             "status in the response keeps every "
                             "dedup/loss invariant record-grained).",
    "agent.drain.replay_rps": "Token-bucket cap on spool-replay "
                              "records/second, so a rejoining agent "
                              "slews its backlog in instead of dumping "
                              "it on a recovering replica (`0` = "
                              "unpaced).",
    "agent.drain.retry_after_max": "Clamp on any server-sent "
                                   "`Retry-After` the agent honors — "
                                   "an adversarial owner must not be "
                                   "able to park an agent forever.",
    "agent.wire.version": "Report wire format: `2` (default) = binary "
                          "delta-encoded v2 frames (struct-packed "
                          "header, changed workload rows only in "
                          "steady state); `1` pins the legacy "
                          "JSON-headered frames (rollout escape "
                          "hatch).",
    "agent.wire.keyframe_every": "Send a full keyframe every N windows "
                                 "even when a delta would do — bounds "
                                 "the state a fresh owner must request "
                                 "(409 needs-keyframe) after a "
                                 "hand-off.",
    "agent.wire.degraded_ttl": "How long a replica that answered "
                               "415/400 to v2 bytes is remembered as "
                               "v1-only before the agent re-probes v2 "
                               "(the wire-version analog of the batch "
                               "404/405 downgrade).",
    "service.restart_max": "Supervised restarts per crashing service "
                           "before the group fails (`0` = reference "
                           "semantics: first crash ends the group).",
    "service.restart_backoff_initial": "Initial supervised-restart "
                                       "backoff (exponential, jittered).",
    "service.restart_backoff_max": "Supervised-restart backoff ceiling.",
    "fault.enabled": "Arm the fault-injection plan at startup (YAML-only "
                     "chaos harness; see docs/developer/resilience.md).",
    "fault.seed": "Fault-plan RNG seed: the same seed replays the same "
                  "fault sequence.",
    "fault.specs": "Fault specs: mappings with a `site` "
                   "(e.g. `net.refuse`, `device.read_error`) plus "
                   "optional probability/count/skip/start/duration/arg.",
    "telemetry.enabled": "Self-telemetry plane: span tracing of the "
                         "monitor/exporter/fleet hot paths, "
                         "`kepler_self_*` metrics, and `/debug/traces`. "
                         "Disabled spans cost one global read per call "
                         "(see docs/developer/observability.md).",
    "telemetry.ring_size": "Complete cycle traces kept for "
                           "`/debug/traces`, per cycle name (newest "
                           "wins; per-name rings keep high-rate cycles "
                           "from evicting rare ones).",
    "telemetry.stage_buckets": "`kepler_self_stage_duration_seconds` "
                               "histogram bucket bounds in seconds "
                               "(empty = built-in defaults, 0.5ms–10s).",
    "telemetry.delivery_buckets": "`kepler_fleet_delivery_latency_"
                                  "seconds` histogram bucket bounds in "
                                  "seconds (empty = built-in defaults, "
                                  "10ms–6h — the tail reaches hours "
                                  "because spool replays carry outage "
                                  "durations).",
    "telemetry.journal.enabled": "Fleet black box: the HLC-stamped "
                                 "causal event journal behind "
                                 "`/debug/journal` and `/debug/bundle`, "
                                 "plus the `X-Kepler-HLC` clock "
                                 "piggyback on fleet wire exchanges. "
                                 "Disabled emission costs one global "
                                 "read per call (see "
                                 "docs/developer/observability.md).",
    "telemetry.journal.ring_size": "Journal events kept in memory "
                                   "(newest win) — the `/debug/journal` "
                                   "page and the bundle's journal "
                                   "section.",
    "telemetry.journal.dir": "Durable journal spool directory (empty = "
                             "ring only). CRC-framed `.kepj` files, one "
                             "per node, readable by "
                             "`python -m kepler_tpu.blackbox` after a "
                             "crash.",
    "telemetry.journal.max_bytes": "Durable spool cap per file; at the "
                                   "cap the file rotates once to "
                                   "`.kepj.1` (bounded disk, newest "
                                   "events always on disk).",
    "aggregator.hlc_max_drift": "HLC clamp: an inbound clock stamp may "
                                "advance this replica's clock at most "
                                "this far past local wall time. Clamped "
                                "stamps count in "
                                "`kepler_fleet_hlc_clamped_total`.",
    "dev.fake_cpu_meter.enabled": "Dev-only synthetic meter (YAML-only, "
                                  "never a flag — reference "
                                  "config.go:104,189).",
    "dev.fake_cpu_meter.zones": "Zone names the fake meter exposes "
                                "(empty = package/core/dram/uncore).",
}

# dotted path → CLI flag (only paths that HAVE flags; YAML-only settings
# simply aren't listed). Checked against the real parser below.
FLAG_OF = {
    "log.level": "--log.level",
    "log.format": "--log.format",
    "host.sysfs": "--host.sysfs",
    "host.procfs": "--host.procfs",
    "monitor.interval": "--monitor.interval",
    "monitor.max_terminated": "--monitor.max-terminated",
    "monitor.state_path": "--monitor.state-path",
    "debug.pprof.enabled": "--debug.pprof / --no-debug.pprof",
    "web.config_file": "--web.config-file",
    "web.listen_addresses": "--web.listen-address (repeatable)",
    "web.max_connections": "--web.max-connections",
    "exporter.stdout.enabled": "--exporter.stdout / --no-exporter.stdout",
    "exporter.prometheus.enabled":
        "--exporter.prometheus / --no-exporter.prometheus",
    "exporter.prometheus.metrics_level": "--metrics (cumulative)",
    "kube.enabled": "--kube.enable / --no-kube.enable",
    "kube.config": "--kube.config",
    "kube.node_name": "--kube.node-name",
    "aggregator.enabled": "--aggregator.enable / --no-aggregator.enable",
    "aggregator.listen_address": "--aggregator.listen-address",
    "aggregator.endpoint": "--aggregator.endpoint",
    "aggregator.tls_skip_verify": "--aggregator.tls-skip-verify",
    "aggregator.model": "--aggregator.model",
    "aggregator.params_path": "--aggregator.params-path",
    "aggregator.node_mode": "--aggregator.node-mode",
    "aggregator.accuracy_mode": "--aggregator.accuracy-mode",
    "aggregator.history_window": "--aggregator.history-window",
    "aggregator.training_dump_dir": "--aggregator.training-dump-dir",
    "aggregator.training_dump_max_files":
        "--aggregator.training-dump-max-files",
    "aggregator.dedup_window": "--aggregator.dedup-window",
    "aggregator.pipeline_depth": "--aggregator.pipeline-depth",
    "aggregator.fused_window_k": "--aggregator.fused-window-k",
    "aggregator.bucket_shrink_after": "--aggregator.bucket-shrink-after",
    "aggregator.fallback_enabled":
        "--aggregator.fallback-enabled / --no-aggregator.fallback-enabled",
    "aggregator.repromote_after": "--aggregator.repromote-after",
    "aggregator.dispatch_timeout": "--aggregator.dispatch-timeout",
    "aggregator.scoreboard_cap": "--aggregator.scoreboard-cap",
    "aggregator.anomaly_z": "--aggregator.anomaly-z",
    "aggregator.peers": "--aggregator.peers (repeatable)",
    "aggregator.self_peer": "--aggregator.self-peer",
    "aggregator.ring_epoch": "--aggregator.ring-epoch",
    "aggregator.ring_vnodes": "--aggregator.ring-vnodes",
    "aggregator.admission_enabled":
        "--aggregator.admission-enabled / "
        "--no-aggregator.admission-enabled",
    "agent.spool.dir": "--agent.spool-dir",
    "agent.wire.version": "--agent.wire-version",
    "aggregator.base_row_cache": "--aggregator.base-row-cache",
    "aggregator.multihost.enabled":
        "--aggregator.multihost.enabled / "
        "--no-aggregator.multihost.enabled",
    "aggregator.multihost.coordinator": "--aggregator.multihost.coordinator",
    "aggregator.multihost.num_processes":
        "--aggregator.multihost.num-processes",
    "aggregator.multihost.process_id": "--aggregator.multihost.process-id",
    "aggregator.multihost.init_timeout":
        "--aggregator.multihost.init-timeout",
    "aggregator.multihost.takeover":
        "--aggregator.multihost.takeover / "
        "--no-aggregator.multihost.takeover",
    "aggregator.membership.auto_apply":
        "--aggregator.membership.auto-apply / "
        "--no-aggregator.membership.auto-apply",
    "aggregator.membership.autoscale_enabled":
        "--aggregator.membership.autoscale-enabled / "
        "--no-aggregator.membership.autoscale-enabled",
    "aggregator.membership.scale_up_load":
        "--aggregator.membership.scale-up-load",
    "aggregator.membership.scale_down_load":
        "--aggregator.membership.scale-down-load",
    "aggregator.membership.up_windows":
        "--aggregator.membership.up-windows",
    "aggregator.membership.down_windows":
        "--aggregator.membership.down-windows",
    "aggregator.membership.min_replicas":
        "--aggregator.membership.min-replicas",
    "aggregator.membership.max_replicas":
        "--aggregator.membership.max-replicas",
    "aggregator.membership.standby_peers":
        "--aggregator.membership.standby-peers (repeatable)",
    "aggregator.membership.probe_timeout":
        "--aggregator.membership.probe-timeout",
    "tpu.platform": "--tpu.platform",
    "tpu.fleet_backend": "--tpu.fleet-backend",
    "telemetry.enabled": "--telemetry.enable / --no-telemetry.enable",
    "telemetry.journal.enabled":
        "--telemetry.journal.enable / --no-telemetry.journal.enable",
}

_SNAKE_TO_CAMEL = {v: k for k, v in _CANONICAL_YAML_KEYS.items()}

_DURATION_PATHS = {"monitor.interval", "monitor.staleness",
                   "monitor.stall_after", "monitor.state_max_age",
                   "agent.spool.fsync_interval",
                   "aggregator.interval", "aggregator.stale_after",
                   "aggregator.backoff_initial", "aggregator.backoff_max",
                   "aggregator.breaker_cooldown", "aggregator.flush_timeout",
                   "aggregator.skew_tolerance", "aggregator.degraded_ttl",
                   "aggregator.dispatch_timeout",
                   "aggregator.admission_latency_budget",
                   "aggregator.admission_retry_after",
                   "aggregator.admission_retry_after_max",
                   "agent.drain.retry_after_max",
                   "agent.wire.degraded_ttl",
                   "aggregator.membership.probe_timeout",
                   "aggregator.hlc_max_drift",
                   "service.restart_backoff_initial",
                   "service.restart_backoff_max"}


def yaml_path(path: str) -> str:
    parts = [_SNAKE_TO_CAMEL.get(p, p) for p in path.split(".")]
    return ".".join(parts)


def fmt_default(path: str, value) -> str:
    if path in _DURATION_PATHS:
        secs = float(value)
        return f"`{secs:g}s`"
    if isinstance(value, Level):
        return "`[node, process, container, vm, pod]`"
    if isinstance(value, bool):
        return f"`{str(value).lower()}`"
    if isinstance(value, str):
        return f"`{value!r}`" if value == "" else f"`{value}`"
    return f"`{value}`"


def leaves(obj, prefix=""):
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if dataclasses.is_dataclass(v):
            yield from leaves(v, f"{prefix}{f.name}.")
        else:
            yield f"{prefix}{f.name}", v


def registered_flags() -> set[str]:
    parser = argparse.ArgumentParser(add_help=False)
    register_flags(parser)
    out = set()
    for action in parser._actions:
        for opt in action.option_strings:
            out.add(opt)
    return out


def render() -> str:
    cfg = default_config()
    rows = list(leaves(cfg))
    missing = [p for p, _ in rows if p not in DESCRIPTIONS]
    if missing:
        raise SystemExit(
            f"gen_config_docs: undocumented config fields {missing} — add "
            "DESCRIPTIONS entries")
    stale = [p for p in DESCRIPTIONS if p not in {p for p, _ in rows}]
    if stale:
        raise SystemExit(
            f"gen_config_docs: DESCRIPTIONS has stale paths {stale}")
    doc_flags = " ".join(FLAG_OF.values())
    unmentioned = [
        f for f in registered_flags()
        if f not in doc_flags and not f.startswith("--no-")
        and f not in ("--config.file",)
    ]
    if unmentioned:
        raise SystemExit(
            f"gen_config_docs: flags missing from FLAG_OF: {unmentioned}")

    lines = [
        "# Configuration",
        "",
        "Every option, generated from the live `Config` schema by",
        "`hack/gen_config_docs.py` — do not edit by hand. Regenerate with",
        "`python hack/gen_config_docs.py` (CI checks freshness with",
        "`--check`).",
        "",
        "Precedence (reference `config.go:285-395`): built-in defaults <",
        "YAML file (`--config.file`) < explicitly-passed CLI flags. YAML",
        "keys accept camelCase (`maxTerminated`) and kebab-case",
        "(`max-terminated`) spellings interchangeably. Durations accept",
        "Go syntax (`5s`, `500ms`, `1m30s`).",
        "",
        "Settings without a flag are YAML-only — either dev-only",
        "(`dev.*`) or security-sensitive (`msr.*`), per the reference's",
        "stance of not exposing those on the command line.",
        "",
        "| Key (YAML path) | Default | Flag | Description |",
        "|---|---|---|---|",
    ]
    for path, value in rows:
        flag = FLAG_OF.get(path, "—")
        if flag != "—":
            flag = f"`{flag}`"
        desc = DESCRIPTIONS[path].replace("\n", " ")
        lines.append(
            f"| `{yaml_path(path)}` | {fmt_default(path, value)} | "
            f"{flag} | {desc} |")
    lines += [
        "",
        "## Example",
        "",
        "```yaml",
        "log: {level: info}",
        "monitor: {interval: 5s, staleness: 500ms}",
        "exporter:",
        "  stdout: {enabled: false}",
        "  prometheus:",
        "    enabled: true",
        "    metricsLevel: [node, process, container, vm, pod]",
        "web: {listenAddresses: [':28282']}",
        "kube: {enabled: true, node-name: worker-1}",
        "# agent half of the fleet plane:",
        "aggregator: {endpoint: 'https://aggregator:28283'}",
        "```",
        "",
        "See `docs/user/installation.md` for deployment-specific",
        "configuration (DaemonSet mounts, Helm values, compose).",
    ]
    return "\n".join(lines).rstrip() + "\n"


def main() -> int:
    text = render()
    if "--check" in sys.argv:
        try:
            with open(OUT_PATH, encoding="utf-8") as f:
                current = f.read()
        except OSError:
            current = ""
        if current != text:
            print(f"{OUT_PATH} is stale; run python hack/gen_config_docs.py",
                  file=sys.stderr)
            return 1
        print(f"{OUT_PATH} is up to date")
        return 0
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w", encoding="utf-8") as f:
        f.write(text)
    print(f"wrote {OUT_PATH} ({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
