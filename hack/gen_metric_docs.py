#!/usr/bin/env python3
"""Generate ``docs/user/metrics.md`` from the live collectors.

Reference parity: ``hack/gen-metric-docs/main.go`` — instantiate the real
Prometheus collectors against a fixture monitor (reference ``MockMonitor``,
main.go:31-47), harvest every metric family's name / type / help / labels,
and render the user-facing metrics reference. Running the generator keeps
the doc from drifting from the code; a test pins the output
(reference ``hack/gen-metric-docs/main_test.go``).

Usage:  python hack/gen_metric_docs.py [--check]
  --check   exit 1 if docs/user/metrics.md is stale (CI mode) instead of
            rewriting it.
"""

from __future__ import annotations

import os
import sys
import threading

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kepler_tpu.exporter.prometheus.collector import PowerCollector  # noqa: E402
from kepler_tpu.exporter.prometheus.info_collectors import (  # noqa: E402
    BuildInfoCollector,
    CPUInfoCollector,
    PowerMeterInfoCollector,
)
from kepler_tpu.monitor.snapshot import (  # noqa: E402
    NodeUsage,
    Snapshot,
    WorkloadTable,
)

OUT_PATH = os.path.join(REPO, "docs", "user", "metrics.md")

_ZONES = ("package", "dram")


def _table(kind: str) -> WorkloadTable:
    meta = {
        "process": {"comm": "bash", "exe": "/bin/bash", "type": "regular",
                    "container_id": "", "vm_id": ""},
        "container": {"container_name": "web", "runtime": "docker",
                      "pod_id": "p-1"},
        "vm": {"vm_name": "guest", "hypervisor": "kvm"},
        "pod": {"pod_name": "web-1", "namespace": "default"},
    }[kind]
    return WorkloadTable(
        ids=("1",), meta=(meta,),
        energy_uj=np.full((1, len(_ZONES)), 1e6),
        power_uw=np.full((1, len(_ZONES)), 1e6),
        seconds=np.ones(1) if kind == "process" else None,
    )


class FixtureMonitor:
    """Minimal PowerDataProvider: one workload of every kind, both states
    (the analog of the reference MockMonitor, gen-metric-docs/main.go:31-47).
    """

    def __init__(self) -> None:
        self._ready = threading.Event()
        self._ready.set()
        z = len(_ZONES)
        node = NodeUsage(
            zone_names=_ZONES,
            energy_uj=np.full(z, 1e6), active_uj=np.full(z, 6e5),
            idle_uj=np.full(z, 4e5), power_uw=np.full(z, 1e6),
            active_power_uw=np.full(z, 6e5), idle_power_uw=np.full(z, 4e5),
            window_active_uj=np.full(z, 6e5), usage_ratio=0.6,
        )
        self._snap = Snapshot(
            timestamp=0.0, node=node,
            processes=_table("process"), containers=_table("container"),
            virtual_machines=_table("vm"), pods=_table("pod"),
            terminated_processes=_table("process"),
            terminated_containers=_table("container"),
            terminated_virtual_machines=_table("vm"),
            terminated_pods=_table("pod"),
        )

    def data_channel(self) -> threading.Event:
        return self._ready

    def snapshot(self) -> Snapshot:
        return self._snap


def harvest():
    """Collect (name, type, help, labels) for every family, in emit order."""
    # fixture cpuinfo so label harvesting never depends on the host machine
    import tempfile

    tmp = tempfile.mkdtemp(prefix="kepler-gen-docs-")
    with open(os.path.join(tmp, "cpuinfo"), "w", encoding="utf-8") as f:
        f.write("processor\t: 0\nvendor_id\t: GenuineIntel\n"
                "model name\t: Fixture CPU\nphysical id\t: 0\n"
                "core id\t: 0\n\n")
    collectors = [
        PowerCollector(FixtureMonitor(), node_name="node-a"),  # type: ignore
        BuildInfoCollector(),
        CPUInfoCollector(procfs=tmp),
        PowerMeterInfoCollector("rapl-powercap"),
    ]
    seen: dict[str, tuple[str, str, tuple[str, ...]]] = {}
    for collector in collectors:
        for family in collector.collect():
            labels: tuple[str, ...] = ()
            for sample in family.samples:
                if len(sample.labels) > len(labels):
                    labels = tuple(sample.labels)
            prev = seen.get(family.name)
            if prev is None or len(labels) > len(prev[2]):
                seen[family.name] = (family.type, family.documentation,
                                     labels)
    return seen


_GROUPS = (
    ("Node", "kepler_node_cpu_"),
    ("Process", "kepler_process_"),
    ("Container", "kepler_container_"),
    ("Virtual Machine", "kepler_vm_"),
    ("Pod", "kepler_pod_"),
    ("Exporter self-metrics", "kepler_build_info"),
    ("Node info", "kepler_node_cpu_info"),
)

_SUFFIX = {"counter": "_total"}  # OpenMetrics: counters expose *_total


def render(families) -> str:
    lines = [
        "# Metrics",
        "",
        "All metrics exported by kepler-tpu, generated from the live",
        "collectors by `hack/gen_metric_docs.py` — do not edit by hand.",
        "Regenerate with `make gen-metric-docs` (CI checks freshness with",
        "`python hack/gen_metric_docs.py --check`).",
        "",
        "Naming follows the reference (`docs/user/metrics.md` upstream):",
        "`kepler_<level>_<device>_<metric>[_total]`, energy in joules",
        "(cumulative counters), power in watts (gauges).",
        "",
    ]
    emitted = set()

    def group_of(name: str) -> str:
        if name in ("kepler_build_info",):
            return "Exporter self-metrics"
        if name == "kepler_node_cpu_info":
            return "Node info"
        for title, prefix in _GROUPS:
            if name.startswith(prefix):
                return title
        return "Other"

    order = ["Node", "Process", "Container", "Virtual Machine", "Pod",
             "Node info", "Exporter self-metrics", "Other"]
    by_group: dict[str, list[str]] = {g: [] for g in order}
    for name in families:
        by_group.setdefault(group_of(name), []).append(name)
    for title in order:
        names = by_group.get(title, [])
        if not names:
            continue
        lines += [f"## {title}", ""]
        for name in names:
            if name in emitted:
                continue
            emitted.add(name)
            ftype, doc, labels = families[name]
            exposed = name + _SUFFIX.get(ftype, "")
            lines += [f"### `{exposed}`", "",
                      f"{doc.strip().rstrip('.')}.", "",
                      f"- **Type**: {ftype.capitalize()}"]
            if labels:
                label_list = ", ".join(f"`{label}`" for label in labels)
                lines.append(f"- **Labels**: {label_list}")
            lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def main() -> int:
    text = render(harvest())
    if "--check" in sys.argv:
        try:
            with open(OUT_PATH, encoding="utf-8") as f:
                current = f.read()
        except OSError:
            current = ""
        if current != text:
            print(f"{OUT_PATH} is stale; run python hack/gen_metric_docs.py",
                  file=sys.stderr)
            return 1
        print(f"{OUT_PATH} is up to date")
        return 0
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w", encoding="utf-8") as f:
        f.write(text)
    print(f"wrote {OUT_PATH} ({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
