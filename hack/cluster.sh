#!/usr/bin/env bash
# kind-based dev cluster for kepler-tpu (analog of reference hack/cluster.sh).
#
#   hack/cluster.sh up       create the kind cluster
#   hack/cluster.sh deploy   build + load the image, apply manifests/k8s
#   hack/cluster.sh down     delete the cluster
set -euo pipefail

CLUSTER_NAME=${CLUSTER_NAME:-kepler-tpu-dev}
IMG=${IMG:-kepler-tpu}
TAG=${TAG:-latest}
ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"

need() {
    command -v "$1" >/dev/null 2>&1 || {
        echo "error: '$1' is required" >&2
        exit 1
    }
}

cluster_up() {
    need kind
    if kind get clusters 2>/dev/null | grep -qx "$CLUSTER_NAME"; then
        echo "cluster '$CLUSTER_NAME' already exists"
        return
    fi
    # hostPID DaemonSet needs /proc and /sys from the node; kind nodes are
    # containers, so the agent sees the kind node's (host's) procfs — good
    # enough for dev. RAPL is typically absent: deploy the fake meter config.
    kind create cluster --name "$CLUSTER_NAME" --wait 120s
}

cluster_down() {
    need kind
    kind delete cluster --name "$CLUSTER_NAME"
}

deploy() {
    need kind
    need kubectl
    need docker
    docker build -t "$IMG:$TAG" "$ROOT"
    kind load docker-image "$IMG:$TAG" --name "$CLUSTER_NAME"
    kubectl apply -k "$ROOT/manifests/k8s"
    # kind nodes have no RAPL and no TPUs: switch the agent to the fake
    # meter and drop the aggregator's TPU node selector
    kubectl -n kepler-tpu patch daemonset kepler-tpu --type=json -p='[
      {"op": "add",
       "path": "/spec/template/spec/containers/0/args/-",
       "value": "--config.file=/etc/kepler/config.yaml"}]' || true
    kubectl -n kepler-tpu patch deployment kepler-tpu-aggregator --type=json -p='[
      {"op": "remove", "path": "/spec/template/spec/nodeSelector"},
      {"op": "remove", "path": "/spec/template/spec/containers/0/resources/limits/google.com~1tpu"}]' || true
    kubectl -n kepler-tpu rollout status daemonset/kepler-tpu --timeout=120s
    echo "deployed; scrape any agent at :28282/metrics"
}

case "${1:-}" in
up) cluster_up ;;
down) cluster_down ;;
deploy) deploy ;;
*)
    echo "usage: $0 {up|down|deploy}" >&2
    exit 1
    ;;
esac
