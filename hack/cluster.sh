#!/usr/bin/env bash
# kind-based dev cluster for kepler-tpu (analog of reference hack/cluster.sh).
#
#   hack/cluster.sh up       create the kind cluster
#   hack/cluster.sh deploy   build + load the image, apply manifests/k8s
#   hack/cluster.sh down     delete the cluster
set -euo pipefail

CLUSTER_NAME=${CLUSTER_NAME:-kepler-tpu-dev}
IMG=${IMG:-kepler-tpu}
TAG=${TAG:-latest}
ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"

need() {
    command -v "$1" >/dev/null 2>&1 || {
        echo "error: '$1' is required" >&2
        exit 1
    }
}

cluster_up() {
    need kind
    if kind get clusters 2>/dev/null | grep -qx "$CLUSTER_NAME"; then
        echo "cluster '$CLUSTER_NAME' already exists"
        return
    fi
    # hostPID DaemonSet needs /proc and /sys from the node; kind nodes are
    # containers, so the agent sees the kind node's (host's) procfs — good
    # enough for dev. RAPL is typically absent: deploy the fake meter config.
    kind create cluster --name "$CLUSTER_NAME" --wait 120s
}

cluster_down() {
    need kind
    kind delete cluster --name "$CLUSTER_NAME"
}

deploy() {
    need kind
    need kubectl
    need docker
    docker build -t "$IMG:$TAG" "$ROOT"
    kind load docker-image "$IMG:$TAG" --name "$CLUSTER_NAME"
    kubectl apply -k "$ROOT/manifests/k8s"
    # kind nodes have no RAPL and no TPUs: switch the agent to the fake
    # meter and drop the aggregator's TPU node selector
    kubectl -n kepler-tpu patch daemonset kepler-tpu --type=json -p='[
      {"op": "add",
       "path": "/spec/template/spec/containers/0/args/-",
       "value": "--config.file=/etc/kepler/config.yaml"}]' || true
    kubectl -n kepler-tpu patch deployment kepler-tpu-aggregator --type=json -p='[
      {"op": "remove", "path": "/spec/template/spec/nodeSelector"},
      {"op": "remove", "path": "/spec/template/spec/containers/0/resources/limits/google.com~1tpu"}]' || true
    kubectl -n kepler-tpu rollout status daemonset/kepler-tpu --timeout=120s
    echo "deployed; scrape any agent at :28282/metrics"
}

e2e() {
    # Scrape assertions against a deployed cluster (CI lane: the analog
    # of the reference's k8s-equinix workflow checks). Port-forwards both
    # services and asserts the core series exist.
    need kubectl
    # deliberately NOT `local`: the EXIT trap below outlives this
    # function's scope (and bash < 4.4 trips set -u expanding an empty
    # array, hence the length guard)
    pf_pids=()
    cleanup() {
        if [ "${#pf_pids[@]}" -gt 0 ]; then
            kill "${pf_pids[@]}" 2>/dev/null || true
        fi
    }
    # RETURN covers the normal function exit; EXIT covers the `exit 1`
    # failure paths below, which bypass RETURN and would otherwise orphan
    # the background port-forwards holding ports 28282/28283
    trap cleanup RETURN EXIT

    kubectl -n kepler-tpu wait --for=condition=ready pod \
        -l app.kubernetes.io/name=kepler-tpu --timeout=180s

    kubectl -n kepler-tpu port-forward svc/kepler-tpu 28282:28282 &
    pf_pids+=($!)
    kubectl -n kepler-tpu port-forward svc/kepler-tpu-aggregator \
        28283:28283 &
    pf_pids+=($!)
    sleep 3

    echo "--- agent /metrics"
    # retry: the first scrape may race the first monitor window + jit
    for i in $(seq 1 20); do
        if curl -sf localhost:28282/metrics |
            grep -q '^kepler_node_cpu_joules_total'; then
            break
        fi
        [ "$i" = 20 ] && {
            echo "error: kepler_node_cpu_joules_total never appeared" >&2
            exit 1
        }
        sleep 3
    done
    curl -sf localhost:28282/metrics | grep -c '^kepler_' |
        xargs echo "agent kepler_ series:"
    curl -sf localhost:28282/metrics |
        grep -q '^kepler_process_cpu_watts' ||
        { echo "error: no process attribution series" >&2; exit 1; }

    echo "--- aggregator /metrics"
    for i in $(seq 1 20); do
        if curl -sf localhost:28283/metrics | grep -q '^kepler_fleet_'; then
            break
        fi
        [ "$i" = 20 ] && {
            echo "error: kepler_fleet_* never appeared" >&2
            exit 1
        }
        sleep 3
    done
    curl -sf localhost:28283/metrics | grep -c '^kepler_fleet_' |
        xargs echo "aggregator kepler_fleet_ series:"
    echo "e2e: OK"
}

case "${1:-}" in
up) cluster_up ;;
down) cluster_down ;;
deploy) deploy ;;
e2e) e2e ;;
*)
    echo "usage: $0 {up|down|deploy|e2e}" >&2
    exit 1
    ;;
esac
