#!/usr/bin/env python3
"""`make introspect`: boot a local aggregator, ingest two node reports
over HTTP, run one fleet window, then fetch `/debug/window`,
`/debug/fleet`, and `/debug/ring` and validate their JSON against the
catalog schemas in docs/developer/observability.md ("Device
introspection" / "Fleet scoreboard") and resilience.md ("Ingest
hand-off"). Exit 0 only when all three endpoints serve schema-valid
JSON with a populated engine dump, scoreboard, and ring view — the
zero-to-working proof that the introspection plane is wired end to end
in the real binary wiring (APIServer + Aggregator.init), not just in
unit tests.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

WINDOW_REQUIRED = {"rung", "rung_name", "shards", "timeline",
                   "windows_at_rung", "windows_since_last_failure",
                   "demotions_by_reason", "engines", "stats"}
ENGINE_REQUIRED = {"engine", "n_shards", "window_seq", "buckets",
                   "resident", "shards", "programs", "updates",
                   "compile_count"}
FLEET_REQUIRED = {"cap", "anomaly_z", "flag_ttl_s", "stale_after_s",
                  "states", "nodes"}
RING_REQUIRED = {"enabled", "epoch", "self", "peers", "vnodes",
                 "ownership_ratio", "owned_nodes", "redirected_total",
                 "last_redirect_age_s"}
# the fleet-ingest admission probe on /healthz (ISSUE 12): resilience.md
# "Overload and backpressure"
INGEST_REQUIRED = {"ok", "shedding", "inflight", "max_inflight",
                   "latency_ewma_s", "latency_budget_s", "load",
                   "shed_total", "shed_by_reason"}
# the fleet black box (ISSUE 19): observability.md "Fleet black box"
JOURNAL_REQUIRED = {"node", "enabled", "stats", "events", "cursor"}
JOURNAL_STATS_REQUIRED = {"enabled", "node", "events_total", "ring",
                          "spool", "write_errors", "hlc_clamped_total",
                          "hlc_drift_seconds"}
BUNDLE_REQUIRED = {"schema", "node", "captured_hlc", "journal",
                   "journal_stats", "rung", "rung_timeline",
                   "scoreboard", "ring", "stats",
                   "config_fingerprint"}
HLC_REQUIRED = {"phys_us", "logical", "node"}
NODE_REQUIRED = {"state", "state_code", "last_seen_age_s", "reports",
                 "duplicates", "windows_lost", "quarantined",
                 "delivery_ewma_s", "power_w", "power_mean_w",
                 "power_z", "anomalous"}


def _check(cond: bool, what: str) -> None:
    if not cond:
        print(f"FAIL: {what}", file=sys.stderr)
        sys.exit(1)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from kepler_tpu.fleet.aggregator import Aggregator
    from kepler_tpu.fleet.journal import EventJournal
    from kepler_tpu.fleet.wire import encode_report
    from kepler_tpu.parallel.fleet import MODE_MODEL, MODE_RATIO, NodeReport
    from kepler_tpu.server.http import APIServer
    from kepler_tpu.service.lifecycle import CancelContext

    server = APIServer(listen_addresses=["127.0.0.1:0"])
    # a 1-peer ring: ownership machinery active (epoch, /debug/ring
    # populated) with every node owned locally — the smoke's reports
    # must ingest, not redirect
    agg = Aggregator(server, model_mode="mlp", node_bucket=8,
                     workload_bucket=16, stale_after=1e9,
                     peers=["127.0.0.1:28283"],
                     self_peer="127.0.0.1:28283",
                     admission_enabled=True,
                     journal=EventJournal(enabled=True,
                                          node="127.0.0.1:28283"))
    agg.init()
    server.init()
    ctx = CancelContext()
    thread = threading.Thread(target=server.run, args=(ctx,), daemon=True)
    thread.start()
    host, port = server.addresses[0]
    base = f"http://{host}:{port}"
    try:
        rng = np.random.default_rng(0)
        for name, mode in (("node-a", MODE_RATIO), ("node-b", MODE_MODEL)):
            w = 3
            cpu = rng.uniform(0.1, 5.0, w).astype(np.float32)
            report = NodeReport(
                node_name=name,
                zone_deltas_uj=rng.uniform(1e6, 1e8, 2).astype(np.float32),
                zone_valid=np.ones(2, bool),
                usage_ratio=0.6,
                cpu_deltas=cpu,
                workload_ids=[f"{name}-w{i}" for i in range(w)],
                node_cpu_delta=float(cpu.sum()),
                dt_s=5.0,
                mode=mode,
                workload_kinds=np.ones(w, np.int8),
            )
            body = encode_report(report, ["package", "dram"], seq=1,
                                 run="smoke")
            req = urllib.request.Request(f"{base}/v1/report", data=body,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=10) as resp:
                _check(resp.status == 204, f"ingest {name}")
        _check(agg.aggregate_once() is not None, "window published")

        with urllib.request.urlopen(f"{base}/debug/window",
                                    timeout=10) as resp:
            window = json.loads(resp.read())
        missing = WINDOW_REQUIRED - set(window)
        _check(not missing, f"/debug/window missing keys {missing}")
        _check(window["engines"], "/debug/window engines populated")
        for label, engine in window["engines"].items():
            gap = ENGINE_REQUIRED - set(engine)
            _check(not gap, f"engine {label} missing keys {gap}")
        programs = next(iter(window["engines"].values()))["programs"]
        # a failed capture stores a truthy {"label", "error"} dict, so
        # require the flops field itself (what collect() exports)
        _check(any(p.get("cost") and "flops" in p["cost"]
                   for p in programs),
               "cost stats captured on the cold compile")

        with urllib.request.urlopen(f"{base}/debug/fleet",
                                    timeout=10) as resp:
            fleet = json.loads(resp.read())
        missing = FLEET_REQUIRED - set(fleet)
        _check(not missing, f"/debug/fleet missing keys {missing}")
        _check(set(fleet["nodes"]) == {"node-a", "node-b"},
               f"scoreboard rows {sorted(fleet['nodes'])}")
        for name, row in fleet["nodes"].items():
            gap = NODE_REQUIRED - set(row)
            _check(not gap, f"scoreboard row {name} missing {gap}")
            _check(row["state"] == "healthy",
                   f"{name} state {row['state']!r} (expected healthy)")
        with urllib.request.urlopen(f"{base}/debug/ring",
                                    timeout=10) as resp:
            ring = json.loads(resp.read())
        missing = RING_REQUIRED - set(ring)
        _check(not missing, f"/debug/ring missing keys {missing}")
        _check(ring["enabled"] is True, "ring enabled")
        _check(ring["epoch"] >= 1, f"ring epoch {ring['epoch']}")
        _check(ring["ownership_ratio"] == 1.0,
               "single peer owns the whole hash space")
        _check(ring["owned_nodes"] == 2,
               f"owned_nodes {ring['owned_nodes']} (expected 2)")
        _check(ring["redirected_total"] == 0,
               "no redirects on a 1-peer ring")

        with urllib.request.urlopen(f"{base}/healthz",
                                    timeout=10) as resp:
            healthz = json.loads(resp.read())
        ingest = healthz.get("components", {}).get("fleet-ingest")
        _check(isinstance(ingest, dict),
               "fleet-ingest probe registered on /healthz")
        missing = INGEST_REQUIRED - set(ingest)
        _check(not missing, f"fleet-ingest probe missing keys {missing}")
        _check(ingest["ok"] is True and ingest["shedding"] is False,
               "admission idle: not shedding")
        _check(ingest["shed_total"] == 0, "no sheds on a quiet smoke")
        _check(set(ingest["shed_by_reason"]) == {"inflight", "latency"},
               f"shed reasons {sorted(ingest['shed_by_reason'])}")

        # a real membership transition (epoch 1 → 2) so the journal has
        # fleet events to serve — initial ring construction is state,
        # not a transition, and correctly emits nothing
        agg.apply_membership(["127.0.0.1:28283", "127.0.0.1:28284"],
                             2, source="operator")
        with urllib.request.urlopen(f"{base}/debug/journal",
                                    timeout=10) as resp:
            journal = json.loads(resp.read())
        missing = JOURNAL_REQUIRED - set(journal)
        _check(not missing, f"/debug/journal missing keys {missing}")
        _check(journal["enabled"] is True, "journal enabled")
        gap = JOURNAL_STATS_REQUIRED - set(journal["stats"])
        _check(not gap, f"journal stats missing keys {gap}")
        kinds = {e.get("kind") for e in journal["events"]}
        _check("membership.apply" in kinds,
               f"membership.apply journaled (got {sorted(kinds)})")
        _check("lease.adopt" in kinds, "lease.adopt journaled")
        for entry in journal["events"]:
            gap = {"hlc", "kind", "fields"} - set(entry)
            _check(not gap, f"journal entry missing {gap}")
            gap = HLC_REQUIRED - set(entry["hlc"])
            _check(not gap, f"journal entry hlc missing {gap}")
        _check(journal["cursor"], "non-empty page carries a cursor")
        # cursor pagination: resuming at the last stamp yields nothing
        with urllib.request.urlopen(
                f"{base}/debug/journal?since={journal['cursor']}",
                timeout=10) as resp:
            page2 = json.loads(resp.read())
        _check(page2["events"] == [], "cursor resume is strictly-after")

        with urllib.request.urlopen(f"{base}/debug/bundle",
                                    timeout=10) as resp:
            bundle_raw = resp.read()
        bundle = json.loads(bundle_raw)
        missing = BUNDLE_REQUIRED - set(bundle)
        _check(not missing, f"/debug/bundle missing keys {missing}")
        _check(bundle["schema"] == "kepler-bundle/v1",
               f"bundle schema {bundle.get('schema')!r}")
        gap = HLC_REQUIRED - set(bundle["captured_hlc"] or {})
        _check(not gap, f"bundle captured_hlc missing {gap}")
        _check(len(bundle["journal"]) >= len(journal["events"]),
               "bundle embeds the journal ring")
        _check(bundle["ring"]["enabled"] is True, "bundle ring view")
        # canonical JSON: re-encoding sorted/compact is byte-identical
        recoded = json.dumps(bundle, sort_keys=True,
                             separators=(",", ":")).encode() + b"\n"
        _check(recoded == bundle_raw, "bundle is canonical JSON")

        print(f"introspect smoke OK: rung={window['rung_name']} "
              f"shards={window['shards']} "
              f"programs={len(programs)} "
              f"nodes={len(fleet['nodes'])} "
              f"states={fleet['states']} "
              f"ring_epoch={ring['epoch']} "
              f"ingest_load={ingest['load']} "
              f"journal_events={journal['stats']['events_total']}")
        return 0
    finally:
        ctx.cancel()
        agg.shutdown()
        server.shutdown()


if __name__ == "__main__":
    sys.exit(main())
