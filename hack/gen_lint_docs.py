#!/usr/bin/env python3
"""Generate ``docs/developer/static-analysis.md`` from the keplint registry.

Same pattern (and teeth) as ``hack/gen_config_docs.py`` /
``gen_metric_docs.py``: the rule catalog is rendered from the live
registry in ``kepler_tpu.analysis``, so the doc can never silently drift
from the rules — adding a rule without regenerating fails ``--check``
(and the freshness test), and every rule must carry a summary and a
rationale or the generator refuses to render.

Usage:  python hack/gen_lint_docs.py [--check]
  --check   exit 1 if docs/developer/static-analysis.md is stale.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kepler_tpu.analysis import all_rules  # noqa: E402

OUT_PATH = os.path.join(REPO, "docs", "developer", "static-analysis.md")

PREAMBLE = """\
# Static analysis: keplint + the typing ratchet

Generated from the live rule registry by `hack/gen_lint_docs.py` — do
not edit by hand; regenerate with `python hack/gen_lint_docs.py` (CI
checks freshness with `--check`).

The attribution formula is only correct while a handful of code-level
invariants hold *everywhere*: counter deltas must be wrap-aware, timing
logic must use monotonic clocks, published snapshots must stay
immutable, jitted kernels must stay pure, lock and input-hygiene
contracts must survive helper-function hops. Generic linters cannot
see those — they are domain invariants — so `keplint`
(`kepler_tpu/analysis/`) encodes each one as an AST check. `make lint`
runs keplint, ruff (config committed in `pyproject.toml`), and mypy
(per-module strictness ratchet, also in `pyproject.toml`).

## Running

```
python -m kepler_tpu.analysis              # lint kepler_tpu/, hack/, benchmarks/
python -m kepler_tpu.analysis path/ file.py
python -m kepler_tpu.analysis --list-rules
python -m kepler_tpu.analysis --format=sarif   # SARIF 2.1.0 (make keplint-sarif)
python -m kepler_tpu.analysis --per-file       # disable cross-module analysis
python -m kepler_tpu.analysis --device-tier    # + trace device programs (KTL120-123)
python -m kepler_tpu.analysis --protocol-tier  # + explore protocol models (KTL130-132)
python -m kepler_tpu.analysis --only=KTL120    # single-rule iteration loop
```

Exit codes: `0` clean (baselined findings tolerated), `1` new
violations, `2` usage errors. `--format=json|sarif` emits
machine-readable reports (SARIF 2.1.0 minimal profile, consumable as
CI annotations). `--only=KTLxxx[,KTLxxx]` restricts a run to the named
rules so a single-rule iteration loop does not pay every family's cost
— in particular the device tier's trace cost. Naming a KTL12x id in
`--only` implies `--device-tier`; a KTL130-132 id implies
`--protocol-tier`.

## Whole-program analysis

KTL101-110 run per file. KTL111-113 run once per lint over a
`ProjectContext` (`kepler_tpu/analysis/project.py`): every file is
parsed **once** per run and shared by all rules, then a module-level
symbol table, light type inference (constructor assignments, parameter
annotations), and a **call graph** link resolved call sites across
modules. On top of the graph:

- **Thread roles** propagate from declared roots along call edges:
  `# keplint: thread-role=<role>` on a `def` or `class` names a root
  (agent thread, `_FetchWorker`, shutdown paths, HTTP handlers); the
  `hot-loop` marker roots the `hot-loop` role; and callables passed to
  a `# keplint: role-registrar=<role>` function (`APIServer.register`)
  become roots of that role. Propagation stops at `# keplint:
  role-boundary` seams — the meter/informer/persistence functions that
  do I/O *by design* and keep their own contracts.
- **Lock summaries** record which locks each function acquires
  (directly and through its call closure), feeding the KTL111
  lock-order graph; lock identity is hoisted to the class that
  constructs the lock, so cross-module acquisitions alias correctly.
- **Taint** (KTL112) flows from sources (`# keplint: taint-source`
  functions like `peek_node_name`; `.headers`/`.path`/`.body` reads in
  `http-handler`-role functions) through assignments and resolved call
  edges until a sanitizer launders it: a function marked `# keplint:
  sanitizes` (the registry: `wire.sanitize_node_name`,
  `wire.decode_report`, `server.http.printable`) or a built-in
  coercion (`int`, `float`, …). Sinks: Prometheus label values, keys
  of object-attached stores, sequence indexes, log-call arguments, and
  `# keplint: taint-sink` functions.

`--per-file` restricts KTL111-113 to one-file contexts (no cross-module
call graph) — useful for bisecting which findings are genuinely
interprocedural; the test suite uses it to prove the call graph is
load-bearing.

## Device tier (kepljax, KTL120-123)

The host tiers see source text; the compiled packed/sharded fleet
programs the attribution math actually runs on are a different plane.
`--device-tier` (wired into `make lint`) traces every entry of a
declarative **program registry**
(`kepler_tpu/analysis/device/registry.py`) abstractly —
`jit(...).trace(ShapeDtypeStruct...)` + StableHLO lowering on a
CPU-only host (`JAX_PLATFORMS=cpu`, virtual devices, no execution, no
backend compile) — and runs four check families over the jaxprs:

- **KTL120 dtype-flow** — no f16/bf16 dot accumulators or reduction
  operands anywhere; half casts only at the boundaries the entry
  declares (`allowed_half_casts`, e.g. the packed program's one
  `float32->float16` wire quantizer, bf16 MXU operand feeds).
- **KTL121 donation-alias** — the entry's `donates` contract must be
  realized in the lowered module's argument attributes
  (`tf.aliasing_output` / `jax.buffer_donor`), and no undeclared arg
  may alias; a dropped donation is a silent full-copy per window.
- **KTL122 collective-discipline** — the traced program's explicit
  collectives must stay inside `allowed_collectives`, and
  `require_shard_map` entries must actually contain a `shard_map`
  (GSPMD inserts collectives at partitioning time, invisible to the
  jaxpr tier — losing the shard_map is how a regression to a
  replicated-index gather reads here).
- **KTL123 program-ratchet** — a normalized structural fingerprint per
  entry/case (aval signatures, compute-primitive histogram with
  version-noisy wrapper primitives excluded, collective set, half-cast
  pairs, shard_map presence, donation map) is committed as a golden
  snapshot in `.kepljax.json`; drift fails lint with a field diff.
  After an INTENDED program change, `make kepljax-snapshots`
  regenerates and the snapshot diff becomes part of code review.

Registry entries are declarative: factory + representative bucket-shape
cases (including pad-row/minimal-ladder edges) + the contract
vocabulary above (`donates`, `allowed_collectives`,
`allowed_half_casts`, `require_shard_map`, `n_devices`). CPU-host
caveats: traces stage the CPU lowering of each program (the packed
program serves f32 estimator compute off-TPU by design, so bf16-only
TPU casts do not appear), and fingerprints describe structure, not
cost. A jax upgrade can legitimately shift a fingerprint; regenerating
snapshots then is expected and the diff shows the cause.

## Protocol tier (kepmc, KTL130-133)

The host tiers read source; the device tier reads jaxprs; neither can
see an *ordering* bug — a safety violation that only a specific
interleaving of deliveries, crashes, restarts and scale events
produces (PR 16 shipped three of them). `--protocol-tier` (wired into
`make lint`; `make protocheck` runs it alone) runs **kepmc**
(`kepler_tpu/analysis/protocol/`): an explicit-state model checker
that exhaustively explores every reachable interleaving of a small
fleet and checks safety invariants in every state.

The models are thin adapters, not re-implementations: each transition
calls the SAME pure decision functions production runs —
`plan_membership_apply`/`CoordinatorLease.adopt`/`plan_succession`
(`fleet/membership.py`), `SeqTracker.observe`/`seed_fresh_tracker`/
`reseed_on_ownership_return`/`keyframe_wanted`/`delta_base_matches`/
`plan_ack_cursor`/`plan_rewind_tail` (`fleet/delivery.py`,
`fleet/spool.py`). A model bug is possible; a model/production *drift*
requires changing a shared function both see. KTL133 (below) fences
the other direction: protocol state may not move outside those
functions.

Specs are declarative registry entries
(`kepler_tpu/analysis/protocol/registry.py`), mirroring the device
tier's `ProgramSpec` shape: a `ProtocolSpec` names the model factory,
the production source module its transitions drive, the invariants to
check, and bounded `ProtocolCase`s (2-3 replicas, 1-2 agents, a
handful of windows/epochs — the scope where these protocols' bugs
live, small enough for exhaustive BFS in seconds). Each case carries a
`max_states` ceiling; blowing it raises `StateExplosionError` — lint
FAILS rather than silently truncating the search.

Event vocabulary (per model, composed from): message `deliver` /
`duplicate` / reorder (messages persist in the state, so any delivery
order is explored), dropped responses, `crash` / `restart`, `leave` /
join succession, false-`suspect` probing, `rewind` / replay,
ownership `scale` swaps, keyframe/delta sends with loss and `409`
responses, base-row eviction.

- **KTL130 protocol-epoch-safety** — lease/membership: at most one
  self-believed holder per epoch (crash-heal scope), the holder is a
  member of its own peer set, epochs stay contiguous (no skipped or
  double-minted bumps), and no replica wedges awaiting a transfer that
  can never arrive.
- **KTL131 protocol-loss-accounting** — delivery/spool: no reachable
  schedule fabricates loss (counts a delivered window as lost), the
  spool ack cursor never skips an unsent record, stale acks are
  rejected, rewinds stay bounded to already-acked tails.
- **KTL132 protocol-replay-idempotence** — replayed windows are
  duplicates, never loss; after a 409 the next send is always a
  keyframe (the needs-keyframe loop converges in one round-trip);
  duplicate keyframes still plant the delta base.

A violation prints as a **counterexample**: the minimal event trace
(BFS guarantees shortest-path) from the initial state to the violating
state, one event per line, ending with the violated invariant and the
state that broke it. Read it top-down as a schedule — each line is one
atomic event the fleet could execute in that order; reproduce it by
replaying the same calls against the real objects (the pinned
regression tests in `tests/test_protocol.py` do exactly that). The
committed baseline stays empty for this tier too: a counterexample on
the shipped tree is a bug to fix, never to grandfather.

KTL133 (`protocol-transition-marker`) is the lexical fence that keeps
the tier honest: inside `kepler_tpu/fleet/`, assignments to protocol
state attributes (lease epoch/holder, ring epoch, seq watermarks,
spool cursor, keyframe base rows) are only legal inside functions
marked `# keplint: protocol-transition`. An unmarked write is exactly
a transition the checker does not know about. It is an ordinary
per-file rule and always runs.

## Suppressing

Append `# keplint: disable=KTL1xx` to the offending line (or put it on
a comment line directly above); several ids separate with commas, and a
bare `disable` suppresses every rule on that line. `# keplint:
disable-file=KTL1xx` anywhere in the file suppresses a rule file-wide.
Every suppression should say *why* in the surrounding comment.
Suppression applies to whole-program rules too: the directive lives in
the file where the diagnostic lands.

## Annotation vocabulary

Rules that need to know which code is special read declarative markers
instead of hardcoding module lists:

| Marker | Meaning |
| --- | --- |
| `# keplint: monotonic-only` (file-level) | KTL101: this module's timing math must never call the wall clock directly |
| `# keplint: hot-loop` (above a `def`) | KTL106/KTL113: this function runs on the monitor refresh path; no sleeps/blocking I/O, lexically or via any call chain |
| `# keplint: guarded-by=_lock` (on an attribute assignment in `__init__`) | KTL108/KTL111: writes to this attribute require `with self._lock` (KTL111 checks writers in other classes/modules too) |
| `# keplint: requires-lock=_lock` (above a `def`) | KTL108/KTL111: this function may only be called with the lock held; callers are checked, cross-module included |
| `# keplint: donates=<positions>` (on a callable binding) | KTL110: calls through this binding consume the arguments at those positions |
| `# keplint: layout-definition` (above a `def`/`class`) | KTL114: the one scope allowed to spell packed row-layout offset arithmetic |
| `# keplint: thread-role=<role>` (above a `def` or `class`) | KTL113: roots the thread role here; it propagates to everything reachable |
| `# keplint: role-registrar=<role>` (above a `def`) | KTL113: callables passed to this function become roots of `<role>` |
| `# keplint: role-boundary` (above a `def`) | KTL113: role propagation stops here — the seam keeps its own contract |
| `# keplint: forbid-role=<role>` (above a `class`) | KTL113: functions running under `<role>` may not call this class's methods |
| `# keplint: allow-role=<role>` (above a `def`) | KTL113: sanctioned exception to the enclosing class's `forbid-role` |
| `# keplint: taint-source` (above a `def`) | KTL112: this function's return value is untrusted input |
| `# keplint: sanitizes` (above a `def`) | KTL112: passing a value through this function launders its taint |
| `# keplint: taint-sink[=label]` (above a `def`) | KTL112: tainted arguments to this function are findings |
| `# keplint: protocol-transition` (above a `def`) | KTL133: this function is a declared protocol transition — the one place protocol state attributes may be written (and the kepmc models cover it) |

## Baseline ratchet

`.keplint.json` at the repo root freezes pre-existing violation counts
per `path::rule`. New violations fail; baselined ones pass; *fixed*
ones surface as stale entries — regenerate with
`python -m kepler_tpu.analysis --write-baseline` to ratchet the ceiling
down. The committed baseline is **empty**: every finding in the shipped
tree was fixed, not grandfathered (`tests/test_keplint.py` pins this —
including for the whole-program rules).

The device tier has its own ratchet shape: the committed
`.kepljax.json` golden fingerprints (see above) — drift fails, and
regeneration is an explicit, reviewable act.

The same ratchet stance applies to typing: `pyproject.toml` declares a
strict mypy tier (`config/`, `monitor/snapshot`, `fleet/wire`,
`fleet/window`, `fleet/scoreboard`, `fleet/aggregator`,
`fleet/membership`, `fleet/delivery`, `fault/`, `analysis/` (the
protocol tier included), `parallel/packed`, `parallel/mesh`,
`parallel/compat` — fully typed, `disallow_untyped_defs`) and a
checked tier (`monitor/`, `fleet/`, `service/` —
`check_untyped_defs`); modules move *up* tiers, never down.

## Extending

Per-file rules subclass `kepler_tpu.analysis.Rule` and implement
`check(ctx)` over the shared `FileContext` (use `ctx.walk_nodes`, the
once-per-run node list, instead of re-walking `ctx.tree`).
Whole-program rules subclass `ProjectRule` and implement
`check_project(project)` over the `ProjectContext` (symbol table, call
graph, roles, lock summaries). Device-tier rules subclass `DeviceRule`
and implement `check_trace(report)` over a
`kepler_tpu.analysis.device.trace.TraceReport`; new device programs
register a `ProgramSpec` (factory + cases + contract) in
`kepler_tpu/analysis/device/registry.py` and commit regenerated
snapshots. Protocol-tier rules subclass `ProtocolRule` and implement
`check_model(report)` over a
`kepler_tpu.analysis.protocol.ModelReport` (the spec, the case, the
exploration result with its counterexamples); new protocol machines
register a `ProtocolSpec` (model factory + bounded cases +
invariants) in `kepler_tpu/analysis/protocol/registry.py`, drive REAL
pure functions from `kepler_tpu/fleet/` in their transitions, and
mark those functions `# keplint: protocol-transition` so KTL133 keeps
the write surface closed. Either way: set `id`/`name`/`severity`/`summary`/
`rationale` (and `tree_scope` if the rule polices `hack/` or
`benchmarks/` too), decorate with `@register`, add a good/bad fixture
pair to `tests/test_keplint.py` (cross-module fixtures for project
rules, spec fixtures in `tests/test_kepljax.py` for device rules), and
regenerate this doc. Engine internals (directives, baselines, file
walking, SARIF) live in `kepler_tpu/analysis/engine.py` and
`__main__.py`.

## Rule catalog
"""


def render() -> str:
    rules = all_rules()
    missing = [r.id for r in rules if not (r.summary and r.rationale)]
    if missing:
        raise SystemExit(
            f"gen_lint_docs: rules missing summary/rationale: {missing}")
    from kepler_tpu.analysis import ProjectRule
    from kepler_tpu.analysis.engine import DeviceRule, ProtocolRule

    lines = [PREAMBLE]
    lines.append("| Rule | Name | Tier | Scope | Severity | Invariant |")
    lines.append("| --- | --- | --- | --- | --- | --- |")
    for r in rules:
        if isinstance(r, ProtocolRule):
            tier, scope = "protocol", "explored protocol models"
        elif isinstance(r, DeviceRule):
            tier, scope = "device", "traced device programs"
        elif isinstance(r, ProjectRule):
            tier = "whole-program"
            scope = ", ".join(f"`{t}/`" for t in r.tree_scope)
        else:
            tier = "per-file"
            scope = ", ".join(f"`{t}/`" for t in r.tree_scope)
        lines.append(f"| `{r.id}` | {r.name} | {tier} | {scope} | "
                     f"{r.severity} | {r.summary} |")
    lines.append("")
    for r in rules:
        lines.append(f"### {r.id} — {r.name}")
        lines.append("")
        lines.append(f"**Invariant:** {r.summary}.")
        lines.append("")
        lines.append(r.rationale)
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def main() -> int:
    text = render()
    if "--check" in sys.argv:
        try:
            with open(OUT_PATH, encoding="utf-8") as f:
                current = f.read()
        except OSError:
            current = ""
        if current != text:
            print(f"{OUT_PATH} is stale; run python hack/gen_lint_docs.py",
                  file=sys.stderr)
            return 1
        print(f"{OUT_PATH} is up to date")
        return 0
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w", encoding="utf-8") as f:
        f.write(text)
    print(f"wrote {OUT_PATH} ({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
