#!/usr/bin/env python3
"""Generate ``docs/developer/static-analysis.md`` from the keplint registry.

Same pattern (and teeth) as ``hack/gen_config_docs.py`` /
``gen_metric_docs.py``: the rule catalog is rendered from the live
registry in ``kepler_tpu.analysis``, so the doc can never silently drift
from the rules — adding a rule without regenerating fails ``--check``
(and the freshness test), and every rule must carry a summary and a
rationale or the generator refuses to render.

Usage:  python hack/gen_lint_docs.py [--check]
  --check   exit 1 if docs/developer/static-analysis.md is stale.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kepler_tpu.analysis import all_rules  # noqa: E402

OUT_PATH = os.path.join(REPO, "docs", "developer", "static-analysis.md")

PREAMBLE = """\
# Static analysis: keplint + the typing ratchet

Generated from the live rule registry by `hack/gen_lint_docs.py` — do
not edit by hand; regenerate with `python hack/gen_lint_docs.py` (CI
checks freshness with `--check`).

The attribution formula is only correct while a handful of code-level
invariants hold *everywhere*: counter deltas must be wrap-aware, timing
logic must use monotonic clocks, published snapshots must stay
immutable, jitted kernels must stay pure. Generic linters cannot see
those — they are domain invariants — so `keplint`
(`kepler_tpu/analysis/`) encodes each one as an AST check. `make lint`
runs keplint, ruff (config committed in `pyproject.toml`), and mypy
(per-module strictness ratchet, also in `pyproject.toml`).

## Running

```
python -m kepler_tpu.analysis              # lint kepler_tpu/ (repo root)
python -m kepler_tpu.analysis path/ file.py
python -m kepler_tpu.analysis --list-rules
```

Exit codes: `0` clean (baselined findings tolerated), `1` new
violations, `2` usage errors.

## Suppressing

Append `# keplint: disable=KTL1xx` to the offending line (or put it on
a comment line directly above); several ids separate with commas, and a
bare `disable` suppresses every rule on that line. `# keplint:
disable-file=KTL1xx` anywhere in the file suppresses a rule file-wide.
Every suppression should say *why* in the surrounding comment.

## Scoping markers

Rules that need to know which code is special read declarative markers
instead of hardcoding module lists:

| Marker | Meaning |
| --- | --- |
| `# keplint: monotonic-only` (file-level) | KTL101: this module's timing math must never call the wall clock directly |
| `# keplint: hot-loop` (above a `def`) | KTL106: this function runs on the monitor refresh path; no sleeps/blocking I/O |
| `# keplint: guarded-by=_lock` (on an attribute assignment in `__init__`) | KTL108: writes to this attribute require `with self._lock` |
| `# keplint: requires-lock=_lock` (above a `def`) | KTL108: this function may only be called with the lock held; callers are checked too |

## Baseline ratchet

`.keplint.json` at the repo root freezes pre-existing violation counts
per `path::rule`. New violations fail; baselined ones pass; *fixed*
ones surface as stale entries — regenerate with
`python -m kepler_tpu.analysis --write-baseline` to ratchet the ceiling
down. The committed baseline is **empty**: every finding in the shipped
tree was fixed, not grandfathered (`tests/test_keplint.py` pins this).

The same ratchet stance applies to typing: `pyproject.toml` declares a
strict mypy tier (`config/`, `monitor/snapshot`, `fleet/wire`,
`fault/`, `analysis/` — fully typed, `disallow_untyped_defs`) and a
checked tier (`monitor/`, `fleet/`, `service/` —
`check_untyped_defs`); modules move *up* tiers, never down.

## Extending

Subclass `kepler_tpu.analysis.Rule`, set `id`/`name`/`severity`/
`summary`/`rationale`, implement `check(ctx)` over `ctx.tree`
(a parsed `ast.Module`), and decorate with `@register` in
`kepler_tpu/analysis/rules.py`. Add a good/bad fixture pair to
`tests/test_keplint.py` and regenerate this doc. Engine internals
(directives, baselines, file walking) live in
`kepler_tpu/analysis/engine.py`.

## Rule catalog
"""


def render() -> str:
    rules = all_rules()
    missing = [r.id for r in rules if not (r.summary and r.rationale)]
    if missing:
        raise SystemExit(
            f"gen_lint_docs: rules missing summary/rationale: {missing}")
    lines = [PREAMBLE]
    lines.append("| Rule | Name | Severity | Invariant |")
    lines.append("| --- | --- | --- | --- |")
    for r in rules:
        lines.append(f"| `{r.id}` | {r.name} | {r.severity} | "
                     f"{r.summary} |")
    lines.append("")
    for r in rules:
        lines.append(f"### {r.id} — {r.name}")
        lines.append("")
        lines.append(f"**Invariant:** {r.summary}.")
        lines.append("")
        lines.append(r.rationale)
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def main() -> int:
    text = render()
    if "--check" in sys.argv:
        try:
            with open(OUT_PATH, encoding="utf-8") as f:
                current = f.read()
        except OSError:
            current = ""
        if current != text:
            print(f"{OUT_PATH} is stale; run python hack/gen_lint_docs.py",
                  file=sys.stderr)
            return 1
        print(f"{OUT_PATH} is up to date")
        return 0
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w", encoding="utf-8") as f:
        f.write(text)
    print(f"wrote {OUT_PATH} ({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
