# kepler-tpu build/test/deploy targets (analog of the reference Makefile).

SHELL := /bin/bash
PYTHON ?= python
IMG ?= kepler-tpu
TAG ?= latest
CLUSTER_NAME ?= kepler-tpu-dev

VERSION := $(shell $(PYTHON) -c "from kepler_tpu.version import __version__; print(__version__)" 2>/dev/null || echo unknown)
GIT_COMMIT := $(shell git rev-parse --short HEAD 2>/dev/null || echo unknown)
GIT_BRANCH := $(shell git rev-parse --abbrev-ref HEAD 2>/dev/null || echo unknown)

.PHONY: all
all: test

# -- test ---------------------------------------------------------------------
# Tests run on a virtual 8-device CPU mesh (tests/conftest.py) so multi-chip
# sharding is exercised without TPU hardware — the analog of the reference's
# `go test -race` everywhere (Makefile:131).
.PHONY: test
test:
	$(PYTHON) -m pytest tests/ -q

.PHONY: test-verbose
test-verbose:
	$(PYTHON) -m pytest tests/ -v

.PHONY: chaos
chaos: ## fault-injection resilience subset (chaos marker) + randomized kepchaos sweep (25 schedules, shrinks on red) + diurnal scale soak
	$(PYTHON) -m pytest tests/ -q -m chaos
	$(PYTHON) -m kepler_tpu.chaos --seed 1 --schedules 25
	$(PYTHON) -m benchmarks.soak --agents 40 --seconds 36 --interval 3 \
		--workloads 20 --diurnal

.PHONY: chaos-long
chaos-long: ## extended kepchaos sweep: 100 randomized schedules from seed 1
	$(PYTHON) -m kepler_tpu.chaos --seed 1 --schedules 100

.PHONY: verify
verify: lint chaos multihost ## the lint surface plus the chaos subset and the multi-host dryrun — the PR gate's sibling path

.PHONY: bench
bench: ## north-star benchmark; prints one JSON line (BASELINE.json metric)
	$(PYTHON) bench.py

.PHONY: bench-scenarios
bench-scenarios: ## five BASELINE.json scenarios + temporal-fleet; budget GATE (exits nonzero on regression)
	$(PYTHON) benchmarks/scenarios.py

.PHONY: dryrun
dryrun: ## compile-check driver entry points on a virtual 8-device mesh
	$(PYTHON) __graft_entry__.py

.PHONY: multichip
multichip: ## node-sharded fleet window dryrun on 8 simulated devices (bit-equal vs single-device)
	$(PYTHON) -c "from __graft_entry__ import dryrun_fleet_sharded; dryrun_fleet_sharded(8)"

.PHONY: multihost
multihost: ## multi-host fleet window dryrun: virtual 2-host leg (bit-equal, capacity, host-death) + real 2-process leg (skips without the Gloo CPU backend)
	$(PYTHON) -c "from __graft_entry__ import dryrun_fleet_multihost; dryrun_fleet_multihost(2)"

.PHONY: introspect
introspect: ## smoke the introspection plane: /debug/window + /debug/fleet on a local aggregator
	$(PYTHON) hack/introspect_smoke.py

.PHONY: blackbox
blackbox: ## 2-replica kill+rejoin; assert the merged black-box timeline names the succession and is bit-deterministic
	$(PYTHON) hack/blackbox_smoke.py

# -- native -------------------------------------------------------------------
.PHONY: native
native: ## build the C++ batched procfs/sysfs scanner (ctypes, no pybind11)
	$(PYTHON) -c "from kepler_tpu.native import ensure_built; print(ensure_built(force=True))"

.PHONY: native-tsan
native-tsan: ## ThreadSanitizer pass over the native scanner (the -race analog)
	g++ -O1 -g -fsanitize=thread -std=c++17 -pthread -Wall -Wextra \
		kepler_tpu/native/src/scan.cpp \
		kepler_tpu/native/src/scan_tsan_test.cpp \
		-o /tmp/kepler_scan_tsan
	/tmp/kepler_scan_tsan

.PHONY: native-asan
native-asan: ## AddressSanitizer pass over the native scanner/renderer
	g++ -O1 -g -fsanitize=address -std=c++17 -pthread -Wall -Wextra \
		kepler_tpu/native/src/scan.cpp \
		kepler_tpu/native/src/scan_tsan_test.cpp \
		-o /tmp/kepler_scan_asan
	/tmp/kepler_scan_asan

# -- lint ---------------------------------------------------------------------
# keplint (stdlib-only, always runs) + ruff + mypy (committed configs in
# pyproject.toml; both skip with a notice when not installed so the lint
# surface degrades predictably instead of failing on toolchain absence).
# See docs/developer/static-analysis.md.
.PHONY: lint
lint:
	$(PYTHON) -m compileall -q kepler_tpu tests hack benchmarks
	$(PYTHON) -m kepler_tpu.analysis --device-tier --protocol-tier kepler_tpu hack benchmarks
	$(PYTHON) hack/gen_lint_docs.py --check
	$(PYTHON) hack/gen_fault_docs.py --check
	$(PYTHON) hack/gen_journal_docs.py --check
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check kepler_tpu tests hack; \
	else \
		echo "ruff not installed; skipping ruff"; \
	fi
	@if $(PYTHON) -c "import mypy" >/dev/null 2>&1; then \
		$(PYTHON) -m mypy kepler_tpu; \
	else \
		echo "mypy not installed; skipping typing ratchet"; \
	fi

.PHONY: keplint
keplint: ## project-native AST invariant checks only (host tiers; no device traces)
	$(PYTHON) -m kepler_tpu.analysis kepler_tpu hack benchmarks

.PHONY: kepljax
kepljax: ## device tier alone: trace registered programs, run KTL120-123
	$(PYTHON) -m kepler_tpu.analysis --device-tier --only=KTL120,KTL121,KTL122,KTL123 kepler_tpu

.PHONY: kepljax-snapshots
kepljax-snapshots: ## regenerate the KTL123 golden program fingerprints (.kepljax.json)
	$(PYTHON) -m kepler_tpu.analysis --update-snapshots

.PHONY: protocheck
protocheck: ## kepmc protocol tier alone: exhaustively explore the registered protocol models, run KTL130-132
	$(PYTHON) -m kepler_tpu.analysis --protocol-tier --only=KTL130,KTL131,KTL132 kepler_tpu

.PHONY: keplint-sarif
keplint-sarif: ## keplint + device/protocol-tier findings as SARIF 2.1.0 (CI annotation feed; stdout is pipeable JSON)
	@$(PYTHON) -m kepler_tpu.analysis --device-tier --protocol-tier --format=sarif kepler_tpu hack benchmarks

.PHONY: keplint-baseline
keplint-baseline: ## refreeze the keplint baseline (after fixing findings)
	$(PYTHON) -m kepler_tpu.analysis --write-baseline

.PHONY: gen-lint-docs
gen-lint-docs: ## regenerate docs/developer/static-analysis.md from the registry
	$(PYTHON) hack/gen_lint_docs.py

.PHONY: gen-fault-docs
gen-fault-docs: ## regenerate the resilience.md fault-site table from fault.SITE_CATALOG
	$(PYTHON) hack/gen_fault_docs.py

.PHONY: gen-journal-docs
gen-journal-docs: ## regenerate the observability.md journal-kind table from journal.KIND_CATALOG
	$(PYTHON) hack/gen_journal_docs.py

# -- docs ---------------------------------------------------------------------
.PHONY: gen-metric-docs
gen-metric-docs: ## regenerate docs/user/metrics.md from the live collectors
	$(PYTHON) hack/gen_metric_docs.py

.PHONY: gen-config-docs
gen-config-docs: ## regenerate docs/user/configuration.md from the Config schema
	$(PYTHON) hack/gen_config_docs.py

.PHONY: check-metric-docs
check-metric-docs:
	$(PYTHON) hack/gen_metric_docs.py --check
	$(PYTHON) hack/gen_config_docs.py --check
	$(PYTHON) hack/gen_lint_docs.py --check
	$(PYTHON) hack/gen_fault_docs.py --check
	$(PYTHON) hack/gen_journal_docs.py --check

# -- run ----------------------------------------------------------------------
.PHONY: run
run: ## run the node agent against the real host (needs RAPL access)
	$(PYTHON) -m kepler_tpu.cmd.main

.PHONY: run-fake
run-fake: ## run with the fake meter + stdout exporter (no hardware needed)
	$(PYTHON) -m kepler_tpu.cmd.main \
		--config.file=compose/dev/kepler/etc/kepler/config.yaml \
		--exporter.stdout --no-kube.enable --aggregator.endpoint=

.PHONY: run-aggregator
run-aggregator: ## run the TPU fleet aggregator
	$(PYTHON) -m kepler_tpu.cmd.aggregator --aggregator.enable

# -- image / deploy -----------------------------------------------------------
.PHONY: image
image:
	docker build -t $(IMG):$(TAG) .

.PHONY: compose-up
compose-up: ## dev stack: agent + aggregator + prometheus + grafana
	cd compose/dev && docker compose up --build -d

.PHONY: compose-down
compose-down:
	cd compose/dev && docker compose down -v

.PHONY: monitoring-up
monitoring-up: ## standalone prometheus+grafana overlay (compose/monitoring)
	cd compose/monitoring && docker compose up -d

.PHONY: monitoring-down
monitoring-down:
	cd compose/monitoring && docker compose down -v

.PHONY: cluster-e2e
cluster-e2e: ## scrape assertions against the deployed kind cluster
	hack/cluster.sh e2e

.PHONY: cluster-up
cluster-up: ## kind dev cluster (hack/cluster.sh)
	CLUSTER_NAME=$(CLUSTER_NAME) hack/cluster.sh up

.PHONY: cluster-down
cluster-down:
	CLUSTER_NAME=$(CLUSTER_NAME) hack/cluster.sh down

.PHONY: deploy
deploy: ## build image, load into kind, apply manifests
	CLUSTER_NAME=$(CLUSTER_NAME) IMG=$(IMG) TAG=$(TAG) hack/cluster.sh deploy

.PHONY: undeploy
undeploy:
	kubectl delete -k manifests/k8s || true

.PHONY: version
version:
	@echo "version=$(VERSION) commit=$(GIT_COMMIT) branch=$(GIT_BRANCH)"

.PHONY: help
help:
	@grep -E '^[a-zA-Z_-]+:.*?## .*$$' $(MAKEFILE_LIST) | \
		awk 'BEGIN {FS = ":.*?## "}; {printf "  \033[36m%-18s\033[0m %s\n", $$1, $$2}'
