"""Packed-transfer fleet program: one-array-in/one-array-out parity with
the unpacked program (f16 scatter-back within the 0.5%-of-RAPL budget)."""

import jax
import numpy as np
import pytest

import jax.numpy as jnp

from kepler_tpu.models import init_mlp
from kepler_tpu.parallel import (
    assemble_fleet_batch,
    make_fleet_program,
    make_mesh,
    run_fleet_attribution,
)
from kepler_tpu.parallel.fleet import MODE_MODEL, NodeReport
from kepler_tpu.parallel.packed import (
    make_packed_fleet_program,
    pack_fleet_inputs,
    unpack_fleet_watts,
)


def make_batch(n_reports=16, z=2, workload_bucket=16):
    rng = np.random.default_rng(0)
    reports = []
    for i in range(n_reports):
        w = int(rng.integers(2, 12))
        cpu = rng.uniform(0.1, 5.0, w).astype(np.float32)
        reports.append(NodeReport(
            node_name=f"n{i}",
            zone_deltas_uj=rng.uniform(1e7, 1e8, z).astype(np.float32),
            zone_valid=np.ones(z, bool),
            usage_ratio=0.6,
            cpu_deltas=cpu,
            workload_ids=[f"n{i}-w{j}" for j in range(w)],
            node_cpu_delta=float(cpu.sum()),
            dt_s=5.0,
            mode=MODE_MODEL if i % 2 else 0,
        ))
    return assemble_fleet_batch(reports, n_zones=z, node_bucket=8,
                                workload_bucket=workload_bucket)


@pytest.mark.parametrize("backend", ["einsum", "pallas"])
def test_packed_matches_unpacked(backend):
    mesh = make_mesh()
    batch = make_batch()
    n, w, z = batch.shape
    params = init_mlp(jax.random.PRNGKey(0), n_zones=z)
    packed_prog = make_packed_fleet_program(
        mesh, n_workloads=w, n_zones=z, model_mode="mlp", backend=backend)
    out = np.asarray(packed_prog(params, jnp.asarray(pack_fleet_inputs(batch))))
    wl_watts, node_watts = unpack_fleet_watts(out)
    assert wl_watts.shape == (n, w, z)
    assert node_watts.shape == (n, z)

    ref = run_fleet_attribution(
        make_fleet_program(mesh, model_mode="mlp"), batch, params)
    ref_wl = np.asarray(ref.workload_power_uw) * 1e-6
    ref_node = np.asarray(ref.node_active_power_uw) * 1e-6
    # f16 wire format: ~0.05% relative error, inside the 0.5% budget
    np.testing.assert_allclose(wl_watts.astype(np.float64), ref_wl,
                               rtol=2e-3, atol=1e-4)
    np.testing.assert_allclose(node_watts.astype(np.float64), ref_node,
                               rtol=2e-3, atol=1e-4)


def test_padding_rides_as_nan_and_returns_zero():
    mesh = make_mesh()
    batch = make_batch()
    n, w, z = batch.shape
    packed = pack_fleet_inputs(batch)
    assert np.isnan(packed[0, :w][~batch.workload_valid[0]]).all()
    assert not np.isnan(packed[0, :w][batch.workload_valid[0]]).any()
    prog = make_packed_fleet_program(mesh, n_workloads=w, n_zones=z,
                                     model_mode=None)
    wl_watts, _ = unpack_fleet_watts(
        np.asarray(prog(None, jnp.asarray(packed))))
    assert (wl_watts[~batch.workload_valid] == 0).all()
    assert np.isfinite(wl_watts).all()


def test_packed_rejects_unknown_backend():
    with pytest.raises(ValueError, match="backend"):
        make_packed_fleet_program(make_mesh(), 16, 2, backend="cuda")
