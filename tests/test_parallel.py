"""Parallel-layer tests on the 8-device virtual CPU mesh: mesh construction,
fleet batch padding/bucketing, sharded fleet attribution (ratio, mixed
ratio+model), distributed dp×tp train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kepler_tpu.models import init_mlp
from kepler_tpu.models.train import create_train_state, make_optimizer
from kepler_tpu.parallel import (
    MODE_MODEL,
    MODE_RATIO,
    NodeReport,
    assemble_fleet_batch,
    make_distributed_train_step,
    make_fleet_program,
    make_mesh,
    mlp_param_shardings,
    run_fleet_attribution,
    shard_train_state,
)
from kepler_tpu.models.features import NUM_FEATURES


def report(name, w=5, mode=MODE_RATIO, zones=2, seed=0):
    rng = np.random.default_rng(seed)
    cpu = rng.uniform(0.1, 5.0, w).astype(np.float32)
    return NodeReport(
        node_name=name,
        zone_deltas_uj=rng.uniform(1e7, 1e8, zones).astype(np.float32),
        zone_valid=np.ones(zones, bool),
        usage_ratio=0.6,
        cpu_deltas=cpu,
        workload_ids=[f"{name}-w{i}" for i in range(w)],
        node_cpu_delta=float(cpu.sum()),
        dt_s=5.0,
        mode=mode,
    )


class TestMesh:
    def test_eight_virtual_devices(self):
        assert jax.device_count() == 8

    def test_default_mesh_all_devices(self):
        mesh = make_mesh()
        assert mesh.devices.shape == (8,)
        assert mesh.axis_names == ("node",)

    def test_2d_mesh(self):
        mesh = make_mesh([4, 2], ["node", "model"])
        assert mesh.devices.shape == (4, 2)

    def test_minus_one_inferred(self):
        mesh = make_mesh([-1, 2], ["node", "model"])
        assert mesh.devices.shape == (4, 2)

    def test_bad_shape_rejected(self):
        with pytest.raises(ValueError):
            make_mesh([3], ["node"])


class TestFleetAssembly:
    def test_padding_and_masks(self):
        batch = assemble_fleet_batch(
            [report("a", w=3), report("b", w=10)],
            n_zones=2, node_bucket=8, workload_bucket=16)
        n, w, z = batch.shape
        assert (n, w, z) == (8, 16, 2)
        assert batch.n_nodes == 2
        assert batch.workload_counts[:2] == [3, 10]
        assert batch.workload_valid[0].sum() == 3
        assert batch.workload_valid[1].sum() == 10
        assert batch.workload_valid[2:].sum() == 0  # padded nodes
        assert batch.cpu_deltas[0, 3:].sum() == 0.0

    def test_bucketing_stabilizes_shapes(self):
        b1 = assemble_fleet_batch([report("a", w=3)], 2, 8, 16)
        b2 = assemble_fleet_batch([report("a", w=9), report("b", w=12)],
                                  2, 8, 16)
        assert b1.shape == b2.shape  # same jit cache entry

    def test_zone_count_mismatch_rejected(self):
        with pytest.raises(ValueError, match="zones"):
            assemble_fleet_batch([report("a", zones=3)], n_zones=2)

    def test_empty_fleet(self):
        batch = assemble_fleet_batch([], n_zones=2)
        assert batch.n_nodes == 0
        assert batch.workload_valid.sum() == 0


class TestShardedAttribution:
    def test_ratio_fleet_matches_unsharded(self):
        mesh = make_mesh()
        program = make_fleet_program(mesh)
        reports = [report(f"n{i}", w=4 + i, seed=i) for i in range(5)]
        batch = assemble_fleet_batch(reports, n_zones=2, node_bucket=8,
                                     workload_bucket=16)
        result = run_fleet_attribution(program, batch)
        n, w, z = batch.shape
        assert result.workload_energy_uj.shape == (n, w, z)
        # conservation per real node
        for i in range(batch.n_nodes):
            total = np.asarray(result.workload_energy_uj[i]).sum(axis=0)
            active = np.asarray(result.node_active_uj[i])
            np.testing.assert_allclose(total, active, rtol=1e-4)
        # padded nodes contribute zero
        assert np.asarray(
            result.workload_energy_uj[batch.n_nodes:]).sum() == 0.0

    def test_sharding_placement(self):
        mesh = make_mesh()
        program = make_fleet_program(mesh)
        batch = assemble_fleet_batch(
            [report(f"n{i}") for i in range(8)], n_zones=2,
            node_bucket=8, workload_bucket=16)
        result = run_fleet_attribution(program, batch)
        sharding = result.workload_energy_uj.sharding
        # node axis actually sharded across the mesh
        assert sharding.spec[0] == "node"

    def test_mixed_fleet_model_nodes(self):
        mesh = make_mesh()
        program = make_fleet_program(mesh, model_mode="linear")
        from kepler_tpu.models import init_linear
        params = init_linear(jax.random.PRNGKey(0), n_zones=2)
        reports = [report("rapl", mode=MODE_RATIO, seed=1),
                   report("norapl", mode=MODE_MODEL, seed=2)]
        batch = assemble_fleet_batch(reports, n_zones=2, node_bucket=8,
                                     workload_bucket=8)
        result = run_fleet_attribution(program, batch, params)
        # ratio node: conservation holds
        total0 = np.asarray(result.workload_energy_uj[0]).sum(axis=0)
        np.testing.assert_allclose(total0, np.asarray(
            result.node_active_uj[0]), rtol=1e-4)
        # model node: node power equals Σ model workload power, idle = 0
        np.testing.assert_allclose(
            np.asarray(result.node_power_uw[1]),
            np.asarray(result.workload_power_uw[1]).sum(axis=0), rtol=1e-4)
        assert np.asarray(result.node_idle_uj[1]).sum() == 0.0


class TestDistributedTraining:
    def test_dp_tp_train_step_runs_and_learns(self):
        mesh = make_mesh([4, 2], ["node", "model"])
        optimizer = make_optimizer(learning_rate=1e-2)
        params = init_mlp(jax.random.PRNGKey(0), n_zones=1, hidden=32)
        state = shard_train_state(
            create_train_state(params, optimizer), mesh)
        # check TP placement took effect
        assert state.params["w0"].sharding.spec == ("model",) or \
            state.params["w0"].sharding.spec[1] == "model"

        step = make_distributed_train_step(mesh, optimizer)
        key = jax.random.PRNGKey(3)
        B, W = 16, 8
        cpu = jax.random.uniform(key, (B, W), minval=0.0, maxval=5.0)
        from kepler_tpu.models import build_features
        feats = build_features(cpu, jnp.ones((B, W), bool),
                               cpu.sum(axis=1), jnp.full((B,), 0.5),
                               jnp.full((B,), 5.0))
        valid = jnp.ones((B, W), bool)
        target = (cpu / 5.0 * 20.0)[..., None]
        losses = []
        for _ in range(60):
            state, loss = step(state, feats, valid, target)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]
        assert int(state.step) == 60

    def test_param_shardings_layout(self):
        mesh = make_mesh([4, 2], ["node", "model"])
        shardings = mlp_param_shardings(mesh)
        assert shardings["w0"].spec == (None, "model")
        assert shardings["w1"].spec == ("model", None)


class TestAccuracyModeServing:
    def test_accuracy_mode_tightens_estimator_error(self):
        """accuracy_mode=True must serve the estimator at f32/highest —
        on a warm-started exact-linear MLP, its fleet watts land within
        the 0.5% budget of the f64 truth where the default bf16 mode has
        visible rounding error."""
        import numpy as np

        from kepler_tpu.models import build_features, init_mlp
        from kepler_tpu.models.train import warm_start_wide
        from kepler_tpu.parallel.aggregator_core import make_fleet_program
        from kepler_tpu.parallel.fleet import MODE_MODEL

        mesh = make_mesh()
        n, w, z = 8, 16, 2
        rng = np.random.default_rng(0)
        cpu = jnp.asarray(rng.uniform(0.5, 5.0, (n, w)), jnp.float32)
        valid = jnp.ones((n, w), bool)
        node_cpu = cpu.sum(axis=1)
        ratio = jnp.full((n,), 0.6, jnp.float32)
        dt = jnp.full((n,), 5.0, jnp.float32)
        feats = build_features(cpu, valid, node_cpu, ratio, dt)
        k = 4.0  # watts per cpu-second
        target = jnp.broadcast_to((cpu * k)[..., None], (n, w, z))
        with jax.default_matmul_precision("highest"):
            params = warm_start_wide(init_mlp(jax.random.PRNGKey(0), z),
                                     feats, valid, target)

        args = (jnp.asarray(rng.uniform(1e6, 1e8, (n, z)), jnp.float32),
                jnp.ones((n, z), bool), ratio, cpu, valid, node_cpu, dt,
                jnp.full((n,), MODE_MODEL, jnp.int32))
        want = np.asarray(cpu, np.float64) * k * 1e6  # µW, per zone

        def max_err(accuracy_mode):
            prog = make_fleet_program(mesh, model_mode="mlp",
                                      accuracy_mode=accuracy_mode)
            res = prog(params, *args)
            got = np.asarray(res.workload_power_uw, np.float64)[..., 0]
            return float(np.max(np.abs(got - want) / want))

        acc = max_err(True)
        assert acc <= 0.005, acc  # the validated budget
        # CPU test mesh note: XLA:CPU computes f32 matmuls in f32 even at
        # default precision, so the bf16-mode gap only appears on TPU —
        # what this test pins everywhere is the accuracy-mode path staying
        # within budget and compiling with the precision wrapper applied.
