"""Device-resident pipelined fleet windows (ISSUE 5).

Correctness contracts of `kepler_tpu.fleet.window` + the pipelined
`Aggregator.aggregate_once`:

* depth-2 pipelining publishes BIT-IDENTICAL windows to the serial
  (depth-1) cycle, per mode, under churn (joins, drops, restarts, zone
  changes) — the strongest possible statement that the resident batch,
  delta H2D, ping-pong donation, and sparse model evaluation change
  scheduling, never results;
* shutdown (and an emptied fleet) drains in-flight windows
  deterministically;
* a mid-pipeline drop/join never mixes stale rows into a fresh window;
* donated-buffer reuse never aliases a window still being read (the
  churn stress would corrupt the bit-exact comparison if it did);
* bucket ladders grow geometrically and shrink only after the
  hysteresis window; delta-H2D row accounting matches what changed.
"""

from __future__ import annotations

import numpy as np
import pytest

from kepler_tpu.fleet.aggregator import Aggregator, _Stored
from kepler_tpu.fleet.window import BucketLadder
from kepler_tpu.parallel.fleet import MODE_MODEL, MODE_RATIO, NodeReport
from kepler_tpu.parallel.mesh import make_mesh
from kepler_tpu.server.http import APIServer

ZONES = ("package", "dram")
ZONES_WIDE = ("package", "dram", "uncore")


def make_report(name: str, seed: int, w: int = 4, zones=ZONES,
                mode: int = MODE_RATIO) -> NodeReport:
    rng = np.random.default_rng(abs(hash((name, seed))) % (2**32))
    cpu = rng.uniform(0.1, 5.0, w).astype(np.float32)
    z = len(zones)
    return NodeReport(
        node_name=name,
        zone_deltas_uj=rng.uniform(1e7, 5e8, z).astype(np.float32),
        zone_valid=np.ones(z, bool),
        usage_ratio=float(rng.uniform(0.2, 0.9)),
        cpu_deltas=cpu,
        workload_ids=[f"{name}-w{k}" for k in range(w)],
        node_cpu_delta=float(cpu.sum()),
        dt_s=5.0,
        mode=mode,
        workload_kinds=np.ones(w, np.int8),
    )


def make_agg(depth: int, **kw) -> Aggregator:
    kw.setdefault("model_mode", "mlp")
    kw.setdefault("node_bucket", 8)
    kw.setdefault("workload_bucket", 8)
    kw.setdefault("stale_after", 1e9)
    if "clock" not in kw:
        ticks = [1e9]
        kw["clock"] = lambda: ticks[0]
        agg = Aggregator(APIServer(), pipeline_depth=depth, **kw)
        agg.test_clock = ticks  # driven by run_schedule/seed helpers
    else:
        agg = Aggregator(APIServer(), pipeline_depth=depth, **kw)
    agg._mesh = make_mesh()
    return agg


def churn_schedule(n_windows: int, base_nodes: int = 6) -> list[dict]:
    """Per-window {name: (seed, zones, mode, seq, run)} with joins,
    drops, a restart, and a zone-set change sprinkled in."""
    schedules = []
    for win in range(n_windows):
        sched = {}
        for i in range(base_nodes):
            name = f"n{i:02d}"
            if win % 5 == 2 and i == 1:
                continue  # n01 drops out this window
            zones = ZONES_WIDE if (win >= 4 and i == 2) else ZONES
            run = "r2" if (win >= 3 and i == 3) else "r1"
            seq = win + 1 if run == "r1" else win - 1  # restart resets
            mode = MODE_MODEL if i % 2 else MODE_RATIO
            sched[name] = (win * 100 + i, zones, mode, max(1, seq), run)
        if win >= 3:  # a late joiner
            sched["n99"] = (win * 100 + 99, ZONES, MODE_MODEL,
                            win - 2, "r1")
        schedules.append(sched)
    return schedules


def seed_window(agg: Aggregator, sched: dict, now: float) -> None:
    for name, (seed, zones, mode, seq, run) in sched.items():
        rep = make_report(name, seed, zones=zones, mode=mode)
        agg._reports[name] = _Stored(report=rep, zone_names=tuple(zones),
                                     received=now, seq=seq, run=run)
    for name in list(agg._reports):
        if name not in sched:
            del agg._reports[name]


def run_schedule(agg: Aggregator, schedules: list[dict]) -> list:
    published = []
    for sched in schedules:
        agg.test_clock[0] += 5.0
        seed_window(agg, sched, agg.test_clock[0])
        result = agg.aggregate_once()
        if result is not None:
            published.append(result)
    tail = agg._drain_pipeline()
    if tail is not None:
        published.append(tail)
    return published


def assert_windows_equal(a, b) -> None:
    assert set(a.names) == set(b.names)
    assert list(a.zones) == list(b.zones)
    for name in a.names:
        i, j = a.rows[name], b.rows[name]
        assert int(a.mode[i]) == int(b.mode[j]), name
        np.testing.assert_array_equal(a.node_power_uw[i],
                                      b.node_power_uw[j], err_msg=name)
        np.testing.assert_array_equal(a.node_energy_uj[i],
                                      b.node_energy_uj[j], err_msg=name)
        np.testing.assert_array_equal(a.node_joules_total[i],
                                      b.node_joules_total[j], err_msg=name)
        assert a.counts[i] == b.counts[j]
        assert a.workload_ids[i] == b.workload_ids[j]
        ra, rb = a.render_node(name), b.render_node(name)
        assert ra == rb, name


class TestPipelineBitExact:
    @pytest.mark.parametrize("model_mode", [None, "mlp"])
    def test_depth2_matches_serial_under_churn(self, model_mode):
        schedules = churn_schedule(9)
        serial = run_schedule(make_agg(1, model_mode=model_mode),
                              schedules)
        piped = run_schedule(make_agg(2, model_mode=model_mode),
                             schedules)
        assert len(serial) == len(schedules)
        assert len(piped) == len(schedules)
        for a, b in zip(serial, piped):
            assert a.timestamp == b.timestamp
            assert_windows_equal(a, b)

    def test_accuracy_mode_legacy_path_pipelines_bit_exact(self):
        schedules = churn_schedule(6)
        serial = run_schedule(make_agg(1, accuracy_mode=True), schedules)
        piped = run_schedule(make_agg(2, accuracy_mode=True), schedules)
        assert len(piped) == len(serial) == len(schedules)
        for a, b in zip(serial, piped):
            assert_windows_equal(a, b)

    def test_temporal_mode_pipelines(self):
        schedules = churn_schedule(4)
        piped = run_schedule(
            make_agg(2, model_mode="temporal", history_window=4),
            schedules)
        assert len(piped) == len(schedules)
        for res in piped:
            for name in res.names:
                node = res.render_node(name)
                assert all(np.isfinite(w["power_uw"]).all()
                           for w in node["workloads"])

    def test_packed_default_within_budget_of_accuracy_path(self):
        # the f16 packed default vs the einsum-f32 accuracy path: node
        # power must agree within the 0.5% budget (ratio-only fleet —
        # untrained estimators have near-zero watts, useless for a
        # relative bound)
        schedules = churn_schedule(3)
        packed = run_schedule(make_agg(1, model_mode=None), schedules)
        exact = run_schedule(
            make_agg(1, model_mode=None, accuracy_mode=True), schedules)
        for a, b in zip(packed, exact):
            for name in a.names:
                pa = a.node_power_uw[a.rows[name]]
                pb = b.node_power_uw[b.rows[name]]
                np.testing.assert_allclose(pa, pb, rtol=5e-3, atol=1.0)


class TestPipelineDrain:
    def test_shutdown_drains_in_flight_window(self):
        agg = make_agg(2)
        seed_window(agg, churn_schedule(1)[0], 1e9)
        assert agg.aggregate_once() is None  # in flight, not published
        assert len(agg._inflight) == 1
        agg.shutdown()
        assert not agg._inflight
        with agg._results_lock:
            assert agg._results is not None
        assert agg._stats["attributions_total"] == 1

    def test_empty_fleet_drains_instead_of_rotting(self):
        agg = make_agg(2, stale_after=10.0, clock=lambda: clock[0])
        clock = [1e9]
        seed_window(agg, churn_schedule(1)[0], clock[0])
        assert agg.aggregate_once() is None
        clock[0] += 100.0  # everything stale now
        result = agg.aggregate_once()  # empty fleet → drain
        assert result is not None
        assert not agg._inflight
        assert agg._stats["attributions_total"] == 1

    def test_run_loop_exit_drains(self):
        from kepler_tpu.service.lifecycle import CancelContext

        agg = make_agg(2, interval=0.01)
        seed_window(agg, churn_schedule(1)[0], 1e9)
        ctx = CancelContext()
        import threading

        t = threading.Thread(target=agg.run, args=(ctx,))
        t.start()
        import time as _t

        deadline = _t.monotonic() + 10
        while (agg._stats["attributions_total"] == 0
               and _t.monotonic() < deadline):
            _t.sleep(0.02)
        ctx.cancel()
        t.join(timeout=10)
        assert not t.is_alive()
        assert not agg._inflight

    def test_published_results_at_most_one_interval_stale(self):
        agg = make_agg(2)
        schedules = churn_schedule(4)
        stamps = []
        for sched in schedules:
            agg.test_clock[0] += 5.0
            seed_window(agg, sched, agg.test_clock[0])
            res = agg.aggregate_once()
            stamps.append((agg.test_clock[0],
                           None if res is None else res.timestamp))
        for dispatched_at, published_ts in stamps[1:]:
            assert published_ts == dispatched_at - 5.0  # exactly 1 behind


class TestMidPipelineChurn:
    def test_drop_join_never_mixes_stale_rows(self):
        agg = make_agg(2)
        now = 1e9
        win1 = {f"n{i}": (i, ZONES, i % 2, 1, "r1") for i in range(4)}
        seed_window(agg, win1, now)
        agg.aggregate_once()
        # n2 drops; n5 joins — dispatched while window 1 is in flight
        # (fresh data seeds: the re-reports carry NEW values)
        win2 = {name: (seed + 10, z, m, 2, r)
                for name, (seed, z, m, _s, r) in win1.items()
                if name != "n2"}
        win2["n5"] = (50, ZONES, MODE_RATIO, 1, "r1")
        now += 5.0
        seed_window(agg, win2, now)
        first = agg.aggregate_once()  # publishes window 1
        assert set(first.names) == set(win1)
        second = agg._drain_pipeline()  # publishes window 2
        assert set(second.names) == set(win2)
        assert "n2" not in second.rows
        assert "n5" in second.rows
        # fresh node's watts actually computed (not a stale zero row)
        n5 = second.render_node("n5")
        assert any(np.asarray(w["power_uw"]).sum() != 0.0
                   for w in n5["workloads"])
        # n0's re-report (new seed → new data) actually refreshed
        assert not np.array_equal(
            first.node_power_uw[first.rows["n0"]],
            second.node_power_uw[second.rows["n0"]])

    def test_returning_node_gets_fresh_row_not_old_buffer_contents(self):
        # absent for one window (row cleared), back with NEW data: the
        # published watts must match a from-scratch aggregator fed the
        # same final window — old resident contents must never leak
        schedules = [
            {f"n{i}": (i, ZONES, MODE_RATIO, 1, "r1") for i in range(3)},
            {f"n{i}": (10 + i, ZONES, MODE_RATIO, 2, "r1")
             for i in range(2)},  # n2 absent
            {f"n{i}": (20 + i, ZONES, MODE_RATIO, 3, "r1")
             for i in range(3)},  # n2 back, new data
        ]
        published = run_schedule(make_agg(2, model_mode=None), schedules)
        fresh = run_schedule(make_agg(1, model_mode=None), [schedules[-1]])
        got = published[-1].render_node("n2")
        want = fresh[-1].render_node("n2")
        assert got["node_power_uw"] == want["node_power_uw"]
        assert [w["power_uw"] for w in got["workloads"]] == \
            [w["power_uw"] for w in want["workloads"]]


class TestBucketLadder:
    def test_grow_is_immediate_and_geometric(self):
        ladder = BucketLadder(8, shrink_after=3)
        assert ladder.fit(5) == 8
        assert ladder.fit(9) == 16
        assert ladder.fit(100) == 128

    def test_align_rounds_base_and_survives_growth(self):
        ladder = BucketLadder(6, shrink_after=3, align=4)
        assert ladder.base == 8
        assert ladder.fit(9) % 4 == 0

    def test_shrink_needs_consecutive_underhalf_windows(self):
        ladder = BucketLadder(8, shrink_after=3)
        ladder.fit(100)  # → 128
        assert ladder.fit(10) == 128  # under half #1
        assert ladder.fit(10) == 128  # under half #2
        assert ladder.fit(100) == 128  # back over half: streak resets
        assert ladder.fit(10) == 128
        assert ladder.fit(10) == 128
        assert ladder.fit(10) == 64  # third consecutive → one step down
        assert ladder.fit(10) == 64  # streak restarts after a shrink

    def test_never_shrinks_below_base(self):
        ladder = BucketLadder(8, shrink_after=1)
        ladder.fit(8)
        for _ in range(10):
            ladder.fit(1)
        assert ladder.bucket == 8


class TestDeltaAccounting:
    def test_unchanged_fleet_uploads_zero_rows(self):
        agg = make_agg(1)
        sched = {f"n{i}": (i, ZONES, i % 2, 1, "r1") for i in range(5)}
        now = 1e9
        seed_window(agg, sched, now)
        agg.aggregate_once()
        assert agg._stats["last_h2d_rows"] == 5  # rebuild packs all
        # same (run, seq) → nothing re-uploaded, on every ring buffer
        for _ in range(3):
            agg.aggregate_once()
            assert agg._stats["last_h2d_rows"] == 0
        # one change → staged once per ring buffer it must reach, then 0
        sched["n3"] = (99, ZONES, 1, 2, "r1")
        seed_window(agg, sched, now)
        staged = []
        for _ in range(4):
            agg.aggregate_once()
            staged.append(agg._stats["last_h2d_rows"])
        assert staged[0] == 1 and staged[-1] == 0
        assert sum(staged) == len(agg._engine._buffers)
        # the first delta compiled the scatter-update once; further
        # same-sized deltas never recompile
        compiles = agg._stats["window_compiles_total"]
        sched["n3"] = (123, ZONES, 1, 3, "r1")
        seed_window(agg, sched, now)
        agg.aggregate_once()
        agg.aggregate_once()
        assert agg._stats["window_compiles_total"] == compiles

    def test_pre_nonce_rows_always_reupload(self):
        agg = make_agg(1)
        sched = {"n0": (1, ZONES, 0, 0, "")}  # no run nonce, seq 0
        seed_window(agg, sched, 1e9)
        agg.aggregate_once()
        agg.aggregate_once()
        assert agg._stats["last_h2d_rows"] == 1

    def test_fleet_growth_compiles_once_per_rung(self):
        agg = make_agg(1, node_bucket=8)
        now = 1e9
        sched = {f"n{i}": (i, ZONES, 0, 1, "r1") for i in range(5)}
        seed_window(agg, sched, now)
        agg.aggregate_once()
        base_compiles = agg._stats["window_compiles_total"]
        # grow past the node bucket: one new program + one new update
        sched.update({f"m{i}": (i, ZONES, 0, 1, "r1") for i in range(8)})
        seed_window(agg, sched, now)
        agg.aggregate_once()
        grown = agg._stats["window_compiles_total"]
        assert grown > base_compiles
        # repeat windows at the new rung: no further compiles
        agg.aggregate_once()
        agg.aggregate_once()
        assert agg._stats["window_compiles_total"] == grown


class TestShardedWindow:
    """ISSUE 7: the packed window sharded over the device mesh
    (ShardedWindowEngine — per-shard rings, sticky node→shard
    assignment, per-shard delta H2D, one sharded dispatch)."""

    def test_rung0_engine_is_sharded_on_multidevice_mesh(self):
        import jax

        from kepler_tpu.fleet.window import ShardedWindowEngine

        agg = make_agg(1)
        seed_window(agg, churn_schedule(1)[0], 1e9)
        agg.aggregate_once()
        assert isinstance(agg._engine, ShardedWindowEngine)
        assert agg._engine.n_shards == len(jax.devices())
        assert agg._stats["window_shards"] == len(jax.devices())
        assert len(agg._stats["last_h2d_shards"]) == len(jax.devices())
        health = agg.window_health()
        assert health["rung_name"] == "packed-sharded-pipelined"
        assert health["shards"] == len(jax.devices())
        families = {f.name: f for f in agg.collect()}
        shards = families["kepler_fleet_window_shards"]
        assert shards.samples[0].value == len(jax.devices())
        agg.shutdown()

    def test_2d_mesh_falls_back_to_unsharded_engine(self):
        from kepler_tpu.fleet.window import (PackedWindowEngine,
                                             ShardedWindowEngine)

        agg = make_agg(1)
        agg._mesh = make_mesh([4, 2], ["node", "model"])
        seed_window(agg, churn_schedule(1)[0], 1e9)
        agg.aggregate_once()
        assert type(agg._engine) is PackedWindowEngine
        assert not isinstance(agg._engine, ShardedWindowEngine)
        assert agg._stats["window_shards"] == 1
        assert agg.window_health()["rung_name"] == "packed-pipelined"
        agg.shutdown()

    @pytest.mark.parametrize("depth", [1, 2])
    def test_sharded_matches_single_device_bit_exact_under_churn(
            self, depth):
        import jax

        schedules = churn_schedule(9)
        sharded = run_schedule(make_agg(depth), schedules)
        single = make_agg(1)
        single._mesh = make_mesh([1], devices=jax.devices()[:1])
        reference = run_schedule(single, schedules)
        assert len(sharded) == len(reference) == len(schedules)
        for a, b in zip(reference, sharded):
            assert a.timestamp == b.timestamp
            assert_windows_equal(a, b)

    def test_sticky_assignment_join_drop_rejoin_touch_one_shard(self):
        """A join (and a drop, and a rejoin) stages rows ONLY to the
        owning shard: every other shard sees zero H2D and no engine
        compiles — surviving nodes never migrate."""
        agg = make_agg(1, node_bucket=32)  # shard bucket 4 on 8 devices
        base = {f"n{i:02d}": (i, ZONES, i % 2, 1, "r1") for i in range(10)}
        now = 1e9
        seed_window(agg, base, now)
        agg.aggregate_once()
        engine = agg._engine
        slots = len(engine._buffers)
        # warm the delta path (every shard stages once, the scatter-
        # update compiles its one shared key), then settle to zero H2D
        warm = {name: (seed + 1000, z, m, 2, r)
                for name, (seed, z, m, _s, r) in base.items()}
        seed_window(agg, warm, now)
        for _ in range(slots):
            agg.aggregate_once()
        agg.aggregate_once()
        assert agg._stats["last_h2d_rows"] == 0
        base = warm
        home = dict(engine._shard_of)
        compiles = agg._stats["window_compiles_total"]

        joined = dict(base)
        joined["n99"] = (99, ZONES, MODE_RATIO, 1, "r1")
        seed_window(agg, joined, now)
        touched = set()
        for _ in range(slots + 1):
            agg.aggregate_once()
            staged = agg._stats["last_h2d_shards"]
            touched |= {k for k, n in enumerate(staged) if n}
        # the join staged on exactly its shard (once per ring slot),
        # nothing recompiled, and nobody else moved or restaged
        assert touched == {engine._shard_of["n99"]}
        assert agg._stats["window_compiles_total"] == compiles
        assert {n: k for n, k in engine._shard_of.items()
                if n != "n99"} == home

        n99_shard = engine._shard_of["n99"]
        seed_window(agg, base, now)  # n99 drops: its shard clears the row
        touched = set()
        for _ in range(slots + 1):
            agg.aggregate_once()
            staged = agg._stats["last_h2d_shards"]
            touched |= {k for k, n in enumerate(staged) if n}
        assert touched == {n99_shard}  # only the freed row's shard cleared
        assert agg._stats["window_compiles_total"] == compiles
        assert dict(engine._shard_of) == home

        joined["n99"] = (123, ZONES, MODE_RATIO, 2, "r1")  # rejoin, new data
        seed_window(agg, joined, now)
        result = agg.aggregate_once()
        staged = agg._stats["last_h2d_shards"]
        assert sum(1 for n in staged if n) == 1
        assert agg._stats["window_compiles_total"] == compiles
        assert {n: k for n, k in engine._shard_of.items()
                if n != "n99"} == home
        # the rejoined node's published row is the FRESH report (old
        # resident contents never leak; joules/timestamp are cumulative
        # and legitimately differ between the two aggregators)
        fresh = make_agg(1)
        fresh_result = run_schedule(fresh, [joined])[-1]
        got = result.render_node("n99")
        want = fresh_result.render_node("n99")
        for key in ("mode", "node_power_uw", "node_energy_uj", "workloads"):
            assert got[key] == want[key], key
        agg.shutdown()
        fresh.shutdown()

    def test_changed_row_stages_only_on_owning_shard(self):
        agg = make_agg(1, node_bucket=32)
        sched = {f"n{i:02d}": (i, ZONES, i % 2, 1, "r1") for i in range(10)}
        seed_window(agg, sched, 1e9)
        agg.aggregate_once()
        engine = agg._engine
        for _ in range(len(engine._buffers)):
            agg.aggregate_once()
        sched["n04"] = (321, ZONES, 0, 2, "r1")
        seed_window(agg, sched, 1e9)
        agg.aggregate_once()
        staged = agg._stats["last_h2d_shards"]
        owner = engine._shard_of["n04"]
        assert staged[owner] == 1
        assert sum(staged) == 1
        agg.shutdown()

    def test_bucket_overflow_rebalances_all_shards(self):
        """Only overflow (no shard has a free row) migrates nodes: the
        rebuild restages every shard at the grown bucket and balances
        MODE_MODEL rows across shards within one row."""
        import jax

        from kepler_tpu.parallel.fleet import MODE_MODEL as MM

        n_dev = len(jax.devices())
        agg = make_agg(1, node_bucket=n_dev)  # shard bucket 1: 8 rows
        sched = {f"n{i:02d}": (i, ZONES, i % 2, 1, "r1")
                 for i in range(n_dev)}
        seed_window(agg, sched, 1e9)
        agg.aggregate_once()
        engine = agg._engine
        compiles = agg._stats["window_compiles_total"]
        sched.update({f"m{i:02d}": (50 + i, ZONES, i % 2, 1, "r1")
                      for i in range(4)})  # 12 nodes > 8 rows: overflow
        seed_window(agg, sched, 1e9)
        agg.aggregate_once()
        staged = agg._stats["last_h2d_shards"]
        assert all(n > 0 for n in staged)  # full rebalance restage
        assert agg._stats["window_compiles_total"] > compiles
        mode_arr = list(engine._mode)
        sb = engine._ladder_n.bucket
        per_shard_model = [
            sum(1 for r in range(k * sb, (k + 1) * sb)
                if mode_arr[r] == MM) for k in range(engine.n_shards)]
        assert max(per_shard_model) - min(per_shard_model) <= 1
        # steady again afterwards
        agg.aggregate_once()
        agg.aggregate_once()
        assert agg._stats["window_compiles_total"] > compiles
        agg.shutdown()
