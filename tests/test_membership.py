"""Elastic fleet membership (ISSUE 16): coordinator-lease succession
properties, runtime join/leave over the /v1/membership plane, the
equal-epoch split-brain detector, and the autoscale hysteresis policy.

The succession properties have ONE source of truth since ISSUE 17: the
kepmc lease model (`kepler_tpu/analysis/protocol`) drives the SAME
pure functions — `plan_succession`, `plan_membership_apply`,
`CoordinatorLease.adopt` — through EVERY interleaving of crash, leave,
false-suspect probing, duplicate/reordered delivery and restart at the
declared scopes, and the KTL130 invariants (no split-brain,
holder-in-peers, contiguous epochs, no await-wedge) are checked in
every reachable state. This suite asserts against that explored state
space; the hand-rolled 5-peer subset sweeps remain as concrete
regression anchors on the pure functions. The aggregator tier runs
five REAL aggregators wired through injected liveness/delivery seams
(no sockets), so the "exactly one survivor bumps the epoch" pin covers
the actual `_demote_mesh` → `apply_membership` → broadcast code path.
"""

from __future__ import annotations

import itertools
import json

import pytest

from kepler_tpu.fleet.aggregator import Aggregator
from kepler_tpu.fleet.membership import (
    AutoscaleDecision,
    AutoscalePolicy,
    AutoscaleSignals,
    CoordinatorLease,
    MembershipError,
    elect_successor,
    lease_id_of,
    plan_succession,
    sanitize_lease_id,
    validate_membership_payload,
)
from kepler_tpu.server.http import APIServer

PEERS5 = [f"10.0.0.{i}:28283" for i in range(1, 6)]


class FakeRequest:
    command = "POST"

    def __init__(self, body: bytes):
        self.body = body


# ---------------------------------------------------------------------------
# Succession properties
# ---------------------------------------------------------------------------


def every_subset(peers):
    for n in range(1, len(peers) + 1):
        yield from itertools.combinations(peers, n)


class TestSuccessionProperties:
    """Universal claims are model-checked (kepmc explores every
    interleaving, not a subset sweep); the 5-peer pins below anchor the
    pure functions against concrete inputs."""

    @staticmethod
    def _explored(spec_name):
        from kepler_tpu.analysis.protocol import (explore_case,
                                                  spec_by_name)

        spec = spec_by_name(spec_name)
        return spec, [(case, explore_case(spec, case).result)
                      for case in spec.cases]

    def test_succession_state_space_has_no_counterexamples(self):
        """The former exactly-one-leader / concurrent-deaths-converge /
        no-self-elect sweeps, generalized: over EVERY reachable
        interleaving of the lease model (crash, leave, delivery in any
        order and multiplicity, restart), the KTL130 invariant set
        holds. A regression in plan_succession or the lease adopt rules
        surfaces here as a minimal counterexample trace."""
        spec, runs = self._explored("lease.succession")
        assert {"no-split-brain", "holder-in-peers",
                "contiguous-epochs", "no-await-wedge"} \
            <= set(spec.invariants)
        for case, result in runs:
            assert result.ok, "\n\n".join(
                cex.format() for cex in result.counterexamples)
            # exhaustive exploration, not a smoke probe: the N=3 case
            # must visit thousands of states
            assert result.states >= 50, (case.name, result.states)

    def test_partitioned_probe_state_space_has_no_counterexamples(self):
        """False-suspect probing (a partitioned prober declares the
        live holder dead and mints a competing lease): transient dual
        holders are legal there, but the holder stays a member of its
        own peer set and epochs stay contiguous — the equal-epoch
        conflict rejection does the rest (pinned directly below)."""
        spec, runs = self._explored("lease.partitioned")
        for case, result in runs:
            assert result.ok, "\n\n".join(
                cex.format() for cex in result.counterexamples)
            assert result.states >= 1000, (case.name, result.states)

    def test_every_subset_elects_exactly_one_leader(self):
        """For EVERY non-empty subset of a 5-peer set, every survivor
        computes the same single issuer — the "exactly one writer"
        property succession rests on."""
        for subset in every_subset(PEERS5):
            # the holder is dead (not in the subset) unless the subset
            # is the full set; either way every survivor must agree
            for holder in PEERS5 + [""]:
                issuers = {plan_succession(holder, subset)
                           for _ in subset}
                assert len(issuers) == 1
                issuer = issuers.pop()
                assert issuer in subset
                if holder in subset:
                    assert issuer == holder  # incumbent retained
                else:
                    assert issuer == min(subset)  # lowest survivor

    def test_concurrent_deaths_converge(self):
        """Two hosts dying in the same window: every survivor probes
        the same survivor set and therefore computes the same issuer —
        no coordination round needed."""
        for dead in itertools.combinations(PEERS5, 2):
            survivors = [p for p in PEERS5 if p not in dead]
            holder = PEERS5[0]
            issuers = {plan_succession(holder, survivors)
                       for _ in survivors}
            assert len(issuers) == 1
            expected = holder if holder in survivors else min(survivors)
            assert issuers == {expected}

    def test_rejoining_peer_never_self_elects_over_live_lease(self):
        """The rejoiner sorts LOWEST, but the incumbent holder is
        alive: succession keeps the incumbent, and the lease's
        equal-epoch conflict check rejects the rejoiner claiming the
        same epoch for itself."""
        rejoiner = "10.0.0.0:28283"  # sorts before every PEERS5 entry
        holder = PEERS5[1]
        survivors = [rejoiner] + PEERS5
        assert plan_succession(holder, survivors) == holder
        lease = CoordinatorLease(holder, epoch=4)
        with pytest.raises(MembershipError) as err:
            lease.adopt(rejoiner, 4)
        assert err.value.reason == "equal_epoch_conflict"
        assert lease.holder == holder  # belief unchanged

    def test_empty_survivor_set_raises(self):
        with pytest.raises(MembershipError) as err:
            elect_successor([])
        assert err.value.reason == "no_survivors"

    def test_two_writers_same_epoch_cannot_both_win(self):
        """Even if a partitioned prober produced two issuers, the
        lease admits only ONE holder per epoch — the second adopt is a
        loud conflict, never a silent overwrite."""
        lease = CoordinatorLease(PEERS5[0], epoch=1)
        lease.adopt(PEERS5[1], 2)
        with pytest.raises(MembershipError) as err:
            lease.adopt(PEERS5[2], 2)
        assert err.value.reason == "equal_epoch_conflict"
        # the SAME holder re-asserting the epoch is an idempotent adopt
        lease.adopt(PEERS5[1], 2)
        assert lease.holder == PEERS5[1]


class TestLease:
    def test_monotonic_epoch(self):
        lease = CoordinatorLease(PEERS5[0], epoch=3)
        with pytest.raises(MembershipError) as err:
            lease.adopt(PEERS5[1], 2)
        assert err.value.reason == "stale_epoch"
        lease.adopt(PEERS5[1], 5)
        assert (lease.holder, lease.epoch) == (PEERS5[1], 5)
        assert lease.lease_id == f"5:{PEERS5[1]}"

    def test_issuer_for_uses_incumbent_rule(self):
        lease = CoordinatorLease(PEERS5[2], epoch=1)
        assert lease.issuer_for(PEERS5) == PEERS5[2]
        assert lease.issuer_for(PEERS5[3:]) == PEERS5[3]

    @pytest.mark.parametrize("bad", [
        None, 42, "", "no-separator", "x:holder", "-1:holder",
        "3:", "3:bad\nname", "3:" + "x" * 300, "2.5:holder",
    ])
    def test_sanitize_lease_id_rejects(self, bad):
        assert sanitize_lease_id(bad) is None

    def test_sanitize_lease_id_roundtrip(self):
        lid = lease_id_of(PEERS5[0], 7)
        assert sanitize_lease_id(lid) == lid
        # holder may itself contain colons (host:port)
        assert sanitize_lease_id("7:10.0.0.1:28283") == "7:10.0.0.1:28283"

    @pytest.mark.parametrize("holder,epoch", [
        ("bad\x01peer", 1), ("", 1), (PEERS5[0], 0), (PEERS5[0], True),
    ])
    def test_ctor_rejects_bad_inputs(self, holder, epoch):
        with pytest.raises(MembershipError):
            CoordinatorLease(holder, epoch=epoch)


class TestPayloadLaundering:
    """Equal/stale/hostile-field boundary tests for the wire payload
    chokepoint, `validate_membership_payload` (the `/v1/membership`
    analog of the ring-header coercion suite)."""

    @pytest.mark.parametrize("payload,reason", [
        (None, "bad_payload"),
        ([], "bad_payload"),
        ("{}", "bad_payload"),
        ({"op": "takeover"}, "bad_op"),
        ({"op": 42}, "bad_op"),
        ({"peers": "not-a-list"}, "bad_peer"),
        ({"peers": [42]}, "bad_peer"),
        ({"peers": ["ok:1", "evil\nname"]}, "bad_peer"),
        ({"peers": ["x" * 300]}, "bad_peer"),
        ({"peer": 42}, "bad_peer"),
        ({"issuer": "bad\x7fissuer"}, "bad_peer"),
        ({"holder": ["a"]}, "bad_peer"),
        ({"epoch": "abc"}, "bad_epoch"),
        ({"epoch": -1}, "bad_epoch"),
        ({"epoch": True}, "bad_epoch"),
        ({"epoch": 2.5}, "bad_epoch"),
        ({"lease": "no-separator"}, "bad_lease"),
        ({"lease": 42}, "bad_lease"),
    ])
    def test_hostile_fields_rejected(self, payload, reason):
        with pytest.raises(MembershipError) as err:
            validate_membership_payload(payload)
        assert err.value.reason == reason

    def test_good_payload_normalized(self):
        out = validate_membership_payload({
            "op": "apply", "peers": list(PEERS5), "epoch": 3,
            "issuer": PEERS5[0], "lease": f"3:{PEERS5[0]}",
            "mesh": True})
        assert out["op"] == "apply"
        assert out["peers"] == list(PEERS5)
        assert out["epoch"] == 3
        assert out["issuer"] == PEERS5[0]
        assert out["mesh"] is True

    @pytest.mark.parametrize("mesh", ["yes", 1, [True], None])
    def test_mesh_flag_clamped_to_bool(self, mesh):
        assert validate_membership_payload({"mesh": mesh})["mesh"] is False


# ---------------------------------------------------------------------------
# Autoscale policy
# ---------------------------------------------------------------------------


def sig(load=0.0, shed=0, replicas=2, flagged=0):
    return AutoscaleSignals(load=load, shed_delta=shed,
                            replicas=replicas, flagged_nodes=flagged)


class TestAutoscalePolicy:
    def test_scale_up_after_consecutive_overload(self):
        policy = AutoscalePolicy(up_windows=3)
        assert policy.observe(sig(load=1.5)).direction == "hold"
        assert policy.observe(sig(load=1.2)).direction == "hold"
        dec = policy.observe(sig(load=1.1))
        assert (dec.direction, dec.replicas) == ("up", 3)
        # the streak reset: the next step needs fresh evidence
        assert policy.observe(sig(load=1.5)).direction == "hold"

    def test_shedding_counts_as_overload(self):
        policy = AutoscalePolicy(up_windows=2)
        policy.observe(sig(load=0.1, shed=5))
        dec = policy.observe(sig(load=0.1, shed=1))
        assert dec.direction == "up"

    def test_scale_down_after_consecutive_idle(self):
        policy = AutoscalePolicy(down_windows=3)
        for _ in range(2):
            assert policy.observe(sig(load=0.1)).direction == "hold"
        dec = policy.observe(sig(load=0.1))
        assert (dec.direction, dec.replicas) == ("down", 1)

    def test_dead_band_preserves_streaks(self):
        """A mid-band window neither advances nor erases evidence."""
        policy = AutoscalePolicy(up_windows=2)
        policy.observe(sig(load=1.5))
        policy.observe(sig(load=0.5))  # dead band: streak survives
        dec = policy.observe(sig(load=1.5))
        assert dec.direction == "up"

    def test_overload_erases_down_streak_and_vice_versa(self):
        policy = AutoscalePolicy(up_windows=2, down_windows=2)
        policy.observe(sig(load=0.1))
        policy.observe(sig(load=1.5))  # resets down streak
        dec = policy.observe(sig(load=0.1))
        assert dec.direction == "hold"

    def test_flagged_nodes_block_scale_down(self):
        """An unhealthy scoreboard is evidence AGAINST shrinking even
        at idle load."""
        policy = AutoscalePolicy(down_windows=2)
        policy.observe(sig(load=0.1, flagged=1))
        policy.observe(sig(load=0.1, flagged=1))
        assert policy.observe(sig(load=0.1, flagged=1)).direction == "hold"

    def test_min_and_max_bounds(self):
        policy = AutoscalePolicy(up_windows=1, down_windows=1,
                                 min_replicas=2, max_replicas=3)
        assert policy.observe(sig(load=1.5, replicas=3)).direction == "hold"
        assert policy.observe(sig(load=0.1, replicas=2)).direction == "hold"
        assert policy.observe(sig(load=1.5, replicas=2)).direction == "up"

    def test_default_cap_is_one_step_up(self):
        policy = AutoscalePolicy(up_windows=1, max_replicas=0)
        dec = policy.observe(sig(load=1.5, replicas=4))
        assert (dec.direction, dec.replicas) == ("up", 5)

    def test_replay_determinism(self):
        """A pure function of the observation sequence: feeding the
        same recorded trace to a fresh policy reproduces the same
        decisions — autoscale is auditable from metrics alone."""
        trace = ([sig(load=1.5)] * 4 + [sig(load=0.5)] * 3
                 + [sig(load=0.1)] * 15 + [sig(load=1.2, shed=2)] * 3)
        runs = []
        for _ in range(2):
            policy = AutoscalePolicy(up_windows=3, down_windows=12)
            runs.append([policy.observe(s) for s in trace])
        assert runs[0] == runs[1]
        assert any(d.direction != "hold" for d in runs[0])

    def test_ctor_validation(self):
        with pytest.raises(ValueError):
            AutoscalePolicy(scale_up_load=0)
        with pytest.raises(ValueError):
            AutoscalePolicy(scale_down_load=1.5, scale_up_load=1.0)
        with pytest.raises(ValueError):
            AutoscalePolicy(up_windows=0)
        with pytest.raises(ValueError):
            AutoscalePolicy(min_replicas=0)


# ---------------------------------------------------------------------------
# Five-host aggregator tier (injected seams, real code path)
# ---------------------------------------------------------------------------


class FiveHostFleet:
    """Five real aggregators sharing one ring, wired through in-process
    liveness and delivery seams: `deliver` routes membership POSTs to
    the target aggregator's actual `/v1/membership` handler."""

    def __init__(self, **agg_kw):
        self.alive = set(PEERS5)
        self.deliveries: list[tuple[str, str, dict]] = []
        self.aggs: dict[str, Aggregator] = {}
        for i, peer in enumerate(PEERS5):
            self.aggs[peer] = self._make(i, peer, **agg_kw)

    def _make(self, i, peer, **agg_kw):
        def deliver(target, payload, _self=peer):
            self.deliveries.append((_self, target, dict(payload)))
            if target not in self.alive:
                raise OSError("connection refused")
            status, _, body = self.aggs[target]._handle_membership(
                FakeRequest(json.dumps(payload).encode()))
            return json.loads(body)

        kw = dict(model_mode=None, node_bucket=8, workload_bucket=8,
                  stale_after=1e9)
        kw.update(agg_kw)
        agg = Aggregator(
            APIServer(), peers=list(PEERS5), self_peer=peer,
            membership_topology={
                "peer_alive": lambda p: p in self.alive,
                "deliver": deliver,
            }, **kw)
        agg.init()
        return agg

    def kill(self, peer):
        self.alive.discard(peer)

    def survivors(self):
        return [self.aggs[p] for p in PEERS5 if p in self.alive]

    def shutdown(self):
        for agg in self.aggs.values():
            agg.shutdown()


@pytest.fixture()
def fleet():
    f = FiveHostFleet()
    yield f
    f.shutdown()


class TestFiveHostSuccession:
    def test_exactly_one_survivor_bumps_epoch_on_single_death(self, fleet):
        """The acceptance pin: a single host death on a 5-peer ring —
        every survivor runs the demotion path, EXACTLY ONE issues the
        membership; the broadcast converges the rest."""
        dead = PEERS5[2]
        fleet.kill(dead)
        for agg in fleet.survivors():
            agg._demote_mesh("host_dead")
        issuers = [p for p in PEERS5 if p in fleet.alive
                   and fleet.aggs[p]._membership_applied.get("succession")]
        assert issuers == [PEERS5[0]]  # the incumbent holder, alive
        # every survivor converged on the same membership + lease
        for agg in fleet.survivors():
            assert agg._ring.epoch == 2
            assert set(agg._ring.peers) == fleet.alive
            assert agg._lease.holder == PEERS5[0]
            assert agg._awaiting_membership is False

    def test_holder_death_elects_lowest_survivor(self, fleet):
        fleet.kill(PEERS5[0])
        for agg in fleet.survivors():
            agg._demote_mesh("host_dead")
        issuers = [p for p in PEERS5 if p in fleet.alive
                   and fleet.aggs[p]._membership_applied.get("succession")]
        assert issuers == [PEERS5[1]]  # lowest surviving peer
        for agg in fleet.survivors():
            assert agg._ring.epoch == 2
            assert agg._lease.holder == PEERS5[1]

    def test_concurrent_two_host_death_converges(self, fleet):
        fleet.kill(PEERS5[0])
        fleet.kill(PEERS5[3])
        for agg in fleet.survivors():
            agg._demote_mesh("host_dead")
        epochs = {a._ring.epoch for a in fleet.survivors()}
        assert epochs == {2}
        for agg in fleet.survivors():
            assert set(agg._ring.peers) == fleet.alive
            assert agg._lease.holder == PEERS5[1]

    def test_takeover_disabled_awaits_operator(self):
        fleet = FiveHostFleet(multihost_takeover=False)
        try:
            fleet.kill(PEERS5[4])
            for agg in fleet.survivors():
                agg._demote_mesh("host_dead")
            for agg in fleet.survivors():
                assert agg._ring.epoch == 1  # untouched
                assert agg._awaiting_membership is True
                assert agg.ring_health()["ok"] is False
        finally:
            fleet.shutdown()

    def test_equal_epoch_conflict_rejected_loudly(self, fleet):
        agg = fleet.aggs[PEERS5[0]]
        agg.apply_membership(PEERS5[:4], 2)
        with pytest.raises(MembershipError) as err:
            agg.apply_membership(PEERS5[:3], 2)
        assert err.value.reason == "equal_epoch_conflict"
        assert agg._membership_rejected["equal_epoch_conflict"] == 1
        # idempotent replay of the SAME set is NOT a conflict
        assert agg.apply_membership(PEERS5[:4], 2) == 0

    def test_operator_cannot_exclude_self(self, fleet):
        agg = fleet.aggs[PEERS5[0]]
        with pytest.raises(MembershipError) as err:
            agg.apply_membership(PEERS5[1:], 2)
        assert err.value.reason == "self_excluded"

    def test_wire_membership_excluding_self_retires(self, fleet):
        """A broadcast that excludes this replica is the scale-down
        path: adopt the ring anyway, own nothing, redirect everything."""
        agg = fleet.aggs[PEERS5[4]]
        agg.apply_membership(PEERS5[:4], 2, source="wire",
                             issuer=PEERS5[0])
        assert agg._ring.epoch == 2
        assert PEERS5[4] not in agg._ring.peers
        assert agg._ring.owner("any-node") != PEERS5[4]


class TestJoinLeave:
    def test_rejoin_takes_shards_back_without_reelection(self, fleet):
        """The rejoin story: host dies, succession heals the ring,
        the host comes back and registers with the lease holder — it
        adopts the INCUMBENT lease (never self-elects) and owns keys
        again."""
        dead = PEERS5[1]
        fleet.kill(dead)
        for agg in fleet.survivors():
            agg._demote_mesh("host_dead")
        holder_before = fleet.aggs[PEERS5[0]]._lease.holder
        # the host returns: fresh process, stale ring at epoch 1
        fleet.alive.add(dead)
        rejoiner = fleet.aggs[dead]
        reply = rejoiner.request_join()
        assert reply["ok"] is True
        for peer in fleet.alive:
            agg = fleet.aggs[peer]
            assert set(agg._ring.peers) == set(PEERS5)
            assert agg._ring.epoch == 3  # death bump + join bump
            assert agg._lease.holder == holder_before  # no re-election
        # the rejoiner owns keys again
        owned = [n for n in ("n1", "n2", "n3", "n4", "n5", "n6", "n7",
                             "n8", "n9", "n10", "n11", "n12")
                 if rejoiner._ring.owner(n) == dead]
        assert owned  # vnode ring: 1/5 of a 12-key sample is ~2+ keys

    def test_join_registration_is_idempotent(self, fleet):
        agg = fleet.aggs[PEERS5[1]]
        reply = agg.request_join()
        assert reply["ok"] is True
        assert reply.get("already_member") is True
        assert agg._ring.epoch == 1  # nothing changed

    def test_join_redirected_from_non_holder(self, fleet):
        """A joiner that asks the WRONG replica gets the membership
        plane's 421 — a structured not_leader naming the holder — and
        follows it."""
        dead = PEERS5[3]
        fleet.kill(dead)
        for agg in fleet.survivors():
            agg._demote_mesh("host_dead")
        fleet.alive.add(dead)
        rejoiner = fleet.aggs[dead]
        reply = rejoiner.request_join(via=PEERS5[4])  # not the holder
        assert reply["ok"] is True
        assert set(rejoiner._ring.peers) == set(PEERS5)
        # the first delivery went to the wrong replica and bounced
        bounced = [(f, t) for f, t, p in fleet.deliveries
                   if f == dead and t == PEERS5[4]
                   and p.get("op") == "join"]
        assert bounced

    def test_graceful_leave_retires_the_leaver(self, fleet):
        holder = fleet.aggs[PEERS5[0]]
        status, _, body = holder._handle_membership(FakeRequest(
            json.dumps({"op": "leave", "peer": PEERS5[4]}).encode()))
        assert status == 200
        reply = json.loads(body)
        assert PEERS5[4] not in reply["peers"]
        for peer in PEERS5:
            agg = fleet.aggs[peer]
            assert agg._ring.epoch == 2
            assert set(agg._ring.peers) == set(PEERS5[:4])
        # the leaver itself was told (extra broadcast) and retired
        leaver = fleet.aggs[PEERS5[4]]
        assert leaver._ring.owner("anything") != PEERS5[4]

    def test_holder_leaving_hands_over_the_lease(self, fleet):
        holder = fleet.aggs[PEERS5[0]]
        status, _, body = holder._handle_membership(FakeRequest(
            json.dumps({"op": "leave", "peer": PEERS5[0]}).encode()))
        assert status == 200
        assert json.loads(body)["holder"] == PEERS5[1]
        for peer in PEERS5[1:]:
            assert fleet.aggs[peer]._lease.holder == PEERS5[1]

    def test_join_leave_on_non_holder_answers_not_leader(self, fleet):
        agg = fleet.aggs[PEERS5[2]]
        status, _, body = agg._handle_membership(FakeRequest(
            json.dumps({"op": "join", "peer": "10.9.9.9:1"}).encode()))
        assert status == 421
        reply = json.loads(body)
        assert reply["reason"] == "not_leader"
        assert reply["holder"] == PEERS5[0]

    def test_join_with_no_reachable_holder_fails_structured(self, fleet):
        # the whole fleet is down: every candidate is a transport
        # error, so the join fails with a STRUCTURED reason (and the
        # counter), never a hang or a self-election
        for peer in PEERS5:
            fleet.kill(peer)
        joiner = fleet.aggs[PEERS5[0]]
        with pytest.raises(MembershipError) as err:
            joiner.request_join()
        assert err.value.reason == "join_failed"
        assert joiner._membership_rejected["join_failed"] == 1
        assert joiner._ring.epoch == 1  # nothing adopted
        assert joiner._lease.holder == PEERS5[0]  # no self-election


class TestAutoscaleIntegration:
    class StubAdmission:
        def __init__(self, load=0.0, shed=0, latency=0.0):
            self._load, self._shed, self._lat = load, shed, latency

        def load(self):
            return self._load

        def shed_by_reason(self):
            return {"overload": self._shed}

        def latency_ewma(self):
            return self._lat

    def make_fleet(self, **kw):
        kw.setdefault("membership_autoscale", True)
        kw.setdefault("membership_up_windows", 2)
        kw.setdefault("membership_down_windows", 2)
        return FiveHostFleet(**kw)

    def test_recommendation_surfaced_without_auto_apply(self):
        """autoApply=false: decisions are recorded and surfaced, the
        ring is NEVER touched — operator behavior byte-for-byte."""
        fleet = self.make_fleet()
        try:
            agg = fleet.aggs[PEERS5[0]]
            agg._admission = self.StubAdmission(load=2.0)
            agg._autoscale_tick()
            agg._autoscale_tick()  # up_windows=2: this one fires
            assert agg._autoscale_last.direction == "up"
            assert agg._autoscale_decisions["up"] == 1
            assert agg._ring.epoch == 1  # untouched
            assert set(agg._ring.peers) == set(PEERS5)
            assert "autoscale" not in agg._membership_applied
        finally:
            fleet.shutdown()

    def test_auto_apply_scale_up_promotes_standby(self):
        standby = "10.0.1.1:28283"
        fleet = self.make_fleet(membership_auto_apply=True,
                                membership_standby_peers=[standby])
        try:
            agg = fleet.aggs[PEERS5[0]]  # the lease holder
            agg._admission = self.StubAdmission(load=2.0)
            agg._autoscale_tick()
            agg._autoscale_tick()
            assert agg._ring.epoch == 2
            assert standby in agg._ring.peers
            assert agg._membership_applied["autoscale"] == 1
            # the change was broadcast to every original member
            for peer in PEERS5[1:]:
                assert standby in fleet.aggs[peer]._ring.peers
        finally:
            fleet.shutdown()

    def test_auto_apply_scale_down_retires_highest_non_holder(self):
        fleet = self.make_fleet(membership_auto_apply=True)
        try:
            agg = fleet.aggs[PEERS5[0]]
            agg._admission = self.StubAdmission(load=0.0)
            agg._autoscale_tick()
            agg._autoscale_tick()
            assert agg._ring.epoch == 2
            assert PEERS5[4] not in agg._ring.peers  # highest-sorted
            assert PEERS5[0] in agg._ring.peers  # never the holder
            # the victim was told and retired
            assert PEERS5[4] not in fleet.aggs[PEERS5[4]]._ring.peers
        finally:
            fleet.shutdown()

    def test_non_holder_never_enacts(self):
        fleet = self.make_fleet(membership_auto_apply=True)
        try:
            agg = fleet.aggs[PEERS5[2]]  # not the holder
            agg._admission = self.StubAdmission(load=0.0)
            for _ in range(4):
                agg._autoscale_tick()
            assert agg._autoscale_last.direction in ("down", "hold")
            assert agg._ring.epoch == 1
        finally:
            fleet.shutdown()

    def test_scale_up_without_standby_stands_pat(self):
        fleet = self.make_fleet(membership_auto_apply=True)
        try:
            agg = fleet.aggs[PEERS5[0]]
            agg._admission = self.StubAdmission(load=2.0)
            agg._autoscale_tick()
            agg._autoscale_tick()
            assert agg._autoscale_last.direction == "up"
            assert agg._ring.epoch == 1  # nothing to promote
        finally:
            fleet.shutdown()

    def test_autoscale_off_is_inert(self, fleet):
        agg = fleet.aggs[PEERS5[0]]
        assert agg._autoscale is None
        agg._autoscale_tick()  # no-op, no error
        assert agg._autoscale_last is None
