"""Learned power-model tests (BASELINE configs 3-4): feature building,
linear/MLP prediction shapes+masking, training convergence on synthetic
ratio-attribution ground truth."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kepler_tpu.models import (
    NUM_FEATURES,
    ModelEstimator,
    build_features,
    fit,
    init_linear,
    init_mlp,
    masked_mse,
    predict_linear,
    predict_mlp,
)


def synth_batch(key, n=128, f_watts_per_core=30.0):
    """Workloads whose true power is watts_per_core × cpu_rate."""
    k1, _ = jax.random.split(key)
    cpu = jax.random.uniform(k1, (n,), minval=0.0, maxval=5.0)
    valid = jnp.ones((n,), bool)
    dt = jnp.float32(5.0)
    node_delta = cpu.sum()
    feats = build_features(cpu, valid, node_delta, jnp.float32(0.7), dt)
    target = (cpu / dt * f_watts_per_core)[:, None]  # [W, 1] watts
    return feats, valid, target


class TestFeatures:
    def test_shapes_and_mask(self):
        cpu = jnp.asarray([1.0, 2.0, 3.0], jnp.float32)
        valid = jnp.asarray([True, True, False])
        feats = build_features(cpu, valid, jnp.float32(3.0),
                               jnp.float32(0.5), jnp.float32(5.0))
        assert feats.shape == (3, NUM_FEATURES)
        assert np.asarray(feats[2]).sum() == 0.0  # masked row all-zero
        np.testing.assert_allclose(feats[0, 0], 1.0)
        np.testing.assert_allclose(feats[0, 1], 1.0 / 3.0, rtol=1e-6)
        np.testing.assert_allclose(feats[1, 4], 2.0 / 5.0, rtol=1e-6)
        np.testing.assert_allclose(feats[0, 5], 1.0)  # bias

    def test_batched_over_nodes(self):
        cpu = jnp.ones((4, 8), jnp.float32)
        valid = jnp.ones((4, 8), bool)
        feats = build_features(cpu, valid, jnp.full((4,), 8.0),
                               jnp.full((4,), 0.5), jnp.full((4,), 5.0))
        assert feats.shape == (4, 8, NUM_FEATURES)

    def test_zero_node_delta_no_nan(self):
        cpu = jnp.zeros((3,), jnp.float32)
        feats = build_features(cpu, jnp.ones(3, bool), jnp.float32(0.0),
                               jnp.float32(0.0), jnp.float32(5.0))
        assert not np.isnan(np.asarray(feats)).any()


class TestPredictors:
    def test_linear_shapes_nonneg_masked(self):
        key = jax.random.PRNGKey(0)
        params = init_linear(key, n_zones=4)
        feats = jax.random.normal(key, (16, NUM_FEATURES)) * 10
        valid = jnp.asarray([True] * 8 + [False] * 8)
        watts = predict_linear(params, feats, valid)
        assert watts.shape == (16, 4)
        assert (np.asarray(watts) >= 0).all()
        assert np.asarray(watts[8:]).sum() == 0.0

    def test_mlp_shapes_nonneg_masked(self):
        key = jax.random.PRNGKey(1)
        params = init_mlp(key, n_zones=2, hidden=32)
        feats = jax.random.normal(key, (3, 16, NUM_FEATURES))
        valid = jnp.ones((3, 16), bool)
        watts = predict_mlp(params, feats, valid)
        assert watts.shape == (3, 16, 2)
        assert (np.asarray(watts) >= 0).all()
        assert watts.dtype == jnp.float32

    def test_estimator_registry(self):
        est = ModelEstimator.create("linear", n_zones=2)
        cpu = jnp.asarray([1.0, 2.0], jnp.float32)
        watts = est.predict_watts(cpu, jnp.ones(2, bool), jnp.float32(3.0),
                                  jnp.float32(0.5), jnp.float32(5.0))
        assert watts.shape == (2, 2)
        with pytest.raises(ValueError, match="unknown estimator"):
            ModelEstimator.create("tree", n_zones=2)


class TestTraining:
    def test_linear_learns_cpu_proportional_power(self):
        key = jax.random.PRNGKey(42)
        feats, valid, target = synth_batch(key)
        params = init_linear(key, n_zones=1)
        params, loss = fit(predict_linear, params, feats, valid, target,
                           steps=500, learning_rate=0.05)
        # targets are in [0, 30] watts; MSE below 0.5 W² means it learned
        assert loss < 0.5, f"linear failed to converge: loss={loss}"

    def test_mlp_learns(self):
        key = jax.random.PRNGKey(7)
        feats, valid, target = synth_batch(key)
        params = init_mlp(key, n_zones=1, hidden=32)
        params, loss = fit(predict_mlp, params, feats, valid, target,
                           steps=500, learning_rate=0.01)
        assert loss < 2.0, f"mlp failed to converge: loss={loss}"

    def test_masked_mse_ignores_invalid(self):
        pred = jnp.asarray([[1.0], [100.0]])
        target = jnp.asarray([[1.0], [0.0]])
        valid = jnp.asarray([True, False])
        assert float(masked_mse(pred, target, valid)) == 0.0


class TestTemporalFastPath:
    def test_last_query_path_matches_full_trunk(self):
        """Dense serving uses the single-query trunk; it must agree with
        the full-sequence trunk + take_along_axis pooling on ragged
        windows (same math, ~4x fewer FLOPs)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kepler_tpu.models.temporal import init_temporal, predict_temporal
        from kepler_tpu.ops.attention import full_attention

        t = 12
        params = init_temporal(jax.random.PRNGKey(0), n_zones=3,
                               d_model=64, t_max=t)
        hist = jax.random.uniform(jax.random.PRNGKey(1), (5, 7, t, 7))
        wv = jnp.array([True, True, False, True, True, True, True])[None, :]
        wv = jnp.broadcast_to(wv, (5, 7))
        lengths = jnp.arange(5 * 7).reshape(5, 7) % t + 1
        tv = jnp.arange(t)[None, None, :] < lengths[..., None]

        fast = predict_temporal(params, hist, wv, tv,
                                compute_dtype=jnp.float32)
        full = predict_temporal(
            params, hist, wv, tv, compute_dtype=jnp.float32,
            attention_fn=lambda q, k, v, tvv: full_attention(
                q, k, v, causal=True, t_valid=tvv,
                compute_dtype=jnp.float32))
        np.testing.assert_allclose(np.asarray(fast), np.asarray(full),
                                   rtol=2e-5, atol=2e-5)

    def test_gapped_t_valid_matches_full_trunk(self):
        """A GAPPED t_valid (not a contiguous right-padded prefix) must
        produce identical output on the fast path and the all-positions
        trunk (advisor r2: the fast path previously masked with t_valid
        only, silently diverging between dense serving and the
        attention_fn/ring path on gapped masks)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kepler_tpu.models.temporal import init_temporal, predict_temporal
        from kepler_tpu.ops.attention import full_attention

        t = 10
        params = init_temporal(jax.random.PRNGKey(3), n_zones=2,
                               d_model=64, t_max=t)
        hist = jax.random.uniform(jax.random.PRNGKey(4), (2, 4, t, 7))
        wv = jnp.ones((2, 4), bool)
        # gapped masks: holes in the middle, valid past the holes
        tv = np.zeros((2, 4, t), bool)
        tv[0, 0, [0, 2, 5]] = True       # gaps at 1, 3-4
        tv[0, 1, [1, 3, 4, 8]] = True    # leading gap + middle gaps
        tv[0, 2, :] = True               # dense for contrast
        tv[0, 3, [9]] = True             # single late tick
        tv[1, :, ::2] = True             # alternating
        tv = jnp.asarray(tv)

        fast = predict_temporal(params, hist, wv, tv,
                                compute_dtype=jnp.float32)
        full = predict_temporal(
            params, hist, wv, tv, compute_dtype=jnp.float32,
            attention_fn=lambda q, k, v, tvv: full_attention(
                q, k, v, causal=True, t_valid=tvv,
                compute_dtype=jnp.float32))
        np.testing.assert_allclose(np.asarray(fast), np.asarray(full),
                                   rtol=2e-5, atol=2e-5)

    def test_empty_history_window_yields_finite_zero_not_nan(self):
        """A valid workload whose history window is entirely invalid (first
        tick before any history accretes) must get finite watts — the
        fast path's masked softmax must not produce NaN."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kepler_tpu.models.temporal import init_temporal, predict_temporal
        from kepler_tpu.ops.attention import full_attention

        t = 8
        params = init_temporal(jax.random.PRNGKey(0), n_zones=2,
                               d_model=64, t_max=t)
        hist = jax.random.uniform(jax.random.PRNGKey(1), (1, 3, t, 7))
        wv = jnp.ones((1, 3), bool)
        tv = jnp.zeros((1, 3, t), bool).at[0, 0].set(True)  # 1 full, 2 empty

        fast = np.asarray(predict_temporal(params, hist, wv, tv,
                                           compute_dtype=jnp.float32))
        assert np.isfinite(fast).all(), fast
        full = np.asarray(predict_temporal(
            params, hist, wv, tv, compute_dtype=jnp.float32,
            attention_fn=lambda q, k, v, tvv: full_attention(
                q, k, v, causal=True, t_valid=tvv,
                compute_dtype=jnp.float32)))
        np.testing.assert_allclose(fast, full, rtol=2e-5, atol=2e-5)


class TestExactFitAndWarmStart:
    """Closed-form linear solve + wide-and-deep warm starts — the machinery
    that puts every estimator family inside the 0.5%-of-ground-truth
    north-star budget at p99 (benchmarks/accuracy.py gates on it)."""

    def _linear_truth(self, key, n=16, w=8, z=3):
        """Fleet features + targets exactly linear in the features."""
        import numpy as np

        rng = np.random.default_rng(int(jax.random.randint(
            key, (), 0, 2**31 - 1)))
        cpu = jnp.asarray(rng.uniform(0.1, 5.0, (n, w)), jnp.float32)
        valid = jnp.asarray(rng.random((n, w)) > 0.2)
        node = jnp.sum(jnp.where(valid, cpu, 0.0), axis=1) * 1.1
        feats = build_features(cpu, valid, node, jnp.full((n,), 0.6),
                               jnp.full((n,), 5.0))
        true_w = jnp.asarray(rng.uniform(-2.0, 4.0, (NUM_FEATURES, z)),
                             jnp.float32)
        target = jnp.where(valid[..., None], feats @ true_w, 0.0)
        return feats, valid, target, true_w

    def test_fit_linear_exact_recovers_weights(self):
        from kepler_tpu.models.linear import fit_linear_exact

        with jax.default_matmul_precision("highest"):
            feats, valid, target, true_w = self._linear_truth(
                jax.random.PRNGKey(0))
            sol = fit_linear_exact(feats, valid, target)
            pred = predict_linear(sol, feats, valid, clamp=False)
        np.testing.assert_allclose(np.asarray(pred), np.asarray(target),
                                   rtol=1e-4, atol=1e-4)

    def test_fit_linear_exact_label_valid_isolates_zones(self):
        """A zone whose labels are masked on half the rows must still solve
        exactly from the remaining rows (not be dragged toward zero)."""
        from kepler_tpu.models.linear import fit_linear_exact

        with jax.default_matmul_precision("highest"):
            feats, valid, target, _ = self._linear_truth(
                jax.random.PRNGKey(1))
            lv = jnp.ones(target.shape, bool).at[:8, :, 0].set(False)
            sol = fit_linear_exact(feats, valid, target, label_valid=lv)
            pred = predict_linear(sol, feats, valid, clamp=False)
        got = np.asarray(pred)[np.asarray(valid)]
        want = np.asarray(target)[np.asarray(valid)]
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_warm_start_wide_makes_mlp_exact_on_linear_truth(self):
        from kepler_tpu.models.train import warm_start_wide

        with jax.default_matmul_precision("highest"):
            feats, valid, target, _ = self._linear_truth(
                jax.random.PRNGKey(2))
            params = init_mlp(jax.random.PRNGKey(3), n_zones=3)
            params = warm_start_wide(params, feats, valid, target)
            pred = predict_mlp(params, feats, valid, clamp=False,
                               compute_dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(pred), np.asarray(target),
                                   rtol=1e-4, atol=1e-4)

    def test_warm_start_moe_solves_per_expert(self):
        from kepler_tpu.models.moe import init_moe, predict_moe
        from kepler_tpu.models.train import warm_start_moe

        with jax.default_matmul_precision("highest"):
            feats, valid, target, true_w = self._linear_truth(
                jax.random.PRNGKey(4))
            # two node types with DIFFERENT linear maps
            eid = jnp.asarray([0, 1] * 8, jnp.int32)
            target = jnp.where((eid == 1)[:, None, None], target * 2.5,
                               target)
            params = init_moe(jax.random.PRNGKey(5), n_zones=3, n_experts=2)
            params = warm_start_moe(params, feats, valid, target, eid)
            pred = predict_moe(params, feats, valid, clamp=False,
                               compute_dtype=jnp.float32, expert_id=eid)
        np.testing.assert_allclose(np.asarray(pred), np.asarray(target),
                                   rtol=1e-4, atol=1e-4)

    def test_masked_relative_mse_weighs_small_rows(self):
        from kepler_tpu.models.train import masked_relative_mse

        # same absolute error on a big and a small row: relative loss must
        # punish the small row ~ (100/1)² harder than plain MSE would
        pred = jnp.asarray([[101.0], [2.0]])
        target = jnp.asarray([[100.0], [1.0]])
        valid = jnp.ones((2,), bool)
        loss = float(masked_relative_mse(pred, target, valid))
        np.testing.assert_allclose(loss, (0.01**2 + 1.0**2) / 2, rtol=1e-5)

    def test_masked_relative_mse_floor_and_masks(self):
        from kepler_tpu.models.train import masked_relative_mse

        pred = jnp.asarray([[0.05], [999.0]])
        target = jnp.asarray([[0.0], [1.0]])
        valid = jnp.asarray([True, False])  # big-error row masked out
        loss = float(masked_relative_mse(pred, target, valid,
                                         floor_watts=0.1))
        np.testing.assert_allclose(loss, (0.05 / 0.1) ** 2, rtol=1e-5)

    def test_skip_path_round_trips_save_load(self):
        import os
        import tempfile

        from kepler_tpu.models.estimator import load_params, save_params
        from kepler_tpu.models.train import warm_start_wide

        feats, valid, target, _ = self._linear_truth(jax.random.PRNGKey(6))
        params = warm_start_wide(init_mlp(jax.random.PRNGKey(7), n_zones=3),
                                 feats, valid, target)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "m.npz")
            save_params(path, params)
            loaded = load_params(path)
        assert set(loaded) == set(params)
        np.testing.assert_allclose(np.asarray(loaded["w_skip"]),
                                   np.asarray(params["w_skip"]))
