"""Overload-resilient ingest (ISSUE 12): admission control + adaptive
shedding on the aggregator, throttle-is-not-a-failure + batched paced
spool drain on the agent, the HTTP server's connection cap, and the
chaos-marked thundering-herd scenario — kill 1 of 3 replicas mid-soak
with admission on, assert sheds fire, ``windows_lost`` stays 0, and the
fleet fully drains within a bounded number of intervals."""

import http.client
import json
import socket
import threading
import time

import pytest

from kepler_tpu import fault
from kepler_tpu.fault import FaultPlan, FaultSpec
from kepler_tpu.fleet import Aggregator, FleetAgent, Spool, encode_report
from kepler_tpu.fleet.admission import (
    PRIORITY_FRESH_GROUND,
    PRIORITY_FRESH_MODEL,
    PRIORITY_REPLAY_GROUND,
    PRIORITY_REPLAY_MODEL,
    AdmissionController,
)
from kepler_tpu.fleet.agent import (
    BREAKER_CLOSED,
    ThrottledError,
    _TokenBucket,
    coerce_retry_after,
)
from kepler_tpu.fleet.wire import (
    WireError,
    decode_report_batch,
    encode_report_batch,
    peek_routing,
    restamp_transmit,
)
from kepler_tpu.parallel.fleet import MODE_MODEL
from kepler_tpu.server.http import APIServer
from kepler_tpu.service.lifecycle import CancelContext

from tests.test_fleet import (
    FakeMeterMonitor,
    make_report,
    make_sample,
    post_report,
)
from tests.test_ring_handoff import (
    drive_interval,
    kill_replica,
    make_tier,
    names_owned_by,
    shutdown_tier,
)


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    fault.uninstall()
    yield
    fault.uninstall()


class _FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def step(self, dt):
        self.t += dt


def make_ctrl(**kw):
    kw.setdefault("max_inflight", 4)
    kw.setdefault("latency_budget", 0.1)
    kw.setdefault("retry_after", 1.0)
    kw.setdefault("retry_after_max", 30.0)
    kw.setdefault("jitter_seed", 0)
    clock = kw.pop("clock", _FakeClock())
    return AdmissionController(monotonic=clock, **kw), clock


class TestAdmissionController:
    def test_under_budget_admits_everything(self):
        ctrl, _ = make_ctrl()
        for p in range(4):
            assert ctrl.admit(p) is None
            ctrl.done(0.01)
        assert ctrl.shed_by_reason() == {"inflight": 0, "latency": 0}

    def test_inflight_cap_sheds_lowest_priority_first(self):
        ctrl, _ = make_ctrl(max_inflight=4)
        for _ in range(4):
            assert ctrl.admit(PRIORITY_FRESH_GROUND) is None
        # load 1.0: replay+model sheds, everything else still admitted
        assert ctrl.admit(PRIORITY_REPLAY_MODEL) is not None
        assert ctrl.admit(PRIORITY_REPLAY_GROUND) is None  # load 1.0 < 1.25
        assert ctrl.shed_by_reason()["inflight"] == 1

    def test_latency_ladder_priorities(self):
        # EWMA pinned via one huge observation: alpha 0.2 × 0.65 s over
        # a 0.1 s budget → load 1.3: replay classes shed, fresh admitted
        ctrl, _ = make_ctrl(latency_budget=0.1)
        ctrl.admit(0)
        ctrl.done(0.65)
        assert 1.25 < ctrl.load() < 1.5
        assert ctrl.admit(PRIORITY_REPLAY_MODEL) is not None
        retry = ctrl.admit(PRIORITY_REPLAY_GROUND)
        assert retry is not None
        assert ctrl.admit(PRIORITY_FRESH_MODEL) is None
        ctrl.done(0.0)
        assert ctrl.admit(PRIORITY_FRESH_GROUND) is None
        ctrl.done(0.0)
        assert ctrl.shed_by_reason()["latency"] == 2

    def test_ground_truth_sheds_last(self):
        ctrl, _ = make_ctrl(latency_budget=0.1)
        ctrl.admit(0)
        ctrl.done(0.9)  # EWMA 0.18 → load 1.8: only priority 0 admitted
        assert ctrl.admit(PRIORITY_FRESH_MODEL) is not None
        assert ctrl.admit(PRIORITY_FRESH_GROUND) is None
        ctrl.done(0.0)

    def test_retry_after_load_derived_jittered_clamped(self):
        ctrl, _ = make_ctrl(retry_after=1.0, retry_after_max=5.0,
                            latency_budget=0.1)
        ctrl.admit(0)
        ctrl.done(5.0)  # EWMA 1.0 → load 10: base × 10 clamps to max
        for _ in range(20):
            retry = ctrl.admit(PRIORITY_FRESH_GROUND)
            assert retry is not None
            # jitter ±50% around the clamped base, never over the cap
            assert 0.05 <= retry <= 5.0

    def test_ewma_decays_while_shedding(self):
        clock = _FakeClock()
        ctrl, _ = make_ctrl(latency_budget=0.1, clock=clock)
        ctrl.admit(0)
        ctrl.done(1.0)  # EWMA 0.2 → load 2.0: full shed
        assert ctrl.admit(PRIORITY_FRESH_GROUND) is not None
        # idle decay: the halved EWMA re-admits without any observation
        clock.step(30.0)
        assert ctrl.load() < 1.0
        assert ctrl.admit(PRIORITY_REPLAY_MODEL) is None
        ctrl.done(0.0)

    def test_health_probe_degrades_while_shedding(self):
        clock = _FakeClock()
        ctrl, _ = make_ctrl(latency_budget=0.1, degraded_ttl=10.0,
                            clock=clock)
        assert ctrl.health()["ok"]
        ctrl.admit(0)
        ctrl.done(1.0)
        assert ctrl.admit(PRIORITY_FRESH_GROUND) is not None
        h = ctrl.health()
        assert not h["ok"] and h["shedding"]
        assert h["shed_total"] == 1
        assert h["latency_budget_s"] == 0.1
        clock.step(60.0)  # past the ttl: recovered on its own
        assert ctrl.health()["ok"]

    def test_hostile_priority_clamped(self):
        ctrl, _ = make_ctrl()
        for bogus in (-5, 99, True, None, "2"):
            assert ctrl.admit(bogus) is None
            ctrl.done(0.0)


class TestBatchWire:
    def test_roundtrip(self):
        payloads = [encode_report(make_report(f"n{i}"),
                                  ["package", "dram"], seq=i + 1,
                                  run="r") for i in range(5)]
        assert decode_report_batch(encode_report_batch(payloads)) \
            == payloads

    def test_rejects_malformed(self):
        good = encode_report_batch([b"abc", b"defg"])
        for bad in (b"", b"XXXXXXXX" + good[8:], good[:-2],
                    good + b"trailing"):
            with pytest.raises(WireError):
                decode_report_batch(bad)

    def test_count_bounds(self):
        import struct
        with pytest.raises(WireError):
            encode_report_batch([])
        with pytest.raises(WireError):
            encode_report_batch([b"x"] * 1025)
        # a forged huge count must bounds-fail, not allocate
        forged = b"KTPUFB1\n" + struct.pack("<I", 2 ** 31) + b"\x00" * 64
        with pytest.raises(WireError):
            decode_report_batch(forged)

    def test_peek_routing(self):
        blob = encode_report(make_report("route-node", mode=MODE_MODEL),
                             ["package", "dram"], seq=3, run="r")
        assert peek_routing(blob) == ("route-node", "fresh", MODE_MODEL)
        stamped = restamp_transmit(blob, 5.0, delivery_path="replay")
        assert peek_routing(stamped)[1] == "replay"
        assert peek_routing(b"garbage") == ("", "fresh", 0)


class TestRetryAfterCoercion:
    """Hostile throttle values coerce to the default and clamp to the
    cap — an adversarial owner must not be able to park an agent."""

    @pytest.mark.parametrize("hostile", [
        None, "", "soon", "1e", [], {}, True, False, "-3", -3, -0.1,
        float("nan"), float("inf"), "nan", "inf",
    ])
    def test_hostile_values_fall_back_to_default(self, hostile):
        assert coerce_retry_after(hostile, default=1.5, cap=300.0) == 1.5

    def test_huge_values_clamp(self):
        assert coerce_retry_after(10_000, cap=300.0) == 300.0
        assert coerce_retry_after("99999999", cap=60.0) == 60.0

    def test_good_values_pass(self):
        assert coerce_retry_after("2.5", cap=300.0) == 2.5
        assert coerce_retry_after(0, cap=300.0) == 0.0
        assert coerce_retry_after(7, cap=300.0) == 7.0


class TestTokenBucket:
    def test_pacing_is_deterministic(self):
        clock = _FakeClock(0.0)
        bucket = _TokenBucket(10.0, 8, clock)  # 10 rps, burst 8
        granted, wait = bucket.take(8)
        assert (granted, wait) == (8, 0.0)
        granted, wait = bucket.take(8)
        assert granted == 0 and wait == pytest.approx(0.1)
        clock.step(0.45)  # 4.5 tokens accrue
        granted, _ = bucket.take(8)
        assert granted == 4
        clock.step(100.0)  # accrual caps at the burst
        granted, _ = bucket.take(100)
        assert granted == 8


def _throttling_server(retry_after="0.05", times=1, status=429):
    """An APIServer whose /v1/report answers `status` `times` times,
    then 204. Returns (server, ctx, calls)."""
    s = APIServer(listen_addresses=["127.0.0.1:0"])
    s.init()
    calls = {"n": 0}

    def handler(request):
        calls["n"] += 1
        if calls["n"] <= times:
            headers = {"Content-Type": "text/plain"}
            if retry_after is not None:
                headers["Retry-After"] = retry_after
            return status, headers, b"shed\n"
        return 204, {}, b""

    s.register("/v1/report", "t", "throttling ingest", handler,
               max_body=64 << 20)
    ctx = CancelContext()
    threading.Thread(target=s.run, args=(ctx,), daemon=True).start()
    time.sleep(0.05)
    return s, ctx, calls


class TestThrottleIsNotAFailure:
    """Acceptance pin: a 429 never increments breaker, peer-rotation,
    or ``_disrupted_at`` state."""

    def test_429_leaves_breaker_rotation_disruption_untouched(
            self, tmp_path):
        s, ctx, calls = _throttling_server(times=1)
        try:
            host, port = s.addresses[0]
            agent = FleetAgent(FakeMeterMonitor(),
                               endpoint=f"http://{host}:{port}",
                               node_name="thr-node", jitter_seed=0,
                               spool=Spool(str(tmp_path / "sp")))
            agent.init()
            agent._on_window(make_sample())
            target_before = agent._target
            agent._drain(None)  # throttled: returns, record stays spooled
            h = agent.health()
            assert h["throttled_total"] == 1
            assert h["breaker"] == BREAKER_CLOSED
            assert h["consecutive_failures"] == 0
            assert h["send_failures"] == 0
            assert h["failovers"] == 0
            assert agent._target is target_before  # no peer rotation
            assert agent._disrupted_at is None  # not a disruption
            assert agent.backlog() == 1  # safe in the spool
            agent._drain(None)  # server recovered → delivers
            assert agent.health()["queued"] == 0
            assert agent.health()["sent_total"] == 1
            # delivered AFTER a throttle (not a disruption): still fresh
            agent.shutdown()
        finally:
            ctx.cancel()
            s.shutdown()

    def test_drain_honors_retry_after_with_jitter(self, tmp_path):
        """With a live CancelContext the drain waits out the coerced
        Retry-After (decorrelated jitter ≥ the hint) and then retries
        WITHOUT counting a failure."""
        s, ctx, calls = _throttling_server(retry_after="0.05", times=2)
        try:
            host, port = s.addresses[0]
            agent = FleetAgent(FakeMeterMonitor(),
                               endpoint=f"http://{host}:{port}",
                               node_name="pace-node", jitter_seed=0,
                               spool=Spool(str(tmp_path / "sp")))
            agent.init()
            agent._on_window(make_sample())
            drain_ctx = CancelContext()
            t0 = time.monotonic()
            agent._drain(drain_ctx)
            elapsed = time.monotonic() - t0
            h = agent.health()
            assert h["queued"] == 0 and h["sent_total"] == 1
            assert h["throttled_total"] == 2
            assert h["send_failures"] == 0
            assert elapsed >= 0.1  # two waits ≥ the 0.05 s hint each
            agent.shutdown()
        finally:
            ctx.cancel()
            s.shutdown()

    def test_hostile_retry_after_does_not_park_agent(self, tmp_path):
        """A huge Retry-After clamps to drain_retry_after_max — the
        drain waits the clamp, not the adversarial value."""
        s, ctx, _ = _throttling_server(retry_after="99999999", times=1)
        try:
            host, port = s.addresses[0]
            agent = FleetAgent(FakeMeterMonitor(),
                               endpoint=f"http://{host}:{port}",
                               node_name="park-node", jitter_seed=0,
                               spool=Spool(str(tmp_path / "sp")),
                               drain_retry_after_max=0.05)
            agent.init()
            agent._on_window(make_sample())
            drain_ctx = CancelContext()
            t0 = time.monotonic()
            agent._drain(drain_ctx)
            assert time.monotonic() - t0 < 2.0  # clamped, not parked
            assert agent.health()["queued"] == 0
            agent.shutdown()
        finally:
            ctx.cancel()
            s.shutdown()

    def test_net_throttle_fault_site(self, tmp_path):
        """The chaos stand-in behaves exactly like a server 429."""
        s, ctx, _ = _throttling_server(times=0)  # server always accepts
        try:
            host, port = s.addresses[0]
            agent = FleetAgent(FakeMeterMonitor(),
                               endpoint=f"http://{host}:{port}",
                               node_name="fault-node", jitter_seed=0,
                               spool=Spool(str(tmp_path / "sp")))
            agent.init()
            with fault.installed(FaultPlan([
                    FaultSpec("net.throttle", count=1, arg=0.01)])) as plan:
                agent._on_window(make_sample())
                agent._drain(None)
                assert plan.fired("net.throttle") == 1
            h = agent.health()
            assert h["throttled_total"] == 1
            assert h["breaker"] == BREAKER_CLOSED
            assert agent.backlog() == 1
            agent._drain(None)
            assert agent.health()["queued"] == 0
            agent.shutdown()
        finally:
            ctx.cancel()
            s.shutdown()


class TestIngestShedding:
    """Aggregator-side: 429 before decode, not charged to the node,
    recovery on its own."""

    def make_admitting_agg(self, **kw):
        s = APIServer(listen_addresses=["127.0.0.1:0"])
        s.init()
        kw.setdefault("model_mode", None)
        kw.setdefault("node_bucket", 8)
        kw.setdefault("workload_bucket", 16)
        kw.setdefault("admission_enabled", True)
        kw.setdefault("admission_jitter_seed", 0)
        agg = Aggregator(s, **kw)
        agg.init()
        ctx = CancelContext()
        threading.Thread(target=s.run, args=(ctx,), daemon=True).start()
        time.sleep(0.05)
        return s, agg, ctx

    def test_shed_is_429_with_retry_after_uncharged(self):
        s, agg, ctx = self.make_admitting_agg(
            admission_latency_budget=0.01)
        try:
            import urllib.error
            import urllib.request
            agg._admission.done(1.0)  # pin the EWMA over budget
            host, port = s.addresses[0]
            blob = encode_report(make_report("shed-node"),
                                 ["package", "dram"], seq=1, run="r")
            req = urllib.request.Request(
                f"http://{host}:{port}/v1/report", data=blob,
                method="POST")
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=5)
            assert err.value.code == 429
            retry = float(err.value.headers["Retry-After"])
            assert retry > 0
            assert json.loads(err.value.read())["retry_after"] == retry
            # shed ≠ quarantine: nothing charged, stored, or tracked
            assert agg._stats["reports_total"] == 0
            assert agg._stats["rejected_total"] == 0
            assert "shed-node" not in agg.degraded_nodes()
            assert "shed-node" not in agg._reports
            assert sum(agg._admission.shed_by_reason().values()) == 1
            fams = {f.name: f for f in agg.collect()}
            shed = {s.labels["reason"]: s.value
                    for s in fams["kepler_fleet_reports_shed"].samples}
            assert shed["latency"] == 1
        finally:
            ctx.cancel()
            s.shutdown()
            agg.shutdown()

    def test_ingest_slow_fault_drives_shedding_then_recovers(self):
        s, agg, ctx = self.make_admitting_agg(
            admission_latency_budget=0.02, degraded_ttl=0.2)
        try:
            with fault.installed(FaultPlan([
                    FaultSpec("aggregator.ingest_slow", count=1,
                              arg=0.5)])):
                post_report(s, make_report("slow-node"), seq=1, run="r")
            # the slow ingest pushed the EWMA over budget → next sheds
            assert agg._admission.load() >= 2.0
            import urllib.error
            with pytest.raises(urllib.error.HTTPError) as err:
                post_report(s, make_report("slow-node"), seq=2, run="r")
            assert err.value.code == 429
            assert not agg._admission.health()["ok"]
            # EWMA decays on its own (no operator action) → re-admits
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and agg._admission.load() >= 1.0:
                time.sleep(0.25)
            post_report(s, make_report("slow-node"), seq=3, run="r")
            assert agg._reports["slow-node"].seq == 3
            time.sleep(0.25)  # past degradedTtl of shed silence
            assert agg._admission.health()["ok"]
        finally:
            ctx.cancel()
            s.shutdown()
            agg.shutdown()

    def test_admission_disabled_is_old_behavior(self):
        """admissionEnabled: false ≡ PR 11: no controller, no probe, no
        429 path, shed families export zeros."""
        s = APIServer(listen_addresses=["127.0.0.1:0"])
        s.init()
        agg = Aggregator(s, model_mode=None, node_bucket=8,
                         workload_bucket=16)
        agg.init()
        ctx = CancelContext()
        threading.Thread(target=s.run, args=(ctx,), daemon=True).start()
        time.sleep(0.05)
        try:
            assert agg._admission is None
            for i in range(1, 9):
                post_report(s, make_report("plain"), seq=i, run="r")
            assert agg._stats["reports_total"] == 8
            ok, components = s.health.check_health()
            assert "fleet-ingest" not in components
            fams = {f.name: f for f in agg.collect()}
            assert all(x.value == 0 for x in
                       fams["kepler_fleet_reports_shed"].samples)
            assert fams["kepler_fleet_ingest_inflight"].samples[0].value \
                == 0
        finally:
            ctx.cancel()
            s.shutdown()
            agg.shutdown()


class TestBatchedDrain:
    def seed_spool(self, tmp_path, name, n, run="rb"):
        spool = Spool(str(tmp_path / name))
        for i in range(1, n + 1):
            spool.append(encode_report(make_report(name),
                                       ["package", "dram"], seq=i,
                                       run=run))
        spool.close()
        return Spool(str(tmp_path / name))

    def make_live_agg(self, **kw):
        s = APIServer(listen_addresses=["127.0.0.1:0"])
        s.init()
        kw.setdefault("model_mode", None)
        kw.setdefault("node_bucket", 8)
        kw.setdefault("workload_bucket", 16)
        kw.setdefault("stale_after", 1e9)
        agg = Aggregator(s, **kw)
        agg.init()
        ctx = CancelContext()
        threading.Thread(target=s.run, args=(ctx,), daemon=True).start()
        time.sleep(0.05)
        return s, agg, ctx

    def test_recovery_replay_ships_batches(self, tmp_path):
        s, agg, ctx = self.make_live_agg()
        try:
            host, port = s.addresses[0]
            agent = FleetAgent(FakeMeterMonitor(),
                               endpoint=f"http://{host}:{port}",
                               node_name="bd-node", jitter_seed=0,
                               spool=self.seed_spool(tmp_path, "bd-node",
                                                     20),
                               drain_batch_max=8)
            # the crash backlog belongs to THIS agent run (a restart
            # would mint a fresh nonce and the watermark would — by
            # design — not advance; see the old-run pin in
            # test_ring_handoff)
            agent._run_nonce = "rb"
            agent.init()
            agent._drain(None)
            h = agent.health()
            assert h["queued"] == 0
            assert h["drain_batch_records"] == 20
            # ≥ 8 records per request while the backlog is deep
            assert h["drain_batches"] <= 3
            assert agg._stats["reports_total"] == 20
            assert agg._stats["windows_lost_total"] == 0
            assert agg._stats["duplicates_total"] == 0
            # the watermark advanced to the run's top acked seq
            assert agent._acked_through == 20
            agent.shutdown()
        finally:
            ctx.cancel()
            s.shutdown()
            agg.shutdown()

    def test_batch_records_dedup_per_record(self, tmp_path):
        """Rewinding and re-draining the same records as a batch is
        absorbed record-by-record (204 per duplicate, counted)."""
        s, agg, ctx = self.make_live_agg()
        try:
            host, port = s.addresses[0]
            spool = self.seed_spool(tmp_path, "dup-node", 6)
            agent = FleetAgent(FakeMeterMonitor(),
                               endpoint=f"http://{host}:{port}",
                               node_name="dup-node", jitter_seed=0,
                               spool=spool, drain_batch_max=8)
            agent.init()
            agent._drain(None)
            assert agg._stats["reports_total"] == 6
            spool.rewind(4)
            agent._drain(None)
            assert agent.health()["queued"] == 0
            assert agg._stats["duplicates_total"] == 4
            assert agg._stats["windows_lost_total"] == 0
            agent.shutdown()
        finally:
            ctx.cancel()
            s.shutdown()
            agg.shutdown()

    def test_batch_unsupported_target_falls_back_to_single(self,
                                                           tmp_path):
        """An old replica without /v1/reports (404) downgrades this
        target to single-record sends — nothing dropped, nothing
        counted as an outage."""
        s = APIServer(listen_addresses=["127.0.0.1:0"])
        s.init()
        accepted = {"n": 0}

        def single_only(request):
            accepted["n"] += 1
            return 204, {}, b""

        # only the single endpoint exists (no /v1/reports registration);
        # the server's 404 for the batch path is the real signal
        s.register("/v1/report", "old", "single-record ingest",
                   single_only, max_body=64 << 20)
        ctx = CancelContext()
        threading.Thread(target=s.run, args=(ctx,), daemon=True).start()
        time.sleep(0.05)
        try:
            host, port = s.addresses[0]
            agent = FleetAgent(FakeMeterMonitor(),
                               endpoint=f"http://{host}:{port}",
                               node_name="old-node", jitter_seed=0,
                               spool=self.seed_spool(tmp_path, "old-node",
                                                     5),
                               drain_batch_max=8)
            agent.init()
            agent._drain(None)
            h = agent.health()
            assert h["queued"] == 0
            assert accepted["n"] == 5  # delivered singly
            assert h["drain_batches"] == 0
            assert h["send_failures"] == 0
            assert h["breaker"] == BREAKER_CLOSED
            agent.shutdown()
        finally:
            ctx.cancel()
            s.shutdown()

    def test_hostile_batch_response_concludes_nothing(self, tmp_path):
        """Garbled/malicious per-record statuses must not ack records:
        non-JSON bodies, non-list results, bool statuses, and empty
        lists each count as a FAILED attempt (backoff path) that
        concludes nothing — never a silent ack, never a spin."""
        s = APIServer(listen_addresses=["127.0.0.1:0"])
        s.init()
        hostile = [b"not json", b'{"results": "yes"}',
                   b'{"results": [{"status": true}]}',
                   b'{"results": []}']
        calls = {"n": 0}

        def batch_handler(request):
            calls["n"] += 1
            if calls["n"] <= len(hostile):
                body = hostile[calls["n"] - 1]
            else:  # recovered: conclude all four records
                body = json.dumps(
                    {"results": [{"status": 204}] * 4}).encode()
            return 200, {"Content-Type": "application/json"}, body

        s.register("/v1/reports", "evil", "hostile batch", batch_handler,
                   max_body=64 << 20)
        ctx = CancelContext()
        threading.Thread(target=s.run, args=(ctx,), daemon=True).start()
        time.sleep(0.05)
        try:
            host, port = s.addresses[0]
            agent = FleetAgent(FakeMeterMonitor(),
                               endpoint=f"http://{host}:{port}",
                               node_name="hx-node", jitter_seed=0,
                               backoff_initial=0.001, backoff_max=0.002,
                               breaker_threshold=100,
                               spool=self.seed_spool(tmp_path, "hx-node",
                                                     4),
                               drain_batch_max=4)
            agent.init()
            for _ in range(len(hostile)):  # one failed attempt each
                agent._drain(None)
            assert agent._spool.stats()["acked_total"] == 0
            assert agent.backlog() == 4  # nothing concluded, nothing lost
            assert agent.health()["send_failures"] == len(hostile)
            agent._drain(None)  # server recovered → all four conclude
            assert agent.health()["queued"] == 0
            assert agent._spool.stats()["acked_total"] == 4
            agent.shutdown()
        finally:
            ctx.cancel()
            s.shutdown()

    def test_batch_byte_budget_splits_large_backlogs(self, tmp_path,
                                                     monkeypatch):
        """A backlog of fat records never builds a request body the
        server would 413 forever: batches truncate at MAX_BATCH_BYTES
        and everything still drains."""
        from kepler_tpu.fleet import agent as agent_mod

        s, agg, ctx = self.make_live_agg()
        try:
            host, port = s.addresses[0]
            spool = Spool(str(tmp_path / "fat-node"))
            blobs = [encode_report(
                make_report("fat-node", meta_pad="x" * 4096),
                ["package", "dram"], seq=i, run="rf")
                for i in range(1, 11)]
            for b in blobs:
                spool.append(b)
            # budget ≈ 2 records per batch (payload lengths differ by a
            # byte across seq widths — size off the largest, plus slack)
            monkeypatch.setattr(agent_mod, "MAX_BATCH_BYTES",
                                2 * (max(len(b) for b in blobs) + 256)
                                + 16)
            agent = FleetAgent(FakeMeterMonitor(),
                               endpoint=f"http://{host}:{port}",
                               node_name="fat-node", jitter_seed=0,
                               spool=spool, drain_batch_max=8)
            agent.init()
            agent._drain(None)
            h = agent.health()
            assert h["queued"] == 0
            assert h["drain_batches"] == 5  # 10 records / 2 per batch
            assert agg._stats["reports_total"] == 10
            assert agg._stats["windows_lost_total"] == 0
            agent.shutdown()
        finally:
            ctx.cancel()
            s.shutdown()
            agg.shutdown()

    def test_413_downgrades_to_single_sends(self, tmp_path):
        """A target whose body cap is smaller than ours answers 413 for
        the batch: fall back to singles instead of wedging on the same
        over-cap batch forever."""
        s = APIServer(listen_addresses=["127.0.0.1:0"])
        s.init()
        accepted = {"n": 0}

        def single_ok(request):
            accepted["n"] += 1
            return 204, {}, b""

        # tiny batch-body cap: every batch POST gets the server's 413;
        # the single endpoint accepts normally
        s.register("/v1/reports", "tiny", "cap-limited batch ingest",
                   lambda r: (200, {}, b"{}"), max_body=64)
        s.register("/v1/report", "ok", "single", single_ok,
                   max_body=64 << 20)
        ctx = CancelContext()
        threading.Thread(target=s.run, args=(ctx,), daemon=True).start()
        time.sleep(0.05)
        try:
            host, port = s.addresses[0]
            agent = FleetAgent(FakeMeterMonitor(),
                               endpoint=f"http://{host}:{port}",
                               node_name="cap-node", jitter_seed=0,
                               spool=self.seed_spool(tmp_path,
                                                     "cap-node", 4),
                               drain_batch_max=4)
            agent.init()
            agent._drain(None)
            h = agent.health()
            assert h["queued"] == 0
            assert accepted["n"] == 4  # delivered singly after the 413
            assert h["drain_batches"] == 0
            agent.shutdown()
        finally:
            ctx.cancel()
            s.shutdown()

    def test_replay_pacing_caps_rate(self, tmp_path):
        """With drain_replay_rps set, a deep backlog drains at the
        bucket's pace instead of as fast as the socket allows."""
        s, agg, ctx = self.make_live_agg()
        try:
            host, port = s.addresses[0]
            agent = FleetAgent(FakeMeterMonitor(),
                               endpoint=f"http://{host}:{port}",
                               node_name="pace2-node", jitter_seed=0,
                               spool=self.seed_spool(tmp_path,
                                                     "pace2-node", 24),
                               drain_batch_max=8,
                               drain_replay_rps=100.0)
            agent.init()
            drain_ctx = CancelContext()
            t0 = time.monotonic()
            agent._drain(drain_ctx)
            elapsed = time.monotonic() - t0
            assert agent.health()["queued"] == 0
            # burst of 8 goes immediately; the remaining 16 records at
            # 100 rps cost ≥ 0.16 s of bucket waits
            assert elapsed >= 0.15
            assert agg._stats["reports_total"] == 24
            agent.shutdown()
        finally:
            ctx.cancel()
            s.shutdown()
            agg.shutdown()


class TestConnectionCap:
    def _occupy(self, addr, path="/slow"):
        conn = http.client.HTTPConnection(*addr, timeout=10)
        t = threading.Thread(
            target=lambda: (conn.request("GET", path),
                            conn.getresponse().read()),
            daemon=True)
        t.start()
        return conn, t

    def test_overflow_answered_503_without_thread(self):
        s = APIServer(listen_addresses=["127.0.0.1:0"],
                      max_connections=2)
        s.init()
        gate = threading.Event()
        s.register("/slow", "slow", "holds the connection",
                   lambda req: (gate.wait(5.0), (200, {}, b"ok\n"))[1])
        ctx = CancelContext()
        threading.Thread(target=s.run, args=(ctx,), daemon=True).start()
        time.sleep(0.05)
        try:
            addr = s.addresses[0]
            before = threading.active_count()
            held = [self._occupy(addr) for _ in range(2)]
            time.sleep(0.2)  # both slots occupied inside the handler
            # overflow: raw socket so the immediate 503 + close is
            # observable byte-for-byte
            raw = socket.create_connection(addr, timeout=5)
            data = raw.recv(4096)
            assert data.startswith(b"HTTP/1.1 503")
            assert b"Connection: close" in data
            assert raw.recv(4096) == b""  # server closed it
            raw.close()
            stats = s.connection_stats()
            assert stats["rejected_total"] == 1
            assert stats["active_connections"] == 2
            # no handler thread was spawned for the overflow accept:
            # the 2 held connections cost 2 client + 2 handler threads,
            # the rejected one costs zero
            assert threading.active_count() <= before + 4
            gate.set()
            for conn, t in held:
                t.join(timeout=5)
                conn.close()
        finally:
            gate.set()
            ctx.cancel()
            s.shutdown()

    def test_cap_holds_under_connection_storm(self):
        s = APIServer(listen_addresses=["127.0.0.1:0"],
                      max_connections=4)
        s.init()
        gate = threading.Event()
        s.register("/slow", "slow", "holds the connection",
                   lambda req: (gate.wait(5.0), (200, {}, b"ok\n"))[1])
        ctx = CancelContext()
        threading.Thread(target=s.run, args=(ctx,), daemon=True).start()
        time.sleep(0.05)
        try:
            addr = s.addresses[0]
            held = [self._occupy(addr) for _ in range(4)]
            time.sleep(0.3)
            rejected = 0
            for _ in range(20):  # the storm
                raw = socket.create_connection(addr, timeout=5)
                data = raw.recv(4096)
                if data.startswith(b"HTTP/1.1 503"):
                    rejected += 1
                raw.close()
            assert rejected == 20
            stats = s.connection_stats()
            assert stats["rejected_total"] == 20
            assert stats["active_connections"] <= 4
            gate.set()
            for conn, t in held:
                t.join(timeout=5)
                conn.close()
        finally:
            gate.set()
            ctx.cancel()
            s.shutdown()

    def test_shutdown_drain_still_works_at_the_cap(self):
        """PR 11's drain semantics hold with every slot occupied: a
        keep-alive connection's next request gets 503 + close."""
        s = APIServer(listen_addresses=["127.0.0.1:0"],
                      max_connections=2)
        s.init()
        s.register("/ping", "ping", "fast", lambda r: (200, {}, b"pong\n"))
        ctx = CancelContext()
        threading.Thread(target=s.run, args=(ctx,), daemon=True).start()
        time.sleep(0.05)
        addr = s.addresses[0]
        conns = []
        for _ in range(2):  # fill the cap with idle keep-alive conns
            conn = http.client.HTTPConnection(*addr, timeout=5)
            conn.request("GET", "/ping")
            assert conn.getresponse().read() == b"pong\n"
            conns.append(conn)
        ctx.cancel()
        s.shutdown()  # returns: the cap never wedges the drain
        for conn in conns:
            conn.request("GET", "/ping")
            resp = conn.getresponse()
            assert resp.status == 503  # draining, severed
            resp.read()
            conn.close()


@pytest.mark.chaos
class TestHerdChaos:
    """The headline scenario: kill 1 of 3 replicas mid-soak with
    admission on → the displaced herd is shed-and-re-paced (shed
    counter fires), windows_lost stays 0 (shed records stay spooled
    and deliver after recovery), batched drain carries the replay, and
    the fleet fully drains within a bounded number of intervals."""

    ADMISSION = dict(
        admission_enabled=True,
        admission_max_inflight=32,
        admission_latency_budget=0.05,
        admission_retry_after=0.02,
        admission_retry_after_max=0.1,
        admission_jitter_seed=0,
    )

    def test_kill_one_of_three_with_admission_on(self, tmp_path):
        servers, aggs, peers, ctxs = make_tier(
            3, stale_after=1e9, degraded_ttl=0.4, **self.ADMISSION)
        victim = 1
        agents = []
        try:
            ring = aggs[0]._ring
            owned = names_owned_by(ring, peers, per_peer=2)
            displaced = list(owned[peers[victim]])
            agents = [
                FleetAgent(FakeMeterMonitor(),
                           endpoint=f"http://{peers[0]}",
                           node_name=name,
                           peers=[f"http://{p}" for p in peers],
                           spool=Spool(str(tmp_path / name)),
                           backoff_initial=0.001, backoff_max=0.002,
                           jitter_seed=0, timeout_s=5.0,
                           drain_batch_max=8,
                           drain_retry_after_max=0.2)
                for name in sum(owned.values(), [])]
            for a in agents:
                a.init()
            live = [0, 1, 2]

            # pre-kill soak: healthy tier, nothing shed
            ts = 100.0
            for _ in range(4):
                ts += 5.0
                drive_interval(agents, aggs, live, ts)
            assert all(sum(aggs[i]._admission.shed_by_reason().values())
                       == 0 for i in live)

            # kill one replica; survivors adopt epoch 2 AND get slow
            # (the herd lands on a tier that cannot absorb it at full
            # speed — exactly the scenario admission control exists for)
            kill_replica(servers, aggs, ctxs, victim)
            live = [0, 2]
            for i in live:
                aggs[i].apply_membership([peers[0], peers[2]], 2)
            with fault.installed(FaultPlan([
                    FaultSpec("aggregator.ingest_slow", count=4,
                              arg=0.3)])):
                for _ in range(2):
                    ts += 5.0
                    drive_interval(agents, aggs, live, ts)
            shed_total = sum(
                sum(aggs[i]._admission.shed_by_reason().values())
                for i in live)
            assert shed_total > 0, "the herd was never shed"
            # shedding is visible on /healthz while it is happening or
            # just happened (degradedTtl window)
            assert any(not aggs[i]._admission.health()["ok"]
                       for i in live)

            # recovery: the fault is exhausted and the EWMA decays —
            # every shed record drains from the spool within 3 intervals
            drained_at = None
            for k in range(3):
                time.sleep(0.8)  # EWMA decay + Retry-After expiry
                ts += 5.0
                drive_interval(agents, aggs, live, ts)
                if all(a.backlog() == 0 for a in agents):
                    drained_at = k
                    break
            assert drained_at is not None, [a.backlog() for a in agents]

            # ZERO loss: every shed/displaced window was replay, never
            # a seq gap
            for i in live:
                assert aggs[i]._stats["windows_lost_total"] == 0, \
                    aggs[i]._lost_by_node
            # a 429 never opened a breaker or rotated a peer spuriously
            for a in agents:
                h = a.health()
                assert h["breaker"] == BREAKER_CLOSED
                assert h["queued"] == 0
            # the displaced herd's replay ran BATCHED
            assert any(a.health()["drain_batches"] >= 1
                       for a in agents
                       if a._node_name in displaced)
            # survivor ingest stayed within budget once shedding kicked
            # in: the EWMA the controller steers by is back under it
            for i in live:
                assert (aggs[i]._admission.latency_ewma()
                        < self.ADMISSION["admission_latency_budget"])
            # every displaced node is healthy on its new owner
            new_ring = aggs[0]._ring
            for name in displaced:
                agg = aggs[peers.index(new_ring.owner(name))]
                snap = agg._scoreboard.snapshot(agg._clock(), 15.0)
                assert name in snap["nodes"]
                assert snap["nodes"][name]["state"] == "healthy"
            # and the ingest probes recover on their own
            time.sleep(0.6)
            for i in live:
                assert aggs[i]._admission.health()["ok"]
        finally:
            for a in agents:
                a.shutdown()
            shutdown_tier(servers, aggs, ctxs, dead=(victim,))
