"""Fleet flight recorder (ISSUE 8): device-plane cost introspection,
shard-skew metrics, rung timeline, and the per-node fleet scoreboard.

Contracts:

* `/debug/window` and `/debug/fleet` serve schema-valid JSON on a LIVE
  aggregator (over real HTTP), and cost gauges appear after the first
  cold compile;
* stage-label cardinality is independent of mesh size (per-shard span
  names observe one shared histogram stage);
* the rung timeline records demotions and re-promotions, bounded;
* the scoreboard state machine walks healthy → stale / lossy /
  anomalous / quarantined and back, LRU-capped;
* telemetry + fleet families render byte-identically on both
  exposition fast paths under the ShardedWindowEngine, and a Chrome
  trace from a sharded pipelined run still validates.
"""

from __future__ import annotations

import json
import threading
import urllib.request

import numpy as np
import pytest

from kepler_tpu import telemetry
from kepler_tpu.fleet.aggregator import (RUNG_NUMPY, RUNG_PIPELINED,
                                         Aggregator)
from kepler_tpu.fleet.scoreboard import (STATE_ANOMALOUS, STATE_HEALTHY,
                                         STATE_LOSSY, STATE_NAMES,
                                         STATE_QUARANTINED, STATE_STALE,
                                         FleetScoreboard)
from kepler_tpu.fleet.window import DeviceWindowError, PackedWindowEngine
from kepler_tpu.fleet.wire import encode_report
from kepler_tpu.server.http import APIServer
from kepler_tpu.service.lifecycle import CancelContext
from tests.test_window_pipeline import (churn_schedule, make_agg,
                                        run_schedule)

WINDOW_REQUIRED = {"rung", "rung_name", "shards", "timeline",
                   "windows_at_rung", "windows_since_last_failure",
                   "demotions_by_reason", "engines", "stats"}
ENGINE_REQUIRED = {"engine", "n_shards", "window_seq", "buckets",
                   "resident", "shards", "programs", "updates",
                   "compile_count"}
FLEET_REQUIRED = {"cap", "anomaly_z", "flag_ttl_s", "stale_after_s",
                  "states", "nodes"}


class _Req:
    def __init__(self, path="/", command="GET", body=b""):
        self.path = path
        self.command = command
        self.body = body


def window_payload(agg) -> dict:
    status, headers, body = agg._handle_window_debug(_Req("/debug/window"))
    assert status == 200
    assert headers["Content-Type"] == "application/json"
    return json.loads(body)


def fleet_payload(agg) -> dict:
    status, headers, body = agg._handle_fleet_debug(_Req("/debug/fleet"))
    assert status == 200
    return json.loads(body)


def families(agg) -> dict:
    return {f.name: f for f in agg.collect()}


class TestDebugWindow:
    def test_schema_and_cost_after_cold_compile(self):
        import jax

        agg = make_agg(2)
        run_schedule(agg, churn_schedule(3))
        payload = window_payload(agg)
        assert WINDOW_REQUIRED <= set(payload)
        assert payload["rung"] == RUNG_PIPELINED
        engines = payload["engines"]
        assert "pipelined" in engines
        for engine in engines.values():
            assert ENGINE_REQUIRED <= set(engine)
        eng = engines["pipelined"]
        assert eng["n_shards"] == len(jax.devices())
        assert len(eng["shards"]) == eng["n_shards"]
        assert sum(s["rows"] for s in eng["shards"]) == \
            eng["resident"]["rows"]
        assert len(payload["stats"]["last_h2d_shards"]) == eng["n_shards"]
        # cost stats captured on the cold compile: the attribution
        # program reports non-zero FLOPs, updates report cost too
        progs = {p["key"]: p for p in eng["programs"]}
        assert progs, "no cached programs after three windows"
        costed = [p for p in progs.values() if p["cost"]]
        assert costed, "cost stats missing from every compile-cache entry"
        assert any(p["cost"].get("flops", 0) > 0 for p in costed)
        # staleness: one entry per ring slot (depth+1), current slot 0
        staleness = eng["resident"]["staleness_windows"]
        assert len(staleness) == 3  # pipeline_depth 2 → 3 ring slots
        assert min(staleness) == 0
        # json round-trips (the endpoint contract — no numpy leaks)
        json.dumps(payload)
        agg.shutdown()

    def test_endpoints_valid_before_first_window(self):
        agg = Aggregator(APIServer(), model_mode=None)
        payload = window_payload(agg)
        assert WINDOW_REQUIRED <= set(payload)
        assert payload["engines"] == {}
        fleet = fleet_payload(agg)
        assert FLEET_REQUIRED <= set(fleet)
        assert fleet["nodes"] == {}
        assert set(fleet["states"]) == set(STATE_NAMES)

    def test_collect_families_cost_skew_staleness(self):
        import jax

        n_dev = len(jax.devices())
        agg = make_agg(2)
        run_schedule(agg, churn_schedule(3))
        fams = families(agg)
        flops = fams["kepler_fleet_window_program_flops"]
        assert flops.samples, "cost gauges absent after cold compiles"
        assert all(s.value >= 0 for s in flops.samples)
        assert {s.labels["program"] for s in flops.samples} == \
            {s.labels["program"]
             for s in fams["kepler_fleet_window_program_bytes"].samples}
        skew = fams["kepler_fleet_window_shard_skew_ratio"].samples
        assert len(skew) == 1 and skew[0].value >= 1.0
        rows = fams["kepler_fleet_window_shard_rows"].samples
        # exactly 2 series per shard (ratio/model split): bounded by the
        # mesh, not the fleet
        assert len(rows) == 2 * n_dev
        h2d = fams["kepler_fleet_window_shard_h2d_rows"].samples
        assert len(h2d) == n_dev
        staleness = fams[
            "kepler_fleet_window_buffer_staleness_windows"].samples
        assert len(staleness) == 3
        assert {s.labels["slot"] for s in staleness} == {"0", "1", "2"}
        agg.shutdown()

    def test_served_over_live_http(self):
        """Acceptance pin: both endpoints schema-valid on a live
        aggregator reached over real HTTP, after real wire ingest."""
        from tests.test_fleet import make_report

        server = APIServer(listen_addresses=["127.0.0.1:0"])
        agg = Aggregator(server, model_mode="mlp", node_bucket=8,
                         workload_bucket=16, stale_after=1e9)
        agg.init()
        server.init()
        ctx = CancelContext()
        threading.Thread(target=server.run, args=(ctx,),
                         daemon=True).start()
        host, port = server.addresses[0]
        base = f"http://{host}:{port}"
        try:
            for seed, name in enumerate(("node-a", "node-b")):
                req = urllib.request.Request(
                    f"{base}/v1/report",
                    data=encode_report(make_report(name, seed=seed),
                                       ["package", "dram"], seq=1,
                                       run="r1"),
                    method="POST")
                with urllib.request.urlopen(req, timeout=5) as resp:
                    assert resp.status == 204
            assert agg.aggregate_once() is not None
            with urllib.request.urlopen(f"{base}/debug/window",
                                        timeout=5) as resp:
                window = json.loads(resp.read())
            assert WINDOW_REQUIRED <= set(window)
            assert window["engines"]
            programs = next(iter(window["engines"].values()))["programs"]
            assert any(p.get("cost") for p in programs)
            with urllib.request.urlopen(f"{base}/debug/fleet",
                                        timeout=5) as resp:
                fleet = json.loads(resp.read())
            assert FLEET_REQUIRED <= set(fleet)
            assert set(fleet["nodes"]) == {"node-a", "node-b"}
            assert all(row["state"] == "healthy"
                       for row in fleet["nodes"].values())
        finally:
            ctx.cancel()
            agg.shutdown()
            server.shutdown()

    def test_debug_index_links_introspection_surfaces(self):
        from kepler_tpu.server.debug import DebugService

        svc = DebugService(APIServer(listen_addresses=["127.0.0.1:0"]))
        status, _, body = svc._handle(_Req("/debug/pprof/"))
        assert status == 200
        for link in (b"/debug/traces", b"/debug/window", b"/debug/fleet"):
            assert link in body


class TestProgramLabels:
    def test_sharded_labels_distinct_from_serial(self):
        """After a demotion both engines hold cost stats; on a
        multi-device mesh the sharded rung-0 program and the serial
        demotion program can reach the same bucket key for different
        executables — the shard suffix keeps their labels (and so the
        cost gauges) distinct."""
        eng = PackedWindowEngine.__new__(PackedWindowEngine)
        key = (8, 256, 2, "", None)
        assert eng._program_label(key) == "prog_n8_w256_z2_ratio"
        assert eng._update_label((4, 264, 8)) == "upd_n4_x264_d8"
        eng.n_shards = 8
        assert eng._program_label(key) == "prog_n8_w256_z2_ratio_s8"
        assert eng._update_label((4, 264, 8)) == "upd_n4_x264_d8_s8"


class TestRungTimeline:
    def test_demotion_records_transition(self):
        agg = make_agg(1)
        run_schedule(agg, churn_schedule(1))
        agg._handle_device_failure(
            DeviceWindowError("dispatch_error", "injected"))
        probe = agg.window_health()
        assert probe["timeline_len"] == 1
        entry = probe["timeline"][-1]
        assert entry["rung"] == 1
        assert entry["from_rung"] == 0
        assert entry["reason"] == "dispatch_error"
        assert entry["windows_at_prev_rung"] == 1  # one published window
        assert entry["wall_time"] > 0 and entry["monotonic_s"] > 0
        payload = window_payload(agg)
        assert payload["timeline"] == probe["timeline"]
        assert payload["windows_at_rung"] == 0  # reset at the transition
        agg.shutdown()

    def test_repromotion_records_transition(self):
        agg = make_agg(1, repromote_after=2)
        schedules = churn_schedule(4)
        run_schedule(agg, schedules[:1])
        agg._handle_device_failure(
            DeviceWindowError("compile_error", "injected"))
        published = run_schedule(agg, schedules[1:])
        assert published  # demoted rung still publishes
        probe = agg.window_health()
        assert probe["rung"] == RUNG_PIPELINED  # walked back up
        reasons = [e["reason"] for e in probe["timeline"]]
        assert reasons == ["compile_error", "repromoted"]
        promo = probe["timeline"][-1]
        assert promo["rung"] == 0 and promo["from_rung"] == 1
        assert promo["windows_at_prev_rung"] >= 2
        agg.shutdown()

    def test_demoted_rung_introspection_reads_active_engine(self):
        """At a demoted rung the shard/skew/staleness families must
        read the engine actually serving windows (the serial demotion
        engine), not the reset — empty — rung-0 sharded engine: the
        flight recorder must not go blank exactly while degraded."""
        agg = make_agg(1, repromote_after=100)  # stay demoted
        schedules = churn_schedule(3)
        run_schedule(agg, schedules[:1])
        agg._handle_device_failure(
            DeviceWindowError("dispatch_error", "injected"))
        run_schedule(agg, schedules[1:])
        assert agg.window_health()["rung"] == 1  # packed serial
        fams = families(agg)
        rows = fams["kepler_fleet_window_shard_rows"].samples
        assert sum(s.value for s in rows) > 0, \
            "shard occupancy blank at the demoted rung"
        skew = fams["kepler_fleet_window_shard_skew_ratio"].samples[0]
        assert skew.value >= 1.0
        staleness = fams[
            "kepler_fleet_window_buffer_staleness_windows"].samples
        assert staleness, "buffer staleness blank at the demoted rung"
        agg.shutdown()

    def test_timeline_ring_is_bounded(self):
        agg = make_agg(1)
        for _ in range(80):
            agg._handle_device_failure(
                DeviceWindowError("stall", "injected"))
        assert agg.window_health()["timeline_len"] == 64
        assert agg._rung == RUNG_NUMPY  # pinned at the bottom rung
        agg.shutdown()


class TestStageCardinality:
    """Satellite: `window.h2d_delta.s<k>` span names must not mint one
    stage series per shard — the histogram key is the shared stage."""

    def make_recorder(self):
        from kepler_tpu.telemetry.spans import SpanRecorder

        return SpanRecorder(enabled=True)

    def test_stage_key_overrides_histogram_series(self):
        rec = self.make_recorder()
        with rec.span("aggregator.window"):
            for k in range(8):
                with rec.span(f"window.h2d_delta.s{k}",
                              stage="window.h2d_delta.shard"):
                    pass
        stages = rec.stats()["stages"]
        assert "window.h2d_delta.shard" in stages
        assert not [s for s in stages if s.startswith("window.h2d_delta.s")
                    and s != "window.h2d_delta.shard"]
        # all eight spans observed into the ONE stage histogram
        with rec._lock:
            assert rec._hist["window.h2d_delta.shard"].count == 8
        # the trace keeps the per-shard names for readability
        trace = rec.recent_traces()[-1]
        names = {e.name for e in trace.events}
        assert "window.h2d_delta.s7" in names

    def test_empty_stage_is_trace_only(self):
        rec = self.make_recorder()
        with rec.span("cycle"):
            with rec.span("noise.instance42", stage=""):
                pass
        stages = rec.stats()["stages"]
        assert "noise.instance42" not in stages
        assert "cycle" in stages
        names = {e.name for e in rec.recent_traces()[-1].events}
        assert "noise.instance42" in names

    def test_sharded_run_stage_labels_independent_of_mesh(self):
        """Pin: a pipelined run on the 8-device mesh produces NO
        per-shard stage series — the stage-label set would be identical
        on any mesh size."""
        from kepler_tpu.telemetry.spans import SpanRecorder

        rec = SpanRecorder(enabled=True)
        with telemetry.installed(rec):
            agg = make_agg(2)
            run_schedule(agg, churn_schedule(4))
            agg.shutdown()
        stages = rec.stats()["stages"]
        per_shard = [s for s in stages
                     if s.startswith("window.h2d_delta.s")
                     and s != "window.h2d_delta.shard"]
        assert per_shard == [], f"per-shard stage series minted: {per_shard}"
        # churn windows staged deltas, so the shared stage observed
        assert "window.h2d_delta.shard" in stages
        assert "window.h2d_delta" in stages  # the whole-window total


class TestShardedExposition:
    """Satellite: telemetry + fleet families under ShardedWindowEngine
    render on BOTH exposition fast paths, byte-identical to stock."""

    def run_sharded(self, rec):
        with telemetry.installed(rec):
            agg = make_agg(2)
            run_schedule(agg, churn_schedule(4))
            agg.shutdown()
        return agg

    def test_both_exposition_paths_byte_identical(self):
        from prometheus_client import CollectorRegistry
        from prometheus_client.exposition import generate_latest
        from prometheus_client.openmetrics.exposition import (
            generate_latest as om_latest,
        )

        from kepler_tpu.exporter.prometheus.fastexpo import (
            fast_generate_latest,
            fast_generate_openmetrics,
        )
        from kepler_tpu.telemetry.spans import SpanRecorder

        rec = SpanRecorder(enabled=True)
        agg = self.run_sharded(rec)
        registry = CollectorRegistry()
        registry.register(agg)
        with telemetry.installed(rec):
            registry.register(telemetry.collector())
            classic = fast_generate_latest(registry)
            assert classic == generate_latest(registry)
            assert fast_generate_openmetrics(registry) == \
                om_latest(registry)
        text = classic.decode()
        for needle in ("kepler_fleet_window_shard_skew_ratio",
                       "kepler_fleet_window_program_flops",
                       "kepler_fleet_window_shard_rows",
                       "kepler_fleet_window_buffer_staleness_windows",
                       "kepler_fleet_scoreboard_nodes",
                       'kepler_self_stage_duration_seconds_count{'
                       'stage="window.h2d_delta.shard"}'):
            assert needle in text, f"{needle} missing from exposition"

    def test_chrome_trace_from_sharded_run_validates(self):
        from kepler_tpu.telemetry.spans import SpanRecorder
        from tests.test_telemetry import TestChromeTrace

        rec = SpanRecorder(enabled=True)
        self.run_sharded(rec)
        payload = json.loads(json.dumps(rec.chrome_trace()))
        TestChromeTrace().validate_chrome_schema(payload)
        names = {e["name"] for e in payload["traceEvents"]
                 if e["ph"] == "X"}
        assert "aggregator.window" in names
        assert any(n.startswith("window.h2d_delta.s") for n in names)


class TestScoreboardUnit:
    def test_healthy_then_stale(self):
        sb = FleetScoreboard(flag_ttl=60.0)
        sb.observe_report("n1", 100.0, 50.0)
        assert sb.states(101.0, 15.0) == {"n1": STATE_HEALTHY}
        assert sb.states(200.0, 15.0) == {"n1": STATE_STALE}

    def test_quarantine_flag_decays(self):
        sb = FleetScoreboard(flag_ttl=60.0)
        sb.observe_report("n1", 100.0, 50.0)
        sb.observe_quarantine("n1", 100.0, "malformed")
        assert sb.states(110.0, 1e9) == {"n1": STATE_QUARANTINED}
        assert sb.states(200.0, 1e9) == {"n1": STATE_HEALTHY}
        row = sb.snapshot(110.0, 1e9)["nodes"]["n1"]
        assert row["quarantined"] == 1
        assert row["last_quarantine_reason"] == "malformed"

    def test_lossy_flag_decays(self):
        sb = FleetScoreboard(flag_ttl=60.0)
        sb.observe_report("n1", 100.0, 50.0, lost=3)
        assert sb.states(110.0, 1e9) == {"n1": STATE_LOSSY}
        sb.observe_report("n1", 170.0, 50.0)
        assert sb.states(170.0, 1e9) == {"n1": STATE_HEALTHY}
        assert sb.snapshot(170.0, 1e9)["nodes"]["n1"]["windows_lost"] == 3

    def test_anomaly_needs_baseline_then_flags_spike(self):
        sb = FleetScoreboard(anomaly_z=4.0, flag_ttl=60.0)
        rng = np.random.default_rng(0)
        t = 100.0
        # noisy-but-steady baseline: never flags, including the early
        # min_samples window
        for _ in range(20):
            sb.observe_report("n1", t, 100.0 + float(rng.normal(0, 2.0)))
            assert sb.states(t, 1e9)["n1"] == STATE_HEALTHY
            t += 5.0
        sb.observe_report("n1", t, 500.0)  # 5× spike
        assert sb.states(t, 1e9)["n1"] == STATE_ANOMALOUS
        row = sb.snapshot(t, 1e9)["nodes"]["n1"]
        assert row["anomalous"] and abs(row["power_z"]) > 4.0
        # the flag decays after the ttl
        assert sb.states(t + 120.0, 1e9)["n1"] == STATE_HEALTHY

    def test_flat_signal_never_flags(self):
        """Variance floor: a fake meter reporting a constant must not
        flag micro-wiggle as anomalous — the documented floor is
        max(5% of mean, 0.5 W), so a flat 10 W baseline flags only past
        a z × 0.5 W = 2 W excursion."""
        sb = FleetScoreboard(anomaly_z=4.0)
        t = 100.0
        for _ in range(30):
            sb.observe_report("n1", t, 80.0)
            t += 5.0
        sb.observe_report("n1", t, 80.4)  # 0.5% wiggle
        assert sb.states(t, 1e9)["n1"] == STATE_HEALTHY
        flat = FleetScoreboard(anomaly_z=4.0)
        t = 100.0
        for _ in range(30):
            flat.observe_report("n2", t, 10.0)
            t += 5.0
        flat.observe_report("n2", t, 11.5)  # inside the 2 W guarantee
        assert flat.states(t, 1e9)["n2"] == STATE_HEALTHY
        flat.observe_report("n2", t + 5.0, 13.0)  # 3 W: past the floor
        assert flat.states(t + 5.0, 1e9)["n2"] == STATE_ANOMALOUS

    def test_garbage_power_is_ignored(self):
        sb = FleetScoreboard()
        sb.observe_report("n1", 100.0, float("nan"))
        sb.observe_report("n1", 105.0, float("inf"))
        sb.observe_report("n1", 110.0, -5.0)
        row = sb.snapshot(110.0, 1e9)["nodes"]["n1"]
        assert row["reports"] == 3
        assert row["power_mean_w"] == 0.0  # stats never poisoned

    def test_lru_cap_evicts_longest_silent(self):
        sb = FleetScoreboard(cap=3)
        for i, t in enumerate((1.0, 2.0, 3.0)):
            sb.observe_report(f"n{i}", t, 10.0)
        sb.observe_report("n0", 4.0, 10.0)  # refresh n0
        sb.observe_report("n9", 5.0, 10.0)  # evicts n1 (oldest update)
        assert set(sb.states(5.0, 1e9)) == {"n0", "n2", "n9"}
        assert len(sb) == 3

    def test_quarantine_flood_never_evicts_real_nodes(self):
        """Quarantine names are unvalidated wire bytes: a burst of
        spoofed names must churn junk rows, not real nodes' health."""
        sb = FleetScoreboard(cap=4)
        for i in range(3):
            sb.observe_report(f"real{i}", 1.0 + i, 10.0)
        for j in range(50):  # 50 distinct junk names, cap is 4
            sb.observe_quarantine(f"junk{j}", 10.0, "decode")
        nodes = set(sb.states(10.0, 1e9))
        assert {"real0", "real1", "real2"} <= nodes
        assert len(sb) <= 4  # at most one junk row alive at a time
        # once full of accepted reporters, weak inserts are dropped
        sb.observe_report("real3", 11.0, 10.0)
        sb.observe_quarantine("junk-late", 12.0, "decode")
        assert set(sb.states(12.0, 1e9)) == {"real0", "real1",
                                             "real2", "real3"}
        # a known node's quarantine still lands
        sb.observe_quarantine("real1", 13.0, "skew")
        assert sb.states(13.0, 1e9)["real1"] == STATE_QUARANTINED

    def test_junk_rows_subcapped_and_expire(self):
        """Below the LRU cap, spoofed-name rows are bounded by the junk
        sub-cap while their quarantine flag is fresh and expire once it
        decays — never a permanent 'stale' series per junk name."""
        sb = FleetScoreboard(cap=1024, flag_ttl=60.0, junk_cap=8)
        for i in range(3):
            sb.observe_report(f"real{i}", 1.0 + i, 10.0)
        for j in range(200):
            sb.observe_quarantine(f"junk{j}", 10.0, "decode")
        snap = sb.snapshot(11.0, 1e9)
        assert snap["states"]["quarantined"] == 8  # sub-cap, not 200
        assert len(sb) == 3 + 8
        # flag decay expires the junk rows; real rows keep their LRU life
        snap = sb.snapshot(100.0, 1e9)
        assert set(snap["nodes"]) == {"real0", "real1", "real2"}
        assert len(sb) == 3
        # a junk row that starts reporting is promoted, never expired
        sb.observe_quarantine("late", 100.0, "decode")
        sb.observe_report("late", 101.0, 10.0)
        assert "late" in sb.snapshot(500.0, 1e9)["nodes"]

    def test_delivery_ewma(self):
        sb = FleetScoreboard(ewma_alpha=0.5)
        # delivery always follows an accepted report on the real ingest
        # path (a delivery-only row would read as junk and expire)
        sb.observe_report("n1", 0.0, 10.0)
        sb.observe_delivery("n1", 0.1)
        sb.observe_delivery("n1", 0.3)
        row = sb.snapshot(0.0, 0.0)["nodes"]["n1"]
        assert row["delivery_ewma_s"] == pytest.approx(0.2)


class TestScoreboardIngest:
    """The scoreboard through the aggregator's real ingest path."""

    def make(self, **kw):
        ticks = [1e9]
        kw.setdefault("stale_after", 15.0)
        kw.setdefault("degraded_ttl", 60.0)
        agg = Aggregator(APIServer(), model_mode=None,
                         clock=lambda: ticks[0], **kw)
        return agg, ticks

    def post(self, agg, report, zones=("package", "dram"), seq=1,
             run="r1", **kw):
        body = encode_report(report, list(zones), seq=seq, run=run, **kw)
        return agg._handle_report(_Req("/v1/report", "POST", body))

    def test_states_via_ingest(self):
        from tests.test_fleet import make_report

        agg, ticks = self.make()
        status, _, _ = self.post(agg, make_report("node-a"), seq=1)
        assert status == 204
        fleet = fleet_payload(agg)
        assert fleet["nodes"]["node-a"]["state"] == "healthy"
        # a seq gap marks the node lossy and counts the lost windows
        self.post(agg, make_report("node-a"), seq=10)
        fleet = fleet_payload(agg)
        assert fleet["nodes"]["node-a"]["state"] == "lossy"
        assert fleet["nodes"]["node-a"]["windows_lost"] == 8
        # a duplicate is counted but keeps liveness
        self.post(agg, make_report("node-a"), seq=10)
        assert fleet_payload(agg)["nodes"]["node-a"]["duplicates"] == 1
        # silence → stale (after the lossy flag decays)
        ticks[0] += 100.0
        assert fleet_payload(agg)["nodes"]["node-a"]["state"] == "stale"

    def test_quarantined_via_skewed_clock(self):
        from tests.test_fleet import make_report

        agg, ticks = self.make(skew_tolerance=120.0)
        status, _, _ = self.post(agg, make_report("node-b"),
                                 sent_at=ticks[0] - 1e6)
        assert status == 422
        fleet = fleet_payload(agg)
        assert fleet["nodes"]["node-b"]["state"] == "quarantined"
        assert fleet["states"]["quarantined"] == 1

    def test_node_state_gauge_and_rollup(self):
        from tests.test_fleet import make_report

        agg, ticks = self.make()
        self.post(agg, make_report("node-a"), seq=1)
        self.post(agg, make_report("node-c", seed=2), seq=1)
        fams = families(agg)
        states = fams["kepler_fleet_node_state"].samples
        assert {s.labels["node_name"]: s.value for s in states} == \
            {"node-a": 0, "node-c": 0}
        rollup = {s.labels["state"]: s.value
                  for s in fams["kepler_fleet_scoreboard_nodes"].samples}
        assert rollup == {"healthy": 2, "stale": 0, "lossy": 0,
                          "anomalous": 0, "quarantined": 0}
        assert set(rollup) == set(STATE_NAMES)
        ticks[0] += 100.0
        fams = families(agg)
        assert all(s.value == STATE_STALE
                   for s in fams["kepler_fleet_node_state"].samples)

    def test_scoreboard_cap_bounds_gauge_cardinality(self):
        from tests.test_fleet import make_report

        agg, ticks = self.make(scoreboard_cap=4)
        for i in range(10):
            self.post(agg, make_report(f"node-{i:02d}", seed=i), seq=1)
            ticks[0] += 1.0
        fams = families(agg)
        assert len(fams["kepler_fleet_node_state"].samples) == 4
        assert len(fleet_payload(agg)["nodes"]) == 4
